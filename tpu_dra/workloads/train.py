"""pjit training step for the flagship model.

The full distributed recipe: params/optimizer sharded by the rules in
parallel/mesh.py (fsdp/tp), batch sharded over (dp, fsdp), sequence over sp
(ring attention), jit with explicit in/out shardings and donated state so
XLA plans the collectives; bf16 params with fp32 AdamW moments.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding

from tpu_dra.workloads.models import build_model
from tpu_dra.workloads.parallel.context import set_global_mesh
from tpu_dra.workloads.parallel.mesh import (
    MeshConfig,
    _flatten_path,
    batch_sharding,
    build_mesh,
    param_shardings,
    param_spec,
    replicated,
)

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.adamw(
            config.learning_rate,
            b1=config.beta1,
            b2=config.beta2,
            weight_decay=config.weight_decay,
            mu_dtype=jnp.float32,
        ),
    )


def loss_fn(model, params, tokens: jnp.ndarray) -> jnp.ndarray:
    """Next-token cross entropy over [b, s] int tokens (+ MoE aux loss)."""
    aux = 0.0
    if hasattr(model, "apply_with_aux"):
        logits, aux = model.apply_with_aux(params, tokens)
    elif getattr(getattr(model, "config", None), "fused_ce", False):
        # Streamed LM-head loss: never materializes [b, s, vocab]
        # (ops/loss.py); gradients reach the head through the kernel
        # reference into the same param tree.
        from tpu_dra.workloads.ops.loss import fused_next_token_xent

        hidden = model.apply({"params": params}, tokens, return_hidden=True)
        return fused_next_token_xent(
            hidden,
            params["lm_head"]["kernel"],
            tokens,
            chunk=model.config.ce_chunk,
        )
    else:
        logits = model.apply({"params": params}, tokens)  # [b, s, v] fp32
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + aux


class Trainer:
    """Owns mesh, sharded state, and the compiled train/forward steps."""

    def __init__(
        self,
        model_config,
        mesh_config: Optional[MeshConfig] = None,
        train_config: TrainConfig = TrainConfig(),
        devices=None,
    ):
        self.model_config = model_config
        self.model = build_model(model_config)
        devices = devices if devices is not None else jax.devices()
        self.mesh_config = mesh_config or MeshConfig.for_device_count(
            len(devices)
        )
        self.mesh = build_mesh(self.mesh_config, devices)
        set_global_mesh(self.mesh)
        self.train_config = train_config
        self.optimizer = make_optimizer(train_config)

    # --- state ---

    def init_state(self, rng=None, batch: int = 1, seq: int = 8) -> Dict:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        tokens = jnp.zeros((batch, seq), dtype=jnp.int32)

        def init():
            params = self.model.init(rng, tokens)["params"]
            opt_state = self.optimizer.init(params)
            return {"params": params, "opt_state": opt_state, "step": 0}

        shapes = jax.eval_shape(init)
        shardings = self.state_shardings(shapes)
        with self.mesh:
            return jax.jit(init, out_shardings=shardings)()

    def state_shardings(self, state_shapes) -> Dict:
        p_sh = param_shardings(self.mesh, state_shapes["params"])

        def opt_sharding(path, leaf):
            # Optimizer moments mirror their parameter's sharding (the
            # param-path rules match on the path suffix); scalars (counts,
            # schedules) replicate.
            if leaf.ndim == 0:
                return replicated(self.mesh)
            return NamedSharding(
                self.mesh, param_spec(_flatten_path(path), leaf)
            )

        o_sh = jax.tree_util.tree_map_with_path(
            opt_sharding, state_shapes["opt_state"]
        )
        return {
            "params": p_sh,
            "opt_state": o_sh,
            "step": replicated(self.mesh),
        }

    # --- compiled steps ---

    def make_train_step(self) -> Callable:
        model = self.model

        def train_step(state, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(model, p, tokens)
            )(state["params"])
            updates, new_opt = self.optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            new_params = optax.apply_updates(state["params"], updates)
            return (
                {
                    "params": new_params,
                    "opt_state": new_opt,
                    "step": state["step"] + 1,
                },
                loss,
            )

        data_sh = batch_sharding(self.mesh)
        return jax.jit(
            train_step,
            in_shardings=(None, data_sh),
            donate_argnums=(0,),
        )

    def make_forward(self) -> Callable:
        model = self.model

        def forward(params, tokens):
            return model.apply({"params": params}, tokens)

        return jax.jit(forward)


# --- CLI (demo/e2e entrypoint: one worker per host of a DRA-allocated
# slice; the driver-injected env drives jax.distributed bootstrap) ---

MODEL_PRESETS = {
    "llama3-8b": "LLAMA3_8B",
    "tiny": "TINY_LLAMA",
    "mixtral-8x7b": "MIXTRAL_8X7B",
    "tiny-moe": "TINY_MIXTRAL",
}


def main(argv=None) -> int:
    from tpu_dra.workloads import apply_forced_platform

    apply_forced_platform()

    import argparse
    import time

    from tpu_dra.workloads import models as models_mod

    p = argparse.ArgumentParser("tpu-dra-train")
    p.add_argument("--model", choices=sorted(MODEL_PRESETS), default="tiny")
    def positive_int(v):
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError("must be >= 1")
        return n
    def nonnegative_int(v):
        n = int(v)
        if n < 0:
            raise argparse.ArgumentTypeError("must be >= 0")
        return n
    p.add_argument("--steps", type=positive_int, default=10)
    p.add_argument(
        "--batch", type=nonnegative_int, default=0,
        help="global batch (0: one per data shard)",
    )
    p.add_argument("--seq", type=positive_int, default=512)
    p.add_argument(
        "--distributed",
        action="store_true",
        help="initialize jax.distributed from the driver-injected slice env",
    )
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if args.distributed:
        from tpu_dra.workloads.bootstrap import initialize_from_env

        slice_env = initialize_from_env()
        log.info("slice bootstrap: %s", slice_env)

    model_config = getattr(models_mod, MODEL_PRESETS[args.model])
    trainer = Trainer(model_config)
    dp_shards = (
        trainer.mesh.shape.get("dp", 1) * trainer.mesh.shape.get("fsdp", 1)
    )
    batch = args.batch or dp_shards
    state = trainer.init_state(batch=batch, seq=args.seq)
    step = trainer.make_train_step()

    tokens = jax.random.randint(
        jax.random.PRNGKey(1),
        (batch, args.seq),
        0,
        model_config.vocab_size,
        dtype=jnp.int32,
    )
    loss = None
    t0 = time.monotonic()
    for i in range(args.steps):
        state, loss = step(state, tokens)
    loss = float(loss)
    dt = time.monotonic() - t0
    tok_per_s = args.steps * batch * args.seq / dt if dt > 0 else 0.0
    log.info(
        "trained %d steps (%s, batch=%d seq=%d): loss=%.4f, %.0f tok/s",
        args.steps, args.model, batch, args.seq, loss, tok_per_s,
    )
    print({"ok": loss == loss, "steps": args.steps, "loss": loss, "tok_per_s": tok_per_s})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
