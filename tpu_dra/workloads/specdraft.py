"""Draft-token proposers for speculative decoding (ISSUE 15).

The serving engine's speculative path (workloads/engine.py,
``EngineConfig.spec_k``) asks a :class:`DraftSource` for up to K cheap
guesses of the next tokens, writes their K/V into the sequence's pages,
and verifies all K+1 positions in ONE jitted pass against the paged
cache — accepted guesses cost one model pass for many tokens, rejected
ones are rewound host-side. The proposer is a PROTOCOL, not a model:
the built-in :class:`NgramDraft` is the prompt-lookup scheme (find the
most recent prior occurrence of the trailing n-gram in the sequence's
own history and propose what followed it — free, surprisingly strong on
templated/extractive traffic and on the cycles small models fall into),
and a draft-model proposer can slot in behind the same two-method
surface without touching the engine.

Exactness contract: a proposer can only affect SPEED, never tokens.
The engine's acceptance rule replays the exact (seed, serial, position)
pick schedule the per-token path uses, so a wrong draft is rejected and
corrected in the same step — the unfused per-token oracle token-matches
regardless of what the proposer emits (tests/test_engine.py pins it
with an adversarial proposer).
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

import numpy as np


@runtime_checkable
class DraftSource(Protocol):
    """Anything that can guess a sequence's next tokens.

    ``propose(history, k)`` receives the sequence's FULL token history
    (prompt + every emitted token, host-side int32) and returns up to
    ``k`` draft tokens (possibly zero — an empty array means "no guess
    this step", which costs nothing: the verify pass still emits one
    real token). Called on the engine's host thread between chunks; it
    must not touch the device.
    """

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        ...


class NgramDraft:
    """Prompt-lookup proposer: the most recent earlier occurrence of
    the trailing ``order``-gram predicts what comes next.

    Falls back through shorter orders (order, order-1, ..., 1) until a
    match exists; proposes the k tokens that followed the match (capped
    by what the history holds). O(len(history) * order) vectorized
    numpy per call — host-side noise next to a model pass.
    """

    def __init__(self, order: int = 3):
        if order < 1:
            raise ValueError(f"ngram order must be >= 1, got {order}")
        self.order = order

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        history = np.asarray(history, np.int32)
        L = len(history)
        empty = np.zeros(0, np.int32)
        if k < 1 or L < 2:
            return empty
        for n in range(min(self.order, L - 1), 0, -1):
            needle = history[L - n:]
            # Candidate starts i with i + n < L: the trailing needle
            # itself (i == L - n) is excluded — matching it would
            # propose nothing new.
            windows = np.lib.stride_tricks.sliding_window_view(
                history[: L - 1], n
            )  # starts 0 .. L-1-n
            hits = np.flatnonzero(np.all(windows == needle, axis=1))
            if hits.size == 0:
                continue
            start = int(hits[-1]) + n  # most recent occurrence
            out = history[start: start + k]
            if out.size:
                return out.astype(np.int32)
        return empty


class StaticDraft:
    """Test/drill proposer: replays a fixed token sequence (or nothing)
    regardless of history — the adversarial 'always wrong' and 'always
    right' corners of the acceptance sampler are pinned with it."""

    def __init__(self, tokens):
        self.tokens = np.asarray(tokens, np.int32)

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        return self.tokens[:k]
