"""Consume the driver-injected bootstrap environment.

The CD kubelet plugin injects (via CDI) the env the slice daemon rendered
(tpu_dra/computedomain/daemon/bootstrap.py): TPU_WORKER_ID,
TPU_WORKER_HOSTNAMES, JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES,
MEGASCALE_*. A workload calls :func:`initialize_from_env` first thing; on a
single-process allocation it is a no-op.
"""

from __future__ import annotations

import json
import logging
import os
import socket
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)

#: Where the CD kubelet plugin mounts the per-domain config dir into
#: workload containers (cdplugin/device_state.py; the /imexd analog).
DEFAULT_CONFIG_DIR = "/tpu-cd"


def resolve_coordinator(address: str, config_dir: Optional[str] = None) -> str:
    """Resolve the rendered coordinator address to something dialable.

    The daemon renders ``JAX_COORDINATOR_ADDRESS`` with daemon-0's stable
    DNS name (daemon/bootstrap.py), normally resolvable via the
    /etc/hosts block the daemon maintains (dnsnames.go:145-190 analog).
    A workload pod that does not share that hosts file (hostNetwork
    without the mount, or a test process) can still rendezvous: the same
    config dir carries ``peers.json`` mapping every peer's DNS name to
    its registered IP, so fall back to that. The static-DNS-names +
    dynamic-IP-mapping split is exactly the reference's nodes.cfg design
    (dnsnames.go:191-216) — this just reads the mapping consumer-side.
    """
    host, _, port = address.rpartition(":")
    if not host:
        return address
    try:
        socket.getaddrinfo(host, None)
        return address
    except socket.gaierror:
        pass
    cfg = config_dir or os.environ.get("CD_CONFIG_DIR", DEFAULT_CONFIG_DIR)
    peers_path = os.path.join(cfg, "peers.json")
    try:
        with open(peers_path) as f:
            peers = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return address
    for p in peers:
        if p.get("dnsName") == host and p.get("ipAddress"):
            resolved = f"{p['ipAddress']}:{port}"
            log.info("resolved coordinator %s -> %s via %s",
                     address, resolved, peers_path)
            return resolved
    return address


@dataclass
class SliceEnv:
    worker_id: int = 0
    num_processes: int = 1
    coordinator_address: str = ""
    accelerator_type: str = ""
    topology: str = ""
    num_slices: int = 1
    slice_id: int = 0

    @property
    def multi_host(self) -> bool:
        return self.num_processes > 1


def read_slice_env(env: Optional[dict] = None) -> SliceEnv:
    e = env if env is not None else os.environ
    return SliceEnv(
        worker_id=int(e.get("TPU_WORKER_ID", e.get("JAX_PROCESS_ID", "0")) or 0),
        num_processes=int(e.get("JAX_NUM_PROCESSES", "1") or 1),
        coordinator_address=e.get("JAX_COORDINATOR_ADDRESS", ""),
        accelerator_type=e.get("TPU_ACCELERATOR_TYPE", ""),
        topology=e.get("TPU_TOPOLOGY", ""),
        num_slices=int(e.get("MEGASCALE_NUM_SLICES", "1") or 1),
        slice_id=int(e.get("MEGASCALE_SLICE_ID", "0") or 0),
    )


def mesh_config_from_slice_env(
    se: SliceEnv, chips_per_host: int, tp: int = 1, sp: int = 1
):
    """Mesh factorization a CD-bootstrapped trainer should use: the slice
    axis (DCN, gradient-only traffic) maps to ``dp`` — outermost in
    mesh.AXES so cross-slice collectives never interleave with ICI ones —
    and hosts x chips within a slice fill ``fsdp`` (minus any tp/sp the
    caller claims). Mirrors the scaling-book recipe encoded in
    parallel/mesh.py."""
    from tpu_dra.workloads.parallel.mesh import MeshConfig

    total = se.num_processes * chips_per_host * se.num_slices
    inner, rem = divmod(total, se.num_slices * tp * sp)
    if rem:
        raise ValueError(
            f"cannot factor {total} devices into slices={se.num_slices} "
            f"tp={tp} sp={sp}"
        )
    return MeshConfig(dp=se.num_slices, fsdp=inner, sp=sp, tp=tp)


def initialize_from_env(
    env: Optional[dict] = None, config_dir: Optional[str] = None
) -> SliceEnv:
    """jax.distributed.initialize from the injected bootstrap env (no-op on
    single-host allocations). ``config_dir`` points at the mounted per-CD
    config dir for peers.json coordinator resolution (defaults to
    ``$CD_CONFIG_DIR`` or /tpu-cd)."""
    se = read_slice_env(env)
    if se.multi_host and se.coordinator_address:
        import jax

        coordinator = resolve_coordinator(se.coordinator_address, config_dir)
        log.info(
            "initializing jax.distributed: process %d/%d via %s",
            se.worker_id,
            se.num_processes,
            coordinator,
        )
        # Fail FAST when a peer dies mid-rendezvous: jax's default
        # initialization window lets a severed worker sit blocked for
        # many minutes before erroring, so the Job-level restart (the
        # failover path the cd_failover suite kills workers to test)
        # converges a whole rendezvous-timeout later than it needs to.
        # A dead-peer exit within ~2 min turns worker loss into a quick
        # restart instead of a silent stall. Overridable for genuinely
        # slow fleets via TPU_DRA_INIT_TIMEOUT_SECONDS.
        timeout_s = int(
            (env or os.environ).get("TPU_DRA_INIT_TIMEOUT_SECONDS", "120")
        )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=se.num_processes,
            process_id=se.worker_id,
            initialization_timeout=timeout_s,
        )
    return se
