"""Consume the driver-injected bootstrap environment.

The CD kubelet plugin injects (via CDI) the env the slice daemon rendered
(tpu_dra/computedomain/daemon/bootstrap.py): TPU_WORKER_ID,
TPU_WORKER_HOSTNAMES, JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES,
MEGASCALE_*. A workload calls :func:`initialize_from_env` first thing; on a
single-process allocation it is a no-op.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)


@dataclass
class SliceEnv:
    worker_id: int = 0
    num_processes: int = 1
    coordinator_address: str = ""
    accelerator_type: str = ""
    topology: str = ""
    num_slices: int = 1
    slice_id: int = 0

    @property
    def multi_host(self) -> bool:
        return self.num_processes > 1


def read_slice_env(env: Optional[dict] = None) -> SliceEnv:
    e = env if env is not None else os.environ
    return SliceEnv(
        worker_id=int(e.get("TPU_WORKER_ID", e.get("JAX_PROCESS_ID", "0")) or 0),
        num_processes=int(e.get("JAX_NUM_PROCESSES", "1") or 1),
        coordinator_address=e.get("JAX_COORDINATOR_ADDRESS", ""),
        accelerator_type=e.get("TPU_ACCELERATOR_TYPE", ""),
        topology=e.get("TPU_TOPOLOGY", ""),
        num_slices=int(e.get("MEGASCALE_NUM_SLICES", "1") or 1),
        slice_id=int(e.get("MEGASCALE_SLICE_ID", "0") or 0),
    )


def mesh_config_from_slice_env(
    se: SliceEnv, chips_per_host: int, tp: int = 1, sp: int = 1
):
    """Mesh factorization a CD-bootstrapped trainer should use: the slice
    axis (DCN, gradient-only traffic) maps to ``dp`` — outermost in
    mesh.AXES so cross-slice collectives never interleave with ICI ones —
    and hosts x chips within a slice fill ``fsdp`` (minus any tp/sp the
    caller claims). Mirrors the scaling-book recipe encoded in
    parallel/mesh.py."""
    from tpu_dra.workloads.parallel.mesh import MeshConfig

    total = se.num_processes * chips_per_host * se.num_slices
    inner, rem = divmod(total, se.num_slices * tp * sp)
    if rem:
        raise ValueError(
            f"cannot factor {total} devices into slices={se.num_slices} "
            f"tp={tp} sp={sp}"
        )
    return MeshConfig(dp=se.num_slices, fsdp=inner, sp=sp, tp=tp)


def initialize_from_env(env: Optional[dict] = None) -> SliceEnv:
    """jax.distributed.initialize from the injected bootstrap env (no-op on
    single-host allocations)."""
    se = read_slice_env(env)
    if se.multi_host and se.coordinator_address:
        import jax

        log.info(
            "initializing jax.distributed: process %d/%d via %s",
            se.worker_id,
            se.num_processes,
            se.coordinator_address,
        )
        jax.distributed.initialize(
            coordinator_address=se.coordinator_address,
            num_processes=se.num_processes,
            process_id=se.worker_id,
        )
    return se
