"""Cross-process rendezvous smoke: prove a driver-rendered bootstrap env.

The one function that makes a ComputeDomain real for a workload is
``jax.distributed.initialize`` from the env the slice daemon rendered
(daemon/bootstrap.py) and the CD kubelet plugin injected (CDI env +
/tpu-cd mount). This CLI is that workload, reduced to its essence: load
the bootstrap env, rendezvous, assemble the global device view, run one
collective and one data-parallel train step, and print one JSON line.

Reference analog: tests/bats/test_cd_mnnvl_workload.bats:1-60 runs
nvbandwidth across nodes to prove the IMEX domain moves bytes; this
proves the TPU domain rendezvouses and reduces. Run it as the workload
container's command (args default to the injected env), or point
``--config-dir`` at a daemon-rendered dir to source bootstrap.env
explicitly (what the e2e harness and dryrun do).

Exit 0 iff: coordinator bind + all-worker connect succeeded,
``jax.device_count()`` equals processes x local devices, the global psum
saw every process's contribution, and the train-step loss is finite and
bit-identical on every process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpu_dra.computedomain.daemon.bootstrap import read_bootstrap_env
from tpu_dra.workloads.bootstrap import initialize_from_env

FEATURE_DIM = 8


def main(argv=None) -> int:
    from tpu_dra.workloads import apply_forced_platform

    apply_forced_platform()

    p = argparse.ArgumentParser("tpu-dra-rendezvous-smoke")
    p.add_argument(
        "--config-dir",
        default=os.environ.get("CD_CONFIG_DIR", ""),
        help="Per-CD config dir; when set, bootstrap.env from it is "
        "loaded into the process env first (the CDI-injection analog)",
    )
    p.add_argument(
        "--cpu-devices",
        type=int,
        default=0,
        help="Force the CPU platform with N local devices (hardware-free "
        "harnesses; 0 = leave the platform alone)",
    )
    p.add_argument(
        "--rows-per-device",
        type=int,
        default=4,
        help="Local batch rows per addressable device for the train step",
    )
    args = p.parse_args(argv)

    if args.config_dir:
        env = read_bootstrap_env(args.config_dir)
        if env is None:
            print(f"no bootstrap.env under {args.config_dir}", file=sys.stderr)
            return 2
        os.environ.update(env)
        os.environ["CD_CONFIG_DIR"] = args.config_dir

    import jax

    if args.cpu_devices:
        from tpu_dra.workloads import force_cpu_devices

        force_cpu_devices(args.cpu_devices)

    se = initialize_from_env()

    import numpy as np
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == se.num_processes, (
        f"process_count {jax.process_count()} != rendered "
        f"JAX_NUM_PROCESSES {se.num_processes}"
    )
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    assert n_global == se.num_processes * n_local, (
        f"global {n_global} != {se.num_processes} x {n_local}"
    )

    # 1. Global collective: every process contributes 2**worker_id; the
    #    allgathered sum proves each worker's bytes crossed the fabric.
    contrib = multihost_utils.process_allgather(
        np.array([2.0**se.worker_id], np.float32)
    )
    psum = float(contrib.sum())
    expected = float(2.0**se.num_processes - 1)
    assert psum == expected, f"psum {psum} != {expected}"

    # 2. One data-parallel train step over the global mesh: inputs sharded
    #    across all devices (mean reduction = cross-process psum under the
    #    hood), parameters replicated; the updated loss must be finite and
    #    identical everywhere.
    mesh = Mesh(np.array(jax.devices()), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    local = np.stack(
        [
            np.full((FEATURE_DIM,), 1.0 + se.worker_id * 0.5 + i * 0.01,
                    np.float32)
            for i in range(args.rows_per_device * n_local)
        ]
    )
    x = jax.make_array_from_process_local_data(sharding, local)
    w = jnp.ones((FEATURE_DIM,), jnp.float32)

    @jax.jit
    def step(w, x):
        def loss_fn(w):
            return jnp.mean((x @ w) ** 2)

        loss, grad = jax.value_and_grad(loss_fn)(w)
        return loss, w - 0.01 * grad

    loss, w = step(w, x)
    loss2, _ = step(w, x)
    l1, l2 = float(loss), float(loss2)
    assert np.isfinite(l1) and np.isfinite(l2), f"loss not finite: {l1} {l2}"
    assert l2 < l1, f"train step did not descend: {l1} -> {l2}"
    losses = multihost_utils.process_allgather(np.array([l1], np.float32))
    assert np.all(losses == losses[0]), f"loss disagreement: {losses}"

    print(
        json.dumps(
            {
                "worker": se.worker_id,
                "processes": se.num_processes,
                "local_devices": n_local,
                "global_devices": n_global,
                "psum": psum,
                "loss": l1,
                "loss_after_step": l2,
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
