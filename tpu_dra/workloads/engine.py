"""Serving engine: continuous batching + paged KV over DRA leases.

The fixed-batch decode path (workloads/generate.py) runs one static
batch through a scan: every request pads to the longest sequence and
nothing joins or leaves mid-flight. This module is the request-level
layer on top of the same forward math — the refactor ROADMAP item 3
calls for:

- **sequence-state store**: every request is an explicit
  :class:`_Sequence` (context, emitted tokens, block table, timestamps,
  reservation) owned by the engine, not a row of an opaque batch;
- **paged KV** (workloads/paged_kv.py): per-sequence block tables over
  shared page pools, attention through the block-table ops
  (ops/attention.py ``paged_decode_attention`` /
  ``paged_prefill_attention``) — a batch of wildly different lengths
  pays compute and HBM for its LIVE context only;
- **continuous batching**: sequences are admitted and evicted BETWEEN
  scan chunks (``scan_chunk`` decode steps per jitted call), with
  chunked prefill interleaved with decode — the Sarathi-style chunk
  budget: a long prompt never stalls in-flight decodes for more than
  one chunk. Since ISSUE 15 prefill is BATCHED: chunks from every
  currently-prefilling sequence pack into one padded bucket per
  iteration (``prefill_batch``), so TTFT under admission bursts is no
  longer serialized;
- **speculative decoding** (ISSUE 15, ``spec_k``): a pluggable
  :class:`~tpu_dra.workloads.specdraft.DraftSource` (default n-gram
  prompt lookup) proposes up to K tokens; ONE jitted verify pass
  evaluates all K+1 positions against the paged cache, each position's
  pick replaying the exact (seed, serial, position) schedule — so
  acceptance is exact-parity (the per-token oracle token-matches,
  greedy AND sampled) and rejected positions rewind host-side (pages
  freed past the accepted length, boundary tail re-zeroed);
- **copy-on-write prefix sharing** (ISSUE 15, ``Request.prefix_id``):
  sequences sharing a verified prompt prefix map its pages once via
  ``PageAllocator.incref`` and fork on the first divergent write —
  one system prompt costs one page set; the registry is an LRU that
  sheds under page pressure and flushes on drain (resume re-attaches);
- **multiplexd-aware backpressure**: the engine runs behind a
  :class:`LeaseGate`. When the gate closes (a co-tenant holds the chip
  lease, or the daemon revoked ours — workloads/multiplex_client.py),
  the engine DRAINS: admissions stop, every in-flight sequence's state
  is checkpointed host-side (context + tokens emitted so far) and its
  pages freed, and on re-acquire the drained sequences resume at the
  FRONT of the queue — re-prefilled from their checkpointed context, so
  no sequence is lost and no token is emitted twice.

Exact-parity oracles: ``contiguous=True`` allocates each slot a fixed
physically-consecutive page range (the unpaged layout expressed as a
trivial block table) and ``fused=False`` replaces the decode scan with
one jitted step per token — both run the SAME step math, so paged+fused
output is required to be TOKEN-IDENTICAL to the unpaged/unfused oracle
(tests/test_engine.py, ``make enginebench``).

No reference counterpart (the reference is a DRA driver); this is the
workload-payload serving layer. Bench: ``bench.py --leg-serve`` replays
a seeded Poisson arrival trace (workloads/enginebench.py) and records
``serve_tok_s`` / ``serve_p50_ms`` / ``serve_p99_ms``.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Dict, List, Optional

import numpy as np

from tpu_dra.workloads.models.llama import LlamaConfig

# (config, int8?) -> (decode_chunk, decode_step, prefill_chunk) jitted
# callables — see Engine._jit_fns.
_JIT_CACHE: dict = {}

# --- lease gates -------------------------------------------------------------


class LeaseGate:
    """May the engine touch the chip right now? The default gate is
    always open (exclusive claim, no multiplexing)."""

    def ready(self) -> bool:
        return True

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        return True

    def close(self) -> None:
        pass


class EventGate(LeaseGate):
    """Test/drill gate: revoke() closes it, restore() reopens it."""

    def __init__(self, ready: bool = True):
        self._ready = ready
        self.waits = 0

    def ready(self) -> bool:
        return self._ready

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        self.waits += 1
        return self._ready

    def revoke(self) -> None:
        self._ready = False

    def restore(self) -> None:
        self._ready = True


class MultiplexLeaseGate(LeaseGate):
    """The real thing: holds the claim's chip lease through the
    multiplex daemon. ready() pumps the client's event stream (a status
    RPC) so an async revocation flips the gate closed; wait_ready()
    re-acquires, sitting out any post-revocation cooldown the daemon
    imposes."""

    def __init__(self, client):
        from tpu_dra.workloads.multiplex_client import MultiplexClient

        assert isinstance(client, MultiplexClient)
        self._client = client
        self._lease = None

    def ready(self) -> bool:
        if self._lease is None:
            return False
        self._client.status()  # drains pending async revocation events
        if self._client.revoked:
            self._client.revoked = False
            self._lease = None
            return False
        return True

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        from tpu_dra.workloads.multiplex_client import LeaseCooldownError

        if self._lease is not None:
            return True
        try:
            self._lease = self._client.acquire()
            return True
        except LeaseCooldownError as e:
            time.sleep(min(e.retry_after, timeout if timeout else 0.1))
            return False

    def close(self) -> None:
        if self._lease is not None:
            self._client.release()
            self._lease = None
        self._client.close()


def auto_gate(environ=None) -> LeaseGate:
    """MultiplexLeaseGate iff this process runs in a multiplexed
    container (the same CDI-injected env contract as
    multiplex_client.auto_lease), the always-open gate otherwise."""
    import os

    from tpu_dra.workloads.multiplex_client import MultiplexClient

    environ = os.environ if environ is None else environ
    if environ.get("TPU_PROCESS_MULTIPLEXING") != "true":
        return LeaseGate()
    return MultiplexLeaseGate(
        MultiplexClient(environ["TPU_MULTIPLEX_SOCKET_DIR"])
    )


# --- request / sequence state ------------------------------------------------


@dataclasses.dataclass
class Request:
    rid: str
    prompt: np.ndarray  # 1-D int32 token ids
    max_new_tokens: int
    arrival_s: float = 0.0  # offset on the engine's clock; 0 = immediate
    # The caller (the fabric router, resuming an evacuated sequence on
    # a NEW replica) already recorded this request's first token
    # elsewhere: the engine must not observe engine_ttft_seconds again
    # — the resume's "first" token would log a bogus near-zero sample.
    ttft_preobserved: bool = False
    # Prefix sharing (ISSUE 15): requests declaring the same prefix_id
    # AND whose first prefix_len prompt tokens actually match (the
    # engine verifies — an id is a hint, never trusted) map the shared
    # prefix's pages ONCE via PageAllocator.incref and fork
    # copy-on-write at the first divergent write. The fabric router
    # stamps these from its affinity-prefix digest; callers may set
    # them explicitly. 0 / None = no sharing.
    prefix_id: "str | None" = None
    prefix_len: int = 0
    # Sampling-schedule pinning (ISSUE 16): the fabric router journals
    # each request's (seed, serial) so a SAMPLED sequence re-dispatched
    # to a different replica resumes with the exact key schedule the
    # dead engine was using — PR-8's position-keyed folding makes the
    # serial + position the whole schedule. ``sample_serial`` overrides
    # the engine's admission serial in the sampling key only (admission
    # order still breaks drain ties); ``sample_seed`` is an ASSERTION —
    # the seed is an engine-wide traced scalar, so an engine refuses a
    # request pinned to a different seed rather than silently forking
    # the trajectory. None = engine defaults (unpinned).
    sample_seed: "int | None" = None
    sample_serial: "int | None" = None


@dataclasses.dataclass
class Completion:
    rid: str
    tokens: np.ndarray  # the generated tokens (prompt excluded)
    t_submit: float
    t_arrival: float  # t_submit + the request's trace arrival offset
    t_first_token: float
    t_done: float

    @property
    def latency_s(self) -> float:
        """Completion latency from ARRIVAL (a request cannot be served
        before it exists; counting pre-arrival time would flatter
        nothing but punish open-loop traces)."""
        return self.t_done - self.t_arrival

    @property
    def ttft_s(self) -> float:
        return self.t_first_token - self.t_arrival


@dataclasses.dataclass
class Evacuated:
    """One sequence handed back by :meth:`Engine.evacuate` — the
    host-side checkpoint the serving fabric moves to another replica:
    the ORIGINAL request, every token this engine emitted for it, and
    the arrival-side timestamps (the fabric's submitted→first-token SLO
    must survive the move). ``remaining`` new tokens are still owed; a
    resume prefills ``prompt + emitted`` and generates the rest."""

    req: Request
    emitted: np.ndarray  # tokens THIS engine emitted (may be empty)
    t_submit: float
    t_first: Optional[float]  # None when no token was emitted yet

    @property
    def remaining(self) -> int:
        return self.req.max_new_tokens - len(self.emitted)


@dataclasses.dataclass
class SequenceExtent:
    """One sequence lifted off an engine by :meth:`Engine.export_sequence`
    — the live-migration unit (ISSUE 17). Unlike :class:`Evacuated`
    (host checkpoint, resume re-prefills), this carries the sequence's
    KV pages themselves (:class:`~tpu_dra.workloads.paged_kv.KVExtent`),
    so :meth:`Engine.import_sequence` grafts them into the destination
    and decode resumes WITHOUT recomputing a single position. The
    payload is never the source of truth: ``req`` + ``emitted`` suffice
    to rebuild by re-prefill (the crash fallback), token-identically
    under greedy and the pinned (seed, serial, position) schedule."""

    req: Request  # the SOURCE engine's request (prompt = its context)
    emitted: np.ndarray  # tokens the source engine emitted (>= 1)
    extent: object  # paged_kv.KVExtent covering [0, kv_len)
    kv_len: int  # positions written on the source = len(prompt')-1
    t_submit: float
    t_first: Optional[float]
    sample_seed: int  # the source engine's seed — pinned on resume
    sample_serial: int  # the source sequence's sampling serial

    @property
    def remaining(self) -> int:
        return self.req.max_new_tokens - len(self.emitted)

    def resume_request(self) -> Request:
        """The destination-side request: emitted tokens fold into the
        prompt (exactly the fabric's re-dispatch shape), the sampling
        schedule pins, and TTFT rides ``ttft_preobserved`` — the first
        token already happened on the source, so the destination must
        never observe a bogus near-zero sample."""
        return Request(
            rid=self.req.rid,
            prompt=np.concatenate([
                np.asarray(self.req.prompt, np.int32),
                np.asarray(self.emitted, np.int32),
            ]),
            max_new_tokens=self.remaining,
            arrival_s=0.0,
            ttft_preobserved=self.t_first is not None,
            prefix_id=self.req.prefix_id,
            prefix_len=self.req.prefix_len,
            sample_seed=self.sample_seed,
            sample_serial=self.sample_serial,
        )


class _Sequence:
    """Engine-internal per-request state (the sequence-state store)."""

    __slots__ = (
        "req", "context", "out", "slot", "pages", "reserved_left",
        "prefill_cursor", "prefill_done", "t_submit", "t_first", "drains",
        "serial", "sample_serial",
    )

    def __init__(self, req: Request, t_submit: float, serial: int = 0):
        self.serial = serial  # admission order; breaks t_submit ties
        # The sampling-key serial: the caller's pinned schedule when
        # set (cross-replica resume), else the admission serial.
        self.sample_serial = (
            req.sample_serial if req.sample_serial is not None else serial
        )
        self.req = req
        # The tokens to (re-)prefill: the prompt, plus — after a
        # backpressure drain — everything emitted so far.
        self.context = np.asarray(req.prompt, np.int32)
        self.out: List[int] = []  # every emitted token, never reset
        self.slot: Optional[int] = None
        self.pages: List[int] = []
        self.reserved_left = 0
        self.prefill_cursor = 0
        self.prefill_done = False
        self.t_submit = t_submit
        self.t_first: Optional[float] = None
        self.drains = 0

    @property
    def remaining(self) -> int:
        return self.req.max_new_tokens - len(self.out)


class _SharedPrefix:
    """One registered shared prefix: the verified token prefix, the
    page set covering it (full pages shared in place with the
    registering sequence; a mid-page boundary is a FROZEN private copy
    whose tail is zero — so sharers always fork from a page honoring
    the zero-tail invariant), and the registry's own page references
    (dropped on eviction/flush)."""

    __slots__ = ("prefix_id", "tokens", "length", "pages")

    def __init__(self, prefix_id, tokens, length, pages):
        self.prefix_id = prefix_id
        self.tokens = tokens  # np.int32 [length]
        self.length = length
        self.pages = pages  # ordered page ids covering [0, length)


@dataclasses.dataclass
class EngineConfig:
    page_size: int = 16
    max_slots: int = 4
    max_pages_per_seq: int = 16
    num_pages: int = 0  # 0 => 1 + max_slots * max_pages_per_seq
    scan_chunk: int = 8  # decode steps per jitted scan chunk
    prefill_chunk: int = 32  # Sarathi chunk budget per engine iteration
    kv_quant: str = "none"
    # Satellite (ROADMAP item 4 nibble): int8 weight-only matmuls on the
    # WHOLE decode path — attention projections, MLP, and the logits
    # head all go through generate._mm over a quantize_params tree.
    weight_quant: str = "none"
    fused: bool = True  # lax.scan decode chunks; False = per-token oracle
    contiguous: bool = False  # unpaged oracle: fixed consecutive pages
    # Sampling INSIDE the decode scan (PR-2's sample_token/topk_exact):
    # temperature > 0 draws temperature/top-k tokens with a per-sequence
    # key folded as (sample_seed, sequence serial, position) — position-
    # keyed, so the fused scan, the per-token unfused oracle, and a
    # post-drain resume all sample the IDENTICAL token at every position
    # (the engine's sampled parity test pins it). temperature == 0 is
    # greedy (argmax), the previous behavior.
    temperature: float = 0.0
    top_k: int = 0
    sample_seed: int = 0
    # Mesh-sharded decode (SNIPPETS [3] GSPMD pattern): build a
    # (batch x model) mesh over every chip the ComputeDomain's rendered
    # env exposes and NamedShard params / KV pools / batch arrays so the
    # SAME jitted step runs collectively across them — degrading
    # gracefully to a (1, 1) mesh on a single chip. The sharding rules
    # (workloads/parallel/mesh.py) are exactness-preserving: sharded
    # decode is token-identical to unsharded (the shardbench gate).
    sharded: bool = False
    # Speculative decoding (ISSUE 15): spec_k > 0 replaces the decode
    # scan with one jitted VERIFY pass per iteration — a DraftSource
    # (default: NgramDraft(spec_lookup_order) prompt lookup) proposes
    # up to spec_k tokens, their K/V is written into the sequence's
    # pages, and all spec_k+1 positions are evaluated at once. The pick
    # at every position replays the exact (seed, serial, position)
    # schedule the per-token path uses (greedy argmax or the PR-2
    # sampler), so acceptance is exact-parity by construction: the
    # unfused per-token oracle token-matches no matter what the
    # proposer guesses. Rejected positions rewind host-side (pages
    # freed past the accepted length, boundary tail re-zeroed).
    spec_k: int = 0
    spec_lookup_order: int = 3
    # Batched chunked prefill (ISSUE 15): 0 = pack chunks from EVERY
    # currently-prefilling sequence into one padded bucket per
    # iteration (TTFT under admission bursts stops being serialized);
    # n >= 1 caps the rows per bucket (1 = the old one-sequence-per-
    # iteration behavior, kept as the serialized TTFT baseline the
    # bench compares against). The Sarathi stall bound stays the
    # bucket's CHUNK length (<= prefill_chunk); the row count rides the
    # hardware's batch parallelism.
    prefill_batch: int = 0
    # Prefix-sharing registry capacity (LRU): how many distinct shared
    # prefixes this engine keeps pinned. Entries hold page references;
    # eviction (cap, drain, idle exit, page pressure) decrefs them.
    prefix_cache_entries: int = 8

    def resolved_num_pages(self) -> int:
        return self.num_pages or 1 + self.max_slots * self.max_pages_per_seq

    def sampling(self) -> "tuple | None":
        """(temperature, top_k) when sampling is on, None for greedy —
        the STATIC half of the jitted step's signature. The seed is a
        traced input (it rides the device state), so changing seeds
        never recompiles."""
        if self.temperature <= 0.0:
            return None
        return (self.temperature, self.top_k)


class Engine:
    """Continuous-batching serving engine over a paged KV cache.

    ``params`` may be either layout; stacked (``scan_layers=True``)
    trees are unrolled once at construction (the engine steps layers in
    Python, the unrolled in-place idiom). ``gate`` defaults to the
    always-open LeaseGate; pass :func:`auto_gate` () in multiplexed
    containers. ``metrics`` is an optional infra.metrics.Metrics the
    engine exports its gauges/counters into (the doctor consumes
    ``engine_admission_stalled`` and the page-pool gauges).
    """

    def __init__(
        self,
        config: LlamaConfig,
        params: dict,
        engine_config: Optional[EngineConfig] = None,
        gate: Optional[LeaseGate] = None,
        metrics=None,
        clock=time.monotonic,
        draft_source=None,
    ):
        import jax

        from tpu_dra.workloads.generate import unroll_params
        from tpu_dra.workloads.paged_kv import (
            PageAllocator,
            init_paged_cache,
        )
        from tpu_dra.workloads.quantize import quantize_params

        self.config = config
        self.ec = engine_config or EngineConfig()
        if self.ec.scan_chunk < 1 or self.ec.prefill_chunk < 1:
            raise ValueError("scan_chunk and prefill_chunk must be >= 1")
        if self.ec.spec_k < 0 or self.ec.prefill_batch < 0:
            raise ValueError("spec_k and prefill_batch must be >= 0")
        if self.ec.spec_k > 0 and not self.ec.fused:
            raise ValueError(
                "spec_k requires fused=True — the unfused per-token "
                "path IS the exactness oracle speculation is verified "
                "against"
            )
        if self.ec.spec_k > 0 and self.ec.sharded:
            raise ValueError(
                "spec_k with sharded=True is not supported yet (the "
                "verify pass has no GSPMD sharding rules); run "
                "speculation on single-chip engines"
            )
        self._draft = draft_source
        if self._draft is None and self.ec.spec_k > 0:
            from tpu_dra.workloads.specdraft import NgramDraft

            self._draft = NgramDraft(self.ec.spec_lookup_order)
        params = unroll_params(params)
        if self.ec.weight_quant == "int8":
            params = quantize_params(params)
        elif self.ec.weight_quant != "none":
            raise ValueError(
                f"unknown weight_quant {self.ec.weight_quant!r}"
            )
        self.mesh = None
        self._row_sharding = None
        if self.ec.sharded:
            from tpu_dra.workloads.parallel import mesh as meshlib

            self.mesh = meshlib.build_decode_mesh(config)
            # Multi-device mesh: the pallas-capable decode ops must run
            # their XLA paths (no SPMD rule for custom kernels — see
            # mesh.sharded_safe_config). Re-binds self.config so the
            # jit cache keys on the adjusted config.
            self.config = config = meshlib.sharded_safe_config(
                config, self.mesh
            )
            self.params = meshlib.shard_decode_params(self.mesh, params)
            self._row_sharding = meshlib.decode_data_sharding(
                self.mesh, self.ec.max_slots
            )
        else:
            self.params = jax.device_put(params)
        self.gate = gate or LeaseGate()
        self.metrics = metrics
        self.clock = clock

        P = self.ec.resolved_num_pages()
        if self.ec.contiguous:
            need = 1 + self.ec.max_slots * self.ec.max_pages_per_seq
            if P < need:
                raise ValueError(
                    f"contiguous mode needs {need} pages "
                    f"(1 + slots*max_pages_per_seq), got {P}"
                )
        self.cache = init_paged_cache(
            config, P, self.ec.page_size, kv_quant=self.ec.kv_quant
        )
        if self.mesh is not None:
            from tpu_dra.workloads.parallel import mesh as meshlib

            self.cache = jax.tree_util.tree_map(
                lambda leaf: jax.device_put(
                    leaf,
                    meshlib.decode_pool_sharding(
                        self.mesh, config.n_kv_heads, leaf.ndim
                    ),
                ),
                self.cache,
            )
        self.allocator = PageAllocator(P)
        B, M = self.ec.max_slots, self.ec.max_pages_per_seq
        self._tables = np.zeros((B, M), np.int32)  # SCRATCH_PAGE default
        self._lengths = np.zeros((B,), np.int32)
        self._last_tokens = np.zeros((B,), np.int32)
        self._active = np.zeros((B,), bool)
        self._seeds = np.zeros((B,), np.int32)  # per-slot sampling serial
        # The engine-wide sample seed rides as a TRACED scalar (not a
        # jit static): engines differing only by seed share one
        # compiled executable.
        self._seed_scalar = np.int32(self.ec.sample_seed)
        self._slots: List[Optional[_Sequence]] = [None] * B
        # Device mirror of (tables, lengths, last, active, seeds): the
        # fused chunk RETURNS lengths/last as device arrays, so a steady
        # full-slot decode stretch feeds them straight back instead of a
        # host->device round trip per chunk; any host-side mutation
        # (page alloc, admission/eviction, prefill) invalidates it.
        self._dev_state = None

        self._queue: collections.deque = collections.deque()  # _Sequence
        self._prefilling: collections.deque = collections.deque()
        self._pending_zero: List[int] = []
        self._blocked_on_pages = False
        self._serial = 0
        self._rids: set = set()  # every rid ever accepted (dup guard)
        self._progress = 0  # bumps on admission/prefill/tokens: O(1)
        # idle detection for run() instead of O(live) scans per step
        self.completed: Dict[str, Completion] = {}
        self._stalled_since: Optional[float] = None
        self._exhausted_exported = 0
        # Prefix-sharing registry (ISSUE 15): prefix_id -> _SharedPrefix
        # holding ONE page-reference set per distinct shared prefix
        # (insertion-ordered dict = LRU by registration). Entries pin
        # their pages (incref); flushed on drain/evacuate/idle exit,
        # LRU-evicted at the cap, and shed under page pressure.
        self._prefix_registry: Dict[str, _SharedPrefix] = {}
        # Lifetime speculation accounting (bench-readable without a
        # metrics registry).
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.prefix_attached = 0
        self.cow_copies = 0
        self.prefix_saved_hw = 0  # high-water of allocator.shared_extra
        self._jit_fns()

    # --- jitted forward -------------------------------------------------

    def _jit_fns(self):
        import functools

        import jax

        c = self.config
        quant = self.ec.kv_quant == "int8"
        sampling = self.ec.sampling()
        # One jitted callable per (model config, storage mode, sampling
        # statics), shared across Engine instances: jax's trace cache
        # lives on the callable, so a fresh engine over the same shapes
        # reuses the compiled executables instead of re-tracing.
        # (Sharded instances share these too — jit re-lowers per input
        # sharding on its own cache.)
        key = (c, quant, sampling)
        fns = _JIT_CACHE.get(key)
        if fns is None:
            fns = (
                jax.jit(
                    functools.partial(_decode_chunk, c, quant, sampling),
                    static_argnames=("steps",),
                ),
                jax.jit(functools.partial(_decode_step, c, quant, sampling)),
                jax.jit(functools.partial(_prefill_batch, c, quant)),
                jax.jit(functools.partial(_verify_chunk, c, quant, sampling)),
            )
            _JIT_CACHE[key] = fns
        (
            self._decode_chunk_fn,
            self._decode_step_fn,
            self._prefill_chunk_fn,
            self._verify_chunk_fn,
        ) = fns

    # --- public API ------------------------------------------------------

    # thread: any (append-only handoff, safe concurrent with the owner's step; see serving/router.py)
    def add_request(self, req: Request) -> None:
        if len(req.prompt) < 1 or req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: need >= 1 prompt token and >= 1 "
                f"new token"
            )
        # rids key the completion store: a duplicate would make its
        # second _finish a no-op that never releases the slot — an
        # engine hang, so refuse it at the door (O(1) set lookup).
        if req.rid in self._rids:
            raise ValueError(f"duplicate request rid {req.rid!r}")
        # A pinned sampling schedule is only reproducible on an engine
        # sharing the pinned seed (the seed is engine-wide; the serial
        # is per-request). Refuse a mismatch loudly — silently sampling
        # under a different seed would fork the trajectory the caller
        # journaled.
        if (
            req.sample_seed is not None
            and req.sample_seed != self.ec.sample_seed
        ):
            raise ValueError(
                f"request {req.rid}: pinned sample_seed "
                f"{req.sample_seed} != engine seed {self.ec.sample_seed}"
            )
        self._rids.add(req.rid)
        total = (
            len(req.prompt) + req.max_new_tokens + self.ec.scan_chunk
        )
        if total > self.ec.max_pages_per_seq * self.ec.page_size:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + "
                f"max_new {req.max_new_tokens} (+ chunk slack "
                f"{self.ec.scan_chunk}) exceeds the per-sequence page "
                f"budget {self.ec.max_pages_per_seq}x{self.ec.page_size}"
            )
        self._serial += 1
        self._queue.append(
            _Sequence(req, t_submit=self.clock(), serial=self._serial)
        )

    @property
    def busy(self) -> bool:
        return bool(
            self._queue or self._prefilling or any(self._slots)
        )

    @property
    def progress(self) -> int:
        """Monotonic step-progress heartbeat: bumps on every admission,
        prefill chunk, and decode chunk that moved work. The fabric's
        stuck-iteration watchdog (ISSUE 16) declares a replica dead
        when this stands still past a deadline while work is in
        flight."""
        return self._progress

    def step(self) -> bool:
        """One engine iteration: gate check (drain on backpressure),
        admissions, one prefill chunk, one decode chunk. Returns True
        while work remains; never blocks on the gate (run() waits)."""
        now = self.clock()
        if not self.gate.ready():
            self._enter_stall(now)
            self._export()
            return self.busy
        self._exit_stall()
        self._admit(now)
        self._prefill_tick(now)
        self._decode_tick(now)
        self._export()
        return self.busy

    def run(
        self, requests=None, poll_seconds: float = 0.002
    ) -> Dict[str, Completion]:
        """Submit ``requests`` (optional) and step until idle; blocks on
        the lease gate / future arrivals between steps."""
        for r in requests or []:
            self.add_request(r)
        while self.busy:
            stalled = self._stalled_since is not None
            before = self._progress
            self.step()
            made_progress = self._progress != before
            if self._stalled_since is not None:
                if not self.gate.wait_ready(timeout=poll_seconds):
                    # A gate whose wait doesn't block (stub gates) must
                    # not turn the stall into a hot spin.
                    time.sleep(poll_seconds)
            elif not made_progress and not stalled:
                # Idle but not done: waiting on a future arrival.
                time.sleep(poll_seconds)
        # Idle exit: the prefix cache releases its page pins so a
        # completed run leaves the allocator leak-free (the zero-leak
        # acceptance); the next run re-registers on first use.
        self._flush_prefix_registry()
        self._flush_zero()
        self._export()
        return self.completed

    def close(self) -> None:
        self.gate.close()

    def evacuate(self) -> "List[Evacuated]":
        """Tenant-transparent eviction (ISSUE 11): drain every in-flight
        sequence to host state via the PR-7 backpressure drain (pages
        freed, contexts folded), then hand the WHOLE live set — drained
        and still-queued alike — back to the caller, leaving the engine
        empty. The serving fabric's autoscaler uses this as the
        scale-down primitive: the claim behind this engine is only
        deleted once evacuate() returned, and the evacuated sequences
        resume on another replica by prefilling ``prompt + emitted`` —
        no sequence lost, no token re-emitted (under greedy decoding a
        resumed continuation is token-identical to the uninterrupted
        run; a SAMPLED trajectory survives the move too when the caller
        pins the journaled schedule via ``Request.sample_seed`` /
        ``sample_serial`` — the (seed, serial, position) key is then
        identical on the new replica). rids are forgotten, so a
        sequence may later be resubmitted to this same engine."""
        self._drain(self.clock())
        out: List[Evacuated] = []
        while self._queue:
            seq = self._queue.popleft()
            self._rids.discard(seq.req.rid)
            out.append(Evacuated(
                req=seq.req,
                emitted=np.asarray(seq.out, np.int32),
                t_submit=seq.t_submit,
                t_first=seq.t_first,
            ))
        self._flush_zero()
        self._inc("engine_evacuated_total", len(out))
        self._export()
        return out

    # --- live KV migration (ISSUE 17) -------------------------------------

    def decoding_rids(self) -> List[str]:
        """rids of sequences that finished prefill and are actively
        decoding — the migration candidates a prefill-role replica
        ships to the decode pool."""
        return [
            s.req.rid
            for s in self._slots
            if s is not None and s.prefill_done and s.out
        ]

    def export_sequence(self, rid: str) -> SequenceExtent:
        """Lift a decoding sequence off this engine: serialize its
        block-table extent (K/V pools per page, int8 scales included),
        release its slot and pages (each page decref'd exactly once —
        shared-prefix pages stay pinned by the registry/other tables),
        and forget the rid so a fallback may resubmit here. The
        returned :class:`SequenceExtent` grafts into another engine via
        :meth:`import_sequence` and decode resumes at the exact
        position, token-identical to an un-migrated twin."""
        from tpu_dra.workloads import paged_kv

        if self.ec.contiguous:
            raise ValueError(
                "contiguous (oracle) engines do not export extents — "
                "their block tables are fixed physical ranges"
            )
        seq = next(
            (
                s for s in self._slots
                if s is not None and s.req.rid == rid
            ),
            None,
        )
        if seq is None or not seq.prefill_done or not seq.out:
            raise ValueError(
                f"rid {rid!r} is not an exportable decoding sequence"
            )
        slot = seq.slot
        kv_len = int(self._lengths[slot])
        page = self.ec.page_size
        keep = -(-kv_len // page)
        # Pages past the written extent exist only as scan-chunk slack
        # and are entirely zero (the invariant) — they stay behind and
        # free with the slot.
        extent = paged_kv.serialize_extent(
            self.cache, seq.pages[:keep], kv_len
        )
        sx = SequenceExtent(
            req=seq.req,
            emitted=np.asarray(seq.out, np.int32),
            extent=extent,
            kv_len=kv_len,
            t_submit=seq.t_submit,
            t_first=seq.t_first,
            sample_seed=self.ec.sample_seed,
            sample_serial=seq.sample_serial,
        )
        self._release_slot(slot)
        self._rids.discard(rid)
        self._progress += 1
        self._inc("engine_kv_exports_total")
        return sx

    def import_sequence(
        self, sx: SequenceExtent, req: Optional[Request] = None
    ) -> bool:
        """Graft an exported sequence into this engine and resume its
        decode at position ``kv_len`` — no position recomputed. False
        when the engine lacks a free slot or page headroom RIGHT NOW
        (normal backpressure: the caller falls back to re-prefill
        dispatch); config mismatches raise. Leading full pages of a
        prefix this engine already has registered attach by INCREF
        instead of copying (the by-id carry), and the imported prefix
        registers here for future sharers."""
        from tpu_dra.workloads import paged_kv

        if self.ec.contiguous:
            raise ValueError(
                "contiguous (oracle) engines do not import extents"
            )
        req = req if req is not None else sx.resume_request()
        if req.rid in self._rids:
            raise ValueError(f"duplicate request rid {req.rid!r}")
        if (
            req.sample_seed is not None
            and req.sample_seed != self.ec.sample_seed
        ):
            raise ValueError(
                f"request {req.rid}: pinned sample_seed "
                f"{req.sample_seed} != engine seed {self.ec.sample_seed}"
            )
        if sx.extent.page_size != self.ec.page_size:
            raise ValueError(
                f"extent page_size {sx.extent.page_size} != engine "
                f"page_size {self.ec.page_size}"
            )
        if len(req.prompt) != sx.kv_len + 1 or req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: prompt must cover kv_len "
                f"{sx.kv_len} + 1 not-yet-written token, with >= 1 "
                f"token still owed"
            )
        total = (
            len(req.prompt) + req.max_new_tokens + self.ec.scan_chunk
        )
        if total > self.ec.max_pages_per_seq * self.ec.page_size:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + "
                f"max_new {req.max_new_tokens} (+ chunk slack "
                f"{self.ec.scan_chunk}) exceeds the per-sequence page "
                f"budget {self.ec.max_pages_per_seq}x{self.ec.page_size}"
            )
        slot = next(
            (i for i, s in enumerate(self._slots) if s is None), None
        )
        if slot is None:
            return False
        self._serial += 1
        seq = _Sequence(req, t_submit=sx.t_submit, serial=self._serial)
        seq.t_first = sx.t_first
        seq.prefill_done = True
        seq.prefill_cursor = len(seq.context)
        need = self._pages_for(seq)
        if not self.allocator.reserve(need):
            while self._prefix_registry and not self.allocator.can_reserve(
                need
            ):
                self._evict_one_prefix()
            if not self.allocator.reserve(need):
                return False
        self._rids.add(req.rid)
        seq.slot = slot
        seq.reserved_left = need
        # Import-side by-id carry: leading FULL pages of a registered
        # matching prefix attach via incref — the extent's payload for
        # those slots is ignored (prefill KV is a deterministic
        # function of the tokens, so the registered pages hold
        # byte-identical content).
        attach: Dict[int, int] = {}
        entry = (
            self._prefix_registry.get(req.prefix_id)
            if req.prefix_id else None
        )
        if entry is not None and np.array_equal(
            seq.context[: entry.length], entry.tokens
        ):
            n_full = min(
                entry.length // self.ec.page_size, sx.extent.n_pages
            )
            for j in range(n_full):
                attach[j] = entry.pages[j]
        # Deferred zeroing must land before any freed page can be
        # re-allocated into the graft.
        self._flush_zero()

        def _alloc():
            self.allocator.unreserve(1)
            seq.reserved_left -= 1
            return self.allocator.alloc()

        self.cache, pages = paged_kv.graft_extent(
            self.cache, self.allocator, sx.extent,
            alloc=_alloc, attach=attach,
        )
        if attach:
            # Attached pages come off the worst-case reservation (full
            # pages only — writes land past them, COW forks are the
            # write path's business).
            release = min(len(attach), seq.reserved_left)
            if release > 0:
                self.allocator.unreserve(release)
                seq.reserved_left -= release
            self.prefix_attached += 1
            self._inc("engine_prefix_attached_total")
        seq.pages = pages
        self._slots[slot] = seq
        self._tables[slot, : len(pages)] = pages
        self._lengths[slot] = sx.kv_len
        self._last_tokens[slot] = int(seq.context[-1])
        self._active[slot] = True
        self._seeds[slot] = seq.sample_serial
        self._dev_state = None
        self._maybe_register_prefix(seq)
        self._track_shared()
        self._progress += 1
        self._inc("engine_kv_imports_total")
        return True

    def _live(self):
        """Every not-yet-completed sequence, exactly once (prefilling
        sequences appear in both _prefilling and _slots)."""
        seen = set()
        for s in (
            list(self._queue) + list(self._prefilling)
            + [x for x in self._slots if x is not None]
        ):
            if id(s) not in seen:
                seen.add(id(s))
                yield s

    # --- backpressure ----------------------------------------------------

    def _enter_stall(self, now: float) -> None:
        if self._stalled_since is None:
            self._stalled_since = now
            if self._drain(now):
                # Count only stalls that actually drained work — a cold
                # engine waiting for its first lease is not an incident.
                self._inc("engine_backpressure_drains_total")

    def _exit_stall(self) -> None:
        self._stalled_since = None

    def _drain(self, now: float) -> int:
        """Checkpoint every in-flight sequence host-side and free its
        device state: the co-tenant gets the chip AND the pages. Drained
        sequences resume at the FRONT of the queue (oldest first) with
        their emitted tokens folded into the context — nothing is lost,
        nothing re-emitted. Returns how many sequences were drained."""
        # The prefix cache's page pins go too — the co-tenant gets ALL
        # the pages. Resume re-registers through the normal path: the
        # first re-prefilled sharer re-freezes the prefix and the rest
        # RE-ATTACH via incref (sharing survives the drain — pinned by
        # the drain-under-COW test).
        self._flush_prefix_registry()
        drained: List[_Sequence] = []
        for slot, seq in enumerate(self._slots):
            if seq is None:
                continue
            self._release_slot(slot)
            seq.context = np.concatenate(
                [np.asarray(seq.req.prompt, np.int32),
                 np.asarray(seq.out, np.int32)]
            )
            seq.prefill_cursor = 0
            seq.prefill_done = False
            seq.drains += 1
            drained.append(seq)
        self._prefilling.clear()
        # appendleft inverts iteration order, so walk newest-first to
        # land oldest at the queue front; the admission serial breaks
        # t_submit ties (a coarse clock can stamp a whole burst with one
        # value, and a stable sort alone would then resume newest-first).
        for seq in sorted(
            drained, key=lambda s: (s.t_submit, s.serial), reverse=True
        ):
            self._queue.appendleft(seq)
        return len(drained)

    # --- admission / slots ------------------------------------------------

    def _pages_for(self, seq: _Sequence) -> int:
        """Worst-case page count the sequence can touch: full context +
        every generated token + one scan chunk of post-completion slack
        (a sequence finishing mid-chunk keeps writing until the chunk
        ends)."""
        total = (
            len(seq.context) + seq.remaining + self.ec.scan_chunk
        )
        return -(-total // self.ec.page_size)

    def _admit(self, now: float) -> None:
        self._blocked_on_pages = False
        while self._queue:
            seq = self._queue[0]
            if seq.t_submit + seq.req.arrival_s > now and not seq.drains:
                return  # FIFO: the head hasn't arrived yet
            slot = next(
                (i for i, s in enumerate(self._slots) if s is None), None
            )
            if slot is None:
                return
            need = self._pages_for(seq)
            if not self.ec.contiguous and not self.allocator.can_reserve(
                need
            ):
                # Shed prefix-cache pins before declaring backpressure:
                # a cache must never block admission (its pages only
                # free for real once no live table references them).
                while self._prefix_registry and not (
                    self.allocator.can_reserve(need)
                ):
                    self._evict_one_prefix()
            if not self.ec.contiguous and not self.allocator.reserve(need):
                # Page pool too tight for the head-of-line request:
                # admission WAITS until evictions free pages (FIFO — no
                # smaller request jumps the line and starves the head).
                # This is expected backpressure, not exhaustion — it is
                # exported as the blocked-on-pages gauge, never the
                # engine_page_exhausted_total counter (that counter
                # means an allocation the reservation system promised
                # could not be served: an invariant violation).
                self._blocked_on_pages = True
                if not any(s is not None for s in self._slots):
                    from tpu_dra.workloads.paged_kv import (
                        PageExhaustedError,
                    )

                    raise PageExhaustedError(
                        f"request {seq.req.rid} needs {need} pages but "
                        f"the pool ({self.allocator.num_pages} pages) "
                        f"cannot cover it even empty — raise num_pages "
                        f"or lower max_pages_per_seq"
                    )
                return
            self._queue.popleft()
            seq.slot = slot
            seq.reserved_left = 0 if self.ec.contiguous else need
            self._slots[slot] = seq
            self._seeds[slot] = seq.sample_serial
            self._dev_state = None
            self._prefilling.append(seq)
            self._progress += 1
            self._inc("engine_admitted_total")

    def _flush_zero(self) -> None:
        """Batch-zero every page released since the last flush. Runs
        before any page can be re-allocated, so a new owner always
        starts from zero pages (values and scales)."""
        from tpu_dra.workloads import paged_kv

        if self._pending_zero:
            self.cache = paged_kv.zero_pages(self.cache, self._pending_zero)
            self._pending_zero = []

    def _alloc_page(self, seq: _Sequence) -> int:
        self._flush_zero()
        if self.ec.contiguous:
            j = len(seq.pages)
            page = 1 + seq.slot * self.ec.max_pages_per_seq + j
        else:
            self.allocator.unreserve(1)
            seq.reserved_left -= 1
            page = self.allocator.alloc()
        seq.pages.append(page)
        self._tables[seq.slot, len(seq.pages) - 1] = page
        self._dev_state = None
        return page

    def _ensure_pages(self, seq: _Sequence, upto: int) -> None:
        """Grow the block table until it covers positions [0, upto)."""
        need = -(-upto // self.ec.page_size)
        while len(seq.pages) < need:
            self._alloc_page(seq)

    def _release_slot(self, slot: int) -> None:
        from tpu_dra.workloads import paged_kv

        seq = self._slots[slot]
        assert seq is not None
        freed = []
        if not self.ec.contiguous:
            for page in seq.pages:
                if self.allocator.decref(page):
                    freed.append(page)
            if seq.reserved_left:
                self.allocator.unreserve(seq.reserved_left)
                seq.reserved_left = 0
        else:
            freed = list(seq.pages)
        # Freed pages must be re-zeroed (the per-page zero-tail
        # invariant) before ANY of them is handed out again — but one
        # scatter per eviction is pure dispatch overhead, so the zeroing
        # is DEFERRED and flushed as one batch the moment the next
        # allocation (or an idle engine) needs it (_flush_zero).
        self._pending_zero.extend(freed)
        seq.pages = []
        seq.slot = None
        self._slots[slot] = None
        self._tables[slot] = paged_kv.SCRATCH_PAGE
        self._lengths[slot] = 0
        self._last_tokens[slot] = 0
        self._active[slot] = False
        self._seeds[slot] = 0
        self._dev_state = None

    # --- prefix sharing (ISSUE 15) ----------------------------------------

    def _try_attach_prefix(self, seq: _Sequence) -> None:
        """Map a registered shared prefix's pages into this sequence's
        table via incref and skip prefilling those positions. Only a
        sequence that has not started (no pages, cursor 0) may attach;
        the id is a hint — the registered TOKENS must match the
        sequence's own context, or nothing is shared."""
        pid = seq.req.prefix_id
        if not pid or self.ec.contiguous:
            return
        entry = self._prefix_registry.get(pid)
        if entry is None or entry.length > len(seq.context) - 1:
            return
        if not np.array_equal(seq.context[: entry.length], entry.tokens):
            return
        page = self.ec.page_size
        for pg in entry.pages:
            self.allocator.incref(pg)
        seq.pages = list(entry.pages)
        self._tables[seq.slot, : len(entry.pages)] = entry.pages
        seq.prefill_cursor = entry.length
        # The attached pages come off the worst-case reservation —
        # minus one page of copy-on-write allowance when the prefix
        # ends mid-page (the first divergent write forks that page).
        release = len(entry.pages) - (1 if entry.length % page else 0)
        release = min(release, seq.reserved_left)
        if release > 0:
            self.allocator.unreserve(release)
            seq.reserved_left -= release
        # LRU touch: re-insert at the tail.
        self._prefix_registry[pid] = self._prefix_registry.pop(pid)
        self.prefix_attached += 1
        self._dev_state = None
        self._inc("engine_prefix_attached_total")
        self._track_shared()

    def _maybe_register_prefix(self, seq: _Sequence) -> None:
        """Register this sequence's prefix pages for future sharers
        (called at prefill completion, when the pages exist). Full
        pages are shared in place; a mid-page boundary is FROZEN into a
        private copy holding exactly [0, p) with a zero tail, so the
        registering sequence keeps growing its own boundary page
        privately and every sharer forks from a clean page."""
        from tpu_dra.workloads import paged_kv

        pid = seq.req.prefix_id
        plen = seq.req.prefix_len
        if not pid or plen < 1 or self.ec.contiguous:
            return
        if pid in self._prefix_registry:
            return
        # Clamp inside the PROMPT (a drained sequence's context carries
        # emitted tokens — the shared prefix is a prompt property) and
        # so at least one context token remains to prefill: the first
        # generated token needs the last context position's logits,
        # which only a real prefill chunk produces.
        p = min(plen, len(seq.req.prompt), len(seq.context) - 1)
        if p < 1:
            return
        page = self.ec.page_size
        n_full = p // page
        partial = p % page
        pages = list(seq.pages[:n_full])
        if partial:
            # The frozen boundary copy needs one page of UNRESERVED
            # headroom — a cache never eats into admission guarantees.
            if self.allocator.free_pages - self.allocator.reserved_pages < 1:
                return
            self._flush_zero()
            frozen = self.allocator.alloc()
            self.cache = paged_kv.copy_page_prefix(
                self.cache, seq.pages[n_full], frozen, partial
            )
            pages.append(frozen)
        for pg in pages[:n_full]:
            self.allocator.incref(pg)
        self._prefix_registry[pid] = _SharedPrefix(
            pid, np.asarray(seq.context[:p], np.int32).copy(), p, pages
        )
        while len(self._prefix_registry) > max(
            self.ec.prefix_cache_entries, 1
        ):
            self._evict_one_prefix(exclude=pid)
        self._inc("engine_prefix_registered_total")
        self._track_shared()

    def _evict_one_prefix(self, exclude: Optional[str] = None) -> bool:
        for key in self._prefix_registry:
            if key != exclude:
                entry = self._prefix_registry.pop(key)
                for pg in entry.pages:
                    if self.allocator.decref(pg):
                        self._pending_zero.append(pg)
                return True
        return False

    def _flush_prefix_registry(self) -> None:
        while self._evict_one_prefix():
            pass

    def _cow_range(self, seq: _Sequence, lo: int, hi: int) -> None:
        """Copy-on-write guard for a coming write to positions
        [lo, hi): any already-mapped page in that range still shared
        with another table (refcount > 1) is forked — full-page device
        copy (values AND scales travel together), swap into this
        sequence's table, drop the shared reference. The shared page's
        other holders are untouched; it is never zeroed while they
        hold it (decref cannot free it here)."""
        if self.ec.contiguous:
            return
        from tpu_dra.workloads import paged_kv

        page = self.ec.page_size
        for j in range(lo // page, min(-(-hi // page), len(seq.pages))):
            old = seq.pages[j]
            if self.allocator.refcount(old) <= 1:
                continue
            self._flush_zero()
            if seq.reserved_left > 0:
                self.allocator.unreserve(1)
                seq.reserved_left -= 1
            new = self.allocator.alloc()
            self.cache = paged_kv.copy_page(self.cache, old, new)
            self.allocator.decref(old)  # shared: never frees/zeroes here
            seq.pages[j] = new
            self._tables[seq.slot, j] = new
            self.cow_copies += 1
            self._dev_state = None
            self._inc("engine_cow_copies_total")

    def _track_shared(self) -> int:
        # The registry's own pins stand in for no allocation — a
        # registered-but-never-shared prefix must report 0 saved, so
        # its references are discounted from the sharing count.
        pins: Dict[int, int] = {}
        for entry in self._prefix_registry.values():
            for pg in entry.pages:
                pins[pg] = pins.get(pg, 0) + 1
        saved = self.allocator.shared_extra(discount=pins)
        if saved > self.prefix_saved_hw:
            self.prefix_saved_hw = saved
        return saved

    # --- prefill ----------------------------------------------------------

    def _prefill_tick(self, now: float) -> None:
        """One batched-prefill iteration (ISSUE 15): chunks from up to
        ``prefill_batch`` (0 = all) currently-prefilling sequences pack
        into ONE padded bucket. The Sarathi stall bound is the bucket's
        CHUNK LENGTH (still capped by prefill_chunk); its row count
        rides the hardware's batch parallelism — so k waiting prompts
        advance a chunk each for ~one chunk of decode stall, and TTFT
        under admission bursts stops being serialized. The bucket's
        batch dim is the ROW count padded to a power of two (capped at
        max_slots), so a lone prompt pays ~its own cost, not
        max_slots rows; idle pad rows carry valid=0 and write
        nothing."""
        if not self._prefilling:
            return
        import jax.numpy as jnp

        limit = (
            len(self._prefilling) if self.ec.prefill_batch == 0
            else self.ec.prefill_batch
        )
        rows: List[_Sequence] = []
        leading: set = set()
        for seq in self._prefilling:
            if len(rows) >= limit:
                break
            if seq.prefill_cursor == 0 and not seq.pages:
                self._try_attach_prefix(seq)
            pid = seq.req.prefix_id
            unregistered = (
                pid and seq.req.prefix_len > 0
                and pid not in self._prefix_registry
                and not self.ec.contiguous
            )
            if unregistered and pid in leading:
                # Another row in THIS bucket will register this prefix
                # when it completes; prefilling the same prefix
                # privately in parallel would defeat the sharing — the
                # follower waits a tick and attaches instead.
                continue
            if unregistered:
                leading.add(pid)
            rows.append(seq)
        # PER-ROW chunk budget: the bucket's wall clock is set by its
        # CHUNK LENGTH, not its row count (the batch dimension rides
        # the hardware's parallelism — that is the whole win: k waiting
        # prompts advance a chunk each for ~one chunk of decode stall,
        # where the serial schedule advanced one). Splitting the budget
        # across rows would keep the iteration count identical to
        # serial and merely reorder who waits.
        takes = [
            min(
                self.ec.prefill_chunk,
                len(seq.context) - seq.prefill_cursor,
            )
            for seq in rows
        ]
        # Pad the bucket's chunk length to a power of two (capped at
        # the budget): one trace/compile per bucket, pad tokens write
        # to scratch.
        bucket = 1
        while bucket < max(takes):
            bucket *= 2
        bucket = min(bucket, self.ec.prefill_chunk)
        # The ROW count is bucketed to a power of two as well (capped
        # at max_slots): a lone arriving prompt must not pay
        # max_slots x its own FLOPs through every layer for idle
        # scratch rows. Trace-cache growth stays bounded at
        # #chunk-buckets x #row-buckets; idle rows carry valid=0.
        B = 1
        while B < len(rows):
            B *= 2
        B = min(B, self.ec.max_slots)
        tokens = np.zeros((B, bucket), np.int32)
        starts = np.zeros((B,), np.int32)
        valids = np.zeros((B,), np.int32)
        trows = np.zeros((B,) + self._tables.shape[1:],
                         self._tables.dtype)
        for i, (seq, take) in enumerate(zip(rows, takes)):
            self._cow_range(
                seq, seq.prefill_cursor, seq.prefill_cursor + take
            )
            self._ensure_pages(seq, seq.prefill_cursor + take)
            tokens[i, :take] = seq.context[
                seq.prefill_cursor: seq.prefill_cursor + take
            ]
            starts[i] = seq.prefill_cursor
            valids[i] = take
            trows[i] = self._tables[seq.slot]
        self.cache, logits = self._prefill_chunk_fn(
            self.params, self.cache,
            jnp.asarray(trows),
            jnp.asarray(starts), jnp.asarray(tokens),
            jnp.asarray(valids),
        )
        logits_h = None
        finished: List[_Sequence] = []
        for i, (seq, take) in enumerate(zip(rows, takes)):
            slot = seq.slot
            seq.prefill_cursor += take
            self._inc("engine_prefill_tokens_total", take)
            if seq.prefill_cursor == len(seq.context):
                finished.append(seq)
                seq.prefill_done = True
                self._maybe_register_prefix(seq)
                if logits_h is None:
                    logits_h = np.asarray(logits)
                first = self._pick_first(seq, logits_h[i])
                self._record_tokens(seq, [first])
                if seq.slot is not None:  # not finished by that token
                    self._lengths[slot] = len(seq.context)
                    self._last_tokens[slot] = first
                    self._active[slot] = True
        for seq in finished:
            self._prefilling.remove(seq)
        self._progress += 1
        self._dev_state = None

    def _pick_first(self, seq: _Sequence, logits) -> int:
        """First generated token from the prefill logits: argmax, or —
        under sampling — the SAME (seed, serial, position) key schedule
        the decode scan uses, at position len(context). A drained
        sequence re-prefills a longer context and re-samples at the new
        frontier with the same key it would have used mid-scan, so
        resume cannot fork the trajectory."""
        sampling = self.ec.sampling()
        if sampling is None:
            return int(np.argmax(np.asarray(logits)))
        import jax
        import jax.numpy as jnp

        from tpu_dra.workloads.generate import sample_token

        temperature, top_k = sampling
        key = jax.random.fold_in(
            jax.random.fold_in(
                jax.random.PRNGKey(self.ec.sample_seed),
                seq.sample_serial,
            ),
            len(seq.context),
        )
        return int(
            np.asarray(
                sample_token(
                    jnp.asarray(logits)[None], key, temperature, top_k
                )
            )[0]
        )

    # --- decode ------------------------------------------------------------

    def _put_row(self, arr):
        """Host batch array -> device, with the decode mesh's batch
        sharding when the engine runs sharded."""
        import jax
        import jax.numpy as jnp

        if self._row_sharding is not None:
            return jax.device_put(arr, self._row_sharding)
        return jnp.asarray(arr)

    def _decode_tick(self, now: float) -> None:
        if not self._active.any():
            return
        if self.ec.spec_k > 0:
            return self._spec_tick(now)
        import jax.numpy as jnp

        steps = self.ec.scan_chunk
        for slot, seq in enumerate(self._slots):
            if seq is not None and self._active[slot]:
                self._ensure_pages(seq, int(self._lengths[slot]) + steps)
        if self._dev_state is None:
            # Host bookkeeping changed since the last chunk: re-upload.
            self._dev_state = (
                self._put_row(self._tables),
                self._put_row(self._lengths),
                self._put_row(self._last_tokens),
                self._put_row(self._active),
                self._put_row(self._seeds),
                jnp.asarray(self._seed_scalar),
            )
        tables_d, lengths_d, last_d, active_d, seeds_d, seed_d = (
            self._dev_state
        )
        if self.ec.fused:
            self.cache, lengths, last, out = self._decode_chunk_fn(
                self.params, self.cache, tables_d, lengths_d, last_d,
                active_d, seeds_d, seed_d, steps=steps,
            )
        else:
            # Unfused oracle: one XLA entry per token, same step math.
            cache, lengths, last = self.cache, lengths_d, last_d
            outs = []
            for _ in range(steps):
                cache, lengths, last = self._decode_step_fn(
                    self.params, cache, tables_d, lengths, last,
                    active_d, seeds_d, seed_d,
                )
                outs.append(last)
            self.cache = cache
            out = jnp.stack(outs)
        # The chunk's outputs ARE next chunk's inputs: keep them on
        # device (a steady full-slot stretch re-uploads nothing — the
        # per-chunk host->device round trip the roofline work removed);
        # any host mutation below (a mid-chunk finisher evicting) just
        # resets _dev_state.
        self._dev_state = (
            tables_d, lengths, last, active_d, seeds_d, seed_d
        )
        out = np.asarray(out)  # [steps, B]
        # np.array (copy): asarray over a jax buffer is read-only, and
        # the slot bookkeeping writes these in place.
        self._lengths = np.array(lengths)
        self._last_tokens = np.array(last)
        active_slots = [
            (slot, seq) for slot, seq in enumerate(self._slots)
            if seq is not None and self._active[slot]
        ]
        for slot, seq in active_slots:
            self._record_tokens(seq, out[:, slot].tolist())

    # --- speculative decode (ISSUE 15) -------------------------------------

    def _spec_tick(self, now: float) -> None:
        """One speculative iteration: the DraftSource proposes up to
        spec_k tokens per active sequence (host-side, from the
        sequence's own history), their K/V is written into the paged
        cache, and ONE jitted verify pass evaluates all spec_k + 1
        positions — each position's pick replays the exact
        (seed, serial, position) schedule, so the accepted run plus the
        first correction token is byte-what the per-token path would
        have emitted. Rejected positions rewind host-side."""
        import jax.numpy as jnp

        K = self.ec.spec_k
        B = self.ec.max_slots
        drafts = np.zeros((B, K), np.int32)
        counts = np.zeros((B,), np.int32)
        for slot, seq in enumerate(self._slots):
            if seq is None or not self._active[slot]:
                continue
            cap = min(K, seq.remaining - 1)
            if cap > 0 and self._draft is not None:
                history = np.concatenate([
                    np.asarray(seq.req.prompt, np.int32),
                    np.asarray(seq.out, np.int32),
                ])
                d = np.asarray(
                    self._draft.propose(history, cap), np.int32
                ).ravel()[:cap]
                # In-vocab guard: a proposer echoing out-of-range ids
                # would index the embedding out of bounds; truncate at
                # the first bad token (later ones depend on it anyway).
                bad = np.flatnonzero(
                    (d < 0) | (d >= self.config.vocab_size)
                )
                if bad.size:
                    d = d[: int(bad[0])]
                drafts[slot, : len(d)] = d
                counts[slot] = len(d)
            L = int(self._lengths[slot])
            upto = L + int(counts[slot]) + 1
            self._cow_range(seq, L, upto)
            self._ensure_pages(seq, upto)
        if self._dev_state is None:
            self._dev_state = (
                self._put_row(self._tables),
                self._put_row(self._lengths),
                self._put_row(self._last_tokens),
                self._put_row(self._active),
                self._put_row(self._seeds),
                jnp.asarray(self._seed_scalar),
            )
        tables_d, lengths_d, last_d, active_d, seeds_d, seed_d = (
            self._dev_state
        )
        self.cache, new_len, new_last, n_acc, picked = (
            self._verify_chunk_fn(
                self.params, self.cache, tables_d, lengths_d, last_d,
                jnp.asarray(drafts), jnp.asarray(counts), active_d,
                seeds_d, seed_d,
            )
        )
        # Verified lengths/last tokens ARE next iteration's inputs:
        # keep them device-resident like the fused chunk does.
        self._dev_state = (
            tables_d, new_len, new_last, active_d, seeds_d, seed_d
        )
        n_acc_h = np.asarray(n_acc)
        picked_h = np.asarray(picked)
        prev_len = self._lengths.copy()
        self._lengths = np.array(new_len)
        self._last_tokens = np.array(new_last)
        active_slots = [
            (slot, seq) for slot, seq in enumerate(self._slots)
            if seq is not None and self._active[slot]
        ]
        for slot, seq in active_slots:
            na = int(n_acc_h[slot])
            npp = int(counts[slot])
            self.spec_proposed += npp
            self.spec_accepted += na
            if npp:
                self._inc("engine_spec_proposed_total", npp)
            if na:
                self._inc("engine_spec_accepted_total", na)
            valid = int(prev_len[slot]) + na + 1
            written = int(prev_len[slot]) + npp + 1
            self._record_tokens(seq, picked_h[slot, : na + 1].tolist())
            if seq.slot is not None and written > valid:
                self._rewind(seq, valid, written)

    def _rewind(self, seq: _Sequence, valid_len: int,
                written_len: int) -> None:
        """Host-side speculative rewind: the verify pass wrote K/V at
        positions [valid_len, written_len) that the acceptance rule
        rejected. Pages wholly past the accepted extent roll out of the
        block table and free (the batch zero path re-establishes their
        invariant before reuse, and they re-enter the sequence's
        worst-case reservation); the kept boundary page's rejected tail
        is re-zeroed in place."""
        from tpu_dra.workloads import paged_kv

        page = self.ec.page_size
        keep = -(-valid_len // page)
        dropped = seq.pages[keep:]
        if dropped:
            seq.pages = seq.pages[:keep]
            if self.ec.contiguous:
                self._pending_zero.extend(dropped)
            else:
                for pg in dropped:
                    if self.allocator.decref(pg):
                        self._pending_zero.append(pg)
                # Infallible BY CONSTRUCTION: every dropped page was
                # private (the verify pass only writes COW-forked
                # pages) and was just freed above, so the headroom
                # exists. Failing silently here would let a later
                # _alloc_page steal another admitted sequence's
                # reserved headroom — make any regression loud.
                if not self.allocator.reserve(len(dropped)):
                    raise RuntimeError(
                        f"rewind of {seq.req.rid} could not restore "
                        f"{len(dropped)} reserved pages — a dropped "
                        f"page was not freed (shared page in the "
                        f"rejected extent?)"
                    )
                seq.reserved_left += len(dropped)
            self._tables[seq.slot, keep:] = paged_kv.SCRATCH_PAGE
            self._dev_state = None
        off = valid_len % page
        if off and written_len > valid_len:
            self.cache = paged_kv.zero_page_tail(
                self.cache, seq.pages[keep - 1], off
            )

    def _record_tokens(self, seq: _Sequence, toks) -> None:
        # Clock read HERE, after the chunk's host sync (np.asarray /
        # logits fetch) — stamping the iteration's start time would hide
        # the chunk's own compute from every latency quantile.
        now = self.clock()
        take = min(len(toks), seq.remaining)
        if take <= 0:
            return
        if seq.t_first is None:
            seq.t_first = now
            if self.metrics is not None and not seq.req.ttft_preobserved:
                # First-token latency from ARRIVAL, same definition as
                # Completion.ttft_s (ISSUE 11): the router's SLO
                # classes and the fabric bench leg consume TTFT as a
                # first-class exported series, not only a per-request
                # field. Same-engine drains never re-observe (t_first
                # survives the drain); a CROSS-replica resume arrives
                # as a new Request with ttft_preobserved set by the
                # router when the first token already happened
                # elsewhere.
                self.metrics.observe(
                    "engine_ttft_seconds",
                    now - (seq.t_submit + seq.req.arrival_s),
                )
        seq.out.extend(int(t) for t in toks[:take])
        self._progress += 1
        self._inc("engine_tokens_total", take)
        if seq.remaining == 0:
            self._finish(seq, now)

    def _finish(self, seq: _Sequence, now: float) -> None:
        if seq.req.rid in self.completed:
            return
        self._release_slot(seq.slot)
        self.completed[seq.req.rid] = Completion(
            rid=seq.req.rid,
            tokens=np.asarray(seq.out, np.int32),
            t_submit=seq.t_submit,
            t_arrival=seq.t_submit + seq.req.arrival_s,
            t_first_token=seq.t_first if seq.t_first is not None else now,
            t_done=now,
        )
        self._inc("engine_completed_total")
        if self.metrics is not None:
            # Same definition as Completion.latency_s: from ARRIVAL —
            # the exported histogram and the bench quantiles must agree.
            self.metrics.observe(
                "engine_request_latency_seconds",
                now - (seq.t_submit + seq.req.arrival_s),
            )

    # --- metrics -----------------------------------------------------------

    def _inc(self, name: str, value: float = 1.0) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, value)

    def _export(self) -> None:
        if self.metrics is None:
            return
        m = self.metrics
        m.set_gauge(
            "engine_active_sequences",
            float(sum(1 for s in self._slots if s is not None)),
        )
        m.set_gauge("engine_queued_sequences", float(len(self._queue)))
        m.set_gauge(
            "engine_pages_free", float(self.allocator.free_pages)
        )
        stalled = (
            self.clock() - self._stalled_since
            if self._stalled_since is not None else 0.0
        )
        m.set_gauge("engine_admission_stalled", stalled)
        m.set_gauge(
            "engine_admission_blocked_on_pages",
            1.0 if self._blocked_on_pages else 0.0,
        )
        # Live prefix sharing: how many page allocations incref'd
        # tables are currently standing in for (0 when nothing shares).
        m.set_gauge(
            "engine_prefix_shared_pages", float(self._track_shared())
        )
        delta = self.allocator.exhausted - self._exhausted_exported
        if delta:
            m.inc("engine_page_exhausted_total", delta)
            self._exhausted_exported = self.allocator.exhausted


# --- traced forward (module-level so jit caches stay warm per engine) -------


def _decode_step(c, quant, sampling, params, cache, tables, lengths,
                 tokens, active, seeds, sample_seed):
    """One paged decode step for the whole slot batch. tokens/lengths/
    active/seeds: [B]; sample_seed: traced scalar. Inactive slots write
    to the scratch page and contribute exactly zero attention (length
    0); their token and length pass through unchanged. ``sampling`` is
    the static (temperature, top_k) pair or None for greedy; sampled
    tokens draw with a key folded as (seed, slot serial, position) so
    the fused scan, the unfused oracle, and a post-drain resume all
    agree per position."""
    import jax.numpy as jnp

    from tpu_dra.workloads.generate import (
        _finish_block,
        _mm,
        _project_qkv,
        _rms,
    )
    from tpu_dra.workloads.models.llama import rope_frequencies
    from tpu_dra.workloads.paged_kv import SCRATCH_PAGE, PagedKVCache
    from tpu_dra.workloads.ops.attention import paged_decode_attention
    from tpu_dra.workloads.quantize import quantize_kv

    B = tokens.shape[0]
    page = cache.page_size
    x = params["embed"]["embedding"].astype(c.dtype)[tokens][:, None, :]
    cos, sin = rope_frequencies(c, lengths[:, None])  # [B, 1, hd/2]
    pids = jnp.take_along_axis(
        tables, (lengths // page)[:, None], axis=1
    )[:, 0]
    offs = lengths % page
    # Masked writes land on the scratch page, never on a live table row.
    pids = jnp.where(active, pids, SCRATCH_PAGE)
    offs = jnp.where(active, offs, 0)
    len_eff = lengths + active.astype(lengths.dtype)

    k_pools, v_pools = list(cache.k), list(cache.v)
    ks_pools = list(cache.k_scale) if quant else [None] * c.n_layers
    vs_pools = list(cache.v_scale) if quant else [None] * c.n_layers
    for layer in range(c.n_layers):
        lp = params[f"layer_{layer}"]
        q, k, v = _project_qkv(c, lp, x, cos, sin, B, 1)
        k1, v1 = k[:, 0], v[:, 0]  # [B, kvh, hd]
        if quant:
            k1, ksc = quantize_kv(k1)
            v1, vsc = quantize_kv(v1)
            ks_pools[layer] = ks_pools[layer].at[pids, offs].set(ksc)
            vs_pools[layer] = vs_pools[layer].at[pids, offs].set(vsc)
        k_pools[layer] = k_pools[layer].at[pids, offs].set(k1)
        v_pools[layer] = v_pools[layer].at[pids, offs].set(v1)
        out = paged_decode_attention(
            q[:, 0], k_pools[layer], v_pools[layer], tables, len_eff,
            k_scale=ks_pools[layer], v_scale=vs_pools[layer],
            impl=c.paged_decode_impl,
        )[:, None].astype(c.dtype)
        x = _finish_block(c, lp, x, out, B, 1)
    x = _rms(x, params["final_norm"]["scale"], c.norm_eps)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)[:, 0]
    nxt = _pick_tokens(
        sampling, logits, seeds, len_eff, tokens.dtype, sample_seed
    )
    nxt = jnp.where(active, nxt, tokens)
    new_cache = PagedKVCache(
        k=tuple(k_pools), v=tuple(v_pools),
        k_scale=tuple(ks_pools) if quant else None,
        v_scale=tuple(vs_pools) if quant else None,
    )
    return new_cache, len_eff, nxt


def _pick_tokens(sampling, logits, seeds, positions, dtype, sample_seed):
    """Next-token choice for the whole slot batch: argmax (greedy) or
    the PR-2 fused sampler with per-slot position-folded keys. The token
    picked here will sit AT ``positions`` (= length after the current
    write), so its key is fold(fold(seed_key, serial), position) — the
    same key the prefill pick uses for the first generated token."""
    import jax
    import jax.numpy as jnp

    if sampling is None:
        return jnp.argmax(logits, axis=-1).astype(dtype)
    from tpu_dra.workloads.generate import sample_token

    temperature, top_k = sampling
    base = jax.random.PRNGKey(sample_seed)

    def one(lg, sd, pos):
        key = jax.random.fold_in(jax.random.fold_in(base, sd), pos)
        return sample_token(lg[None], key, temperature, top_k)[0]

    return jax.vmap(one)(logits, seeds, positions).astype(dtype)


def _decode_chunk(
    c, quant, sampling, params, cache, tables, lengths, tokens, active,
    seeds, sample_seed, *, steps
):
    """``steps`` decode steps as ONE jitted lax.scan — the fused chunk
    the engine admits/evicts between."""
    from jax import lax

    def step(carry, _):
        cache, lengths, toks = carry
        cache, lengths, toks = _decode_step(
            c, quant, sampling, params, cache, tables, lengths, toks,
            active, seeds, sample_seed,
        )
        return (cache, lengths, toks), toks

    (cache, lengths, toks), out = lax.scan(
        step, (cache, lengths, tokens), None, length=steps
    )
    return cache, lengths, toks, out  # out: [steps, B]


def _prefill_batch(c, quant, params, cache, tables, starts, tokens, valids):
    """One BATCHED prefill bucket (ISSUE 15): chunks from several
    sequences — one row per participating sequence, gathered by the
    host — written and attended in a single pass. tables: [B,
    max_pages]; starts/valids: [B] (valid 0 = idle pad row); tokens:
    [B, s]; both s and B are padded to power-of-two buckets (bounded
    trace-cache growth, and a sparse bucket pays ~its own row count,
    not max_slots). Pad positions and idle rows write to the
    scratch page and their outputs are never read: each query's output
    depends only on its own q row and its own table's written keys, so
    rows cannot pollute each other — per-row math is the same
    write-then-attend chunk the one-sequence path ran, which is what
    keeps batched prefill inside the engine's token-parity contract.
    Returns the cache and the last-VALID-position logits per row
    ([B, vocab]; only rows finishing their prefill consume them)."""
    import jax.numpy as jnp

    from tpu_dra.workloads.generate import _mm
    from tpu_dra.workloads.paged_kv import SCRATCH_PAGE

    B, s = tokens.shape
    page = cache.page_size
    positions = starts[:, None] + jnp.arange(s)[None]  # [B, s]
    in_valid = jnp.arange(s)[None] < valids[:, None]  # [B, s]
    safe_rows = jnp.minimum(positions // page, tables.shape[1] - 1)
    pids = jnp.where(
        in_valid, jnp.take_along_axis(tables, safe_rows, axis=1),
        SCRATCH_PAGE,
    )
    offs = jnp.where(in_valid, positions % page, 0)
    new_cache, x = _write_then_attend(
        c, quant, params, cache, tables, pids, offs, starts, tokens,
        positions,
    )
    # Last valid position per row (idle rows index position 0 — their
    # logits are never read).
    last_idx = jnp.maximum(valids - 1, 0)[:, None, None]
    x_last = jnp.take_along_axis(x, last_idx, axis=1)  # [B, 1, d]
    logits = _mm(x_last, params["lm_head"]).astype(jnp.float32)[:, 0]
    return new_cache, logits


def _write_then_attend(c, quant, params, cache, tables, pids, offs,
                       pos_q, toks, positions):
    """The shared write-then-attend body of :func:`_prefill_batch` and
    :func:`_verify_chunk`: embed ``toks`` [B, S], write every
    position's K/V (quantizing in flight) through the caller's
    (pids, offs) scatter, attend all S positions causally via
    paged_multiquery_attention with per-row chunk starts ``pos_q``,
    and return the updated cache plus the final-norm hidden states.
    ONE implementation — a change to the scatter/quantize/attend path
    cannot split the spec-vs-prefill token-parity contract."""
    from tpu_dra.workloads.generate import (
        _finish_block,
        _project_qkv,
        _rms,
    )
    from tpu_dra.workloads.models.llama import rope_frequencies
    from tpu_dra.workloads.paged_kv import PagedKVCache
    from tpu_dra.workloads.ops.attention import paged_multiquery_attention
    from tpu_dra.workloads.quantize import quantize_kv

    B, S = toks.shape
    x = params["embed"]["embedding"].astype(c.dtype)[toks]  # [B, S, d]
    cos, sin = rope_frequencies(c, positions)  # [B, S, hd/2]
    k_pools, v_pools = list(cache.k), list(cache.v)
    ks_pools = list(cache.k_scale) if quant else [None] * c.n_layers
    vs_pools = list(cache.v_scale) if quant else [None] * c.n_layers
    for layer in range(c.n_layers):
        lp = params[f"layer_{layer}"]
        q, k, v = _project_qkv(c, lp, x, cos, sin, B, S)
        k1, v1 = k, v  # [B, S, kvh, hd]
        if quant:
            k1, ksc = quantize_kv(k1)
            v1, vsc = quantize_kv(v1)
            ks_pools[layer] = ks_pools[layer].at[pids, offs].set(ksc)
            vs_pools[layer] = vs_pools[layer].at[pids, offs].set(vsc)
        k_pools[layer] = k_pools[layer].at[pids, offs].set(k1)
        v_pools[layer] = v_pools[layer].at[pids, offs].set(v1)
        out = paged_multiquery_attention(
            q, k_pools[layer], v_pools[layer], tables, pos_q,
            k_scale=ks_pools[layer], v_scale=vs_pools[layer],
        ).astype(c.dtype)
        x = _finish_block(c, lp, x, out, B, S)
    x = _rms(x, params["final_norm"]["scale"], c.norm_eps)
    new_cache = PagedKVCache(
        k=tuple(k_pools), v=tuple(v_pools),
        k_scale=tuple(ks_pools) if quant else None,
        v_scale=tuple(vs_pools) if quant else None,
    )
    return new_cache, x


def _verify_chunk(c, quant, sampling, params, cache, tables, lengths,
                  tokens, drafts, draft_count, active, seeds, sample_seed):
    """The speculative verify pass (ISSUE 15): ONE jitted evaluation of
    K+1 positions per sequence against the paged cache.

    tokens: [B] — each sequence's real last token (not yet written);
    drafts: [B, K] draft guesses (pad past draft_count); the pass
    writes K/V for [token, d_0, ..., d_{K-1}] at positions
    [L, L+K] (masked rows/pads go to scratch), attends all positions
    causally through the block tables in one paged_multiquery_attention
    call, and picks every position's next token with the exact
    (seed, serial, position) schedule. Acceptance is computed ON
    DEVICE: n_acc = longest prefix where pick[i] == draft[i], the
    emitted run is pick[0..n_acc] (accepted guesses + the first
    correction), new lengths/last tokens return as device arrays so a
    steady verify stretch re-uploads nothing. Exactness: pick[i] only
    depends on K/V at positions <= L+i, which hold REAL tokens
    whenever i <= n_acc — so the accepted run is byte-identical to
    what the unfused per-token oracle emits, greedy or sampled, no
    matter what the proposer guessed."""
    import jax.numpy as jnp

    from tpu_dra.workloads.generate import _mm
    from tpu_dra.workloads.paged_kv import SCRATCH_PAGE

    B, K = drafts.shape
    S = K + 1
    page = cache.page_size
    toks = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [B, S]
    positions = lengths[:, None] + jnp.arange(S)[None]  # [B, S]
    write_ok = active[:, None] & (
        jnp.arange(S)[None] < (draft_count + 1)[:, None]
    )
    safe_rows = jnp.minimum(positions // page, tables.shape[1] - 1)
    pids = jnp.where(
        write_ok, jnp.take_along_axis(tables, safe_rows, axis=1),
        SCRATCH_PAGE,
    )
    offs = jnp.where(write_ok, positions % page, 0)
    pos_q = jnp.where(active, lengths, 0)
    new_cache, x = _write_then_attend(
        c, quant, params, cache, tables, pids, offs, pos_q, toks,
        positions,
    )
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)  # [B, S, V]
    picked = _pick_tokens_batched(
        sampling, logits, seeds, positions + 1, tokens.dtype, sample_seed
    )  # [B, S]
    match = (picked[:, :K] == drafts) & (
        jnp.arange(K)[None] < draft_count[:, None]
    )
    n_acc = jnp.sum(
        jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
    )  # [B]
    new_len = jnp.where(active, lengths + 1 + n_acc, lengths)
    new_last = jnp.where(
        active,
        jnp.take_along_axis(picked, n_acc[:, None], axis=1)[:, 0],
        tokens,
    )
    return new_cache, new_len, new_last, n_acc, picked


def _pick_tokens_batched(sampling, logits, seeds, positions, dtype,
                         sample_seed):
    """:func:`_pick_tokens` over [B, S] positions at once — the verify
    pass's picks, vmapped over the position axis so the single-step
    path's fold(fold(seed_key, serial), position) schedule has exactly
    ONE definition and every position's pick is byte-identical to the
    per-token oracle's."""
    import jax

    def per_pos(lg, pos):  # lg: [B, V], pos: [B]
        return _pick_tokens(sampling, lg, seeds, pos, dtype, sample_seed)

    return jax.vmap(per_pos, in_axes=(1, 1), out_axes=1)(
        logits, positions
    )
