"""Ring attention: sequence/context parallelism over the ICI ring.

Long-context design (first-class per the framework goals): the sequence is
sharded over the ``sp`` mesh axis; each device holds one query chunk and
rotates the K/V chunks around the ring with ``lax.ppermute`` (XLA lowers
this to ICI neighbor exchanges), merging partial attention results with the
flash-style log-sum-exp accumulator. Memory per device is O(seq/sp), and
the K/V transfer overlaps with the attention compute of the previous chunk
(XLA schedules the ppermute asynchronously).

Causality: device ``i`` attends chunk ``j`` fully when ``j < i``, causally
when ``j == i``, and not at all when ``j > i`` — masked via NEG_INF so the
accumulator never sees those contributions. (The skipped work could be
load-balanced with a zig-zag chunk layout; kept simple for now.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpu_dra.workloads.jaxcompat import pcast, shard_map

from tpu_dra.workloads.ops import attention as attn_ops
from tpu_dra.workloads.ops.attention import (
    NEG_INF,
    _repeat_kv,
    flash_attention_with_lse,
)
from tpu_dra.workloads.parallel.context import sequence_parallel_plan

AXIS = "sp"


def _pick_block(s: int) -> int:
    for cand in (256, 128, 64):
        if s % cand == 0:
            return cand
    return 0


def _flash_ok(q, k) -> bool:
    """Use the pallas flash kernel for the per-chunk work when the local
    shapes qualify (and one KV head's chunk fits the VMEM the kernels
    pin per grid program)."""
    b, sq, h, hd = q.shape
    return (
        attn_ops.flash_platform_ok()
        and hd % 64 == 0
        and _pick_block(sq) > 0
        and attn_ops.flash_vmem_ok(k)
    )


def _partial_attention(q, k, v, mode, m, l, acc):
    """One chunk pair; mode: 0=full, 1=causal-diagonal, 2=skip.

    q: [b, sq, h, hd]; k/v: [b, sk, h, hd]; m/l: [b, h, sq]; acc like q
    but fp32. Returns merged (m, l, acc).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    sq, sk = q.shape[1], k.shape[1]
    causal_mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
    mask = jax.lax.switch(
        mode,
        [
            lambda: jnp.ones((sq, sk), dtype=bool),
            lambda: causal_mask,
            lambda: jnp.zeros((sq, sk), dtype=bool),
        ],
    )
    s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def _flash_chunk(q, k_cur, v_cur, mode, lse, acc):
    """One chunk pair through the pallas flash kernel; partials merge by
    logsumexp (each flash output is already normalized, so the merged
    accumulator needs no final division)."""
    bq, bk = _pick_block(q.shape[1]), _pick_block(k_cur.shape[1])

    def full(q, k, v):
        return flash_attention_with_lse(q, k, v, False, bq, bk)

    def diag(q, k, v):
        return flash_attention_with_lse(q, k, v, True, bq, bk)

    def skip(q, k, v):
        b, sq, h, hd = q.shape
        return (
            jnp.zeros(q.shape, q.dtype),
            jnp.full((b, h, sq), NEG_INF, dtype=jnp.float32),
        )

    out_c, lse_c = jax.lax.switch(mode, [full, diag, skip], q, k_cur, v_cur)
    new_lse = jnp.logaddexp(lse, lse_c)
    w_prev = jnp.exp(lse - new_lse).transpose(0, 2, 1)[..., None]
    w_cur = jnp.exp(lse_c - new_lse).transpose(0, 2, 1)[..., None]
    acc = acc * w_prev + out_c.astype(jnp.float32) * w_cur
    return new_lse, acc


def _ring_attention_local(q, k, v, *, axis_name: str, vary_axes: tuple):
    """Body running per-device under shard_map; q/k/v are local chunks.

    Per-chunk attention runs the pallas flash kernel on TPU (no
    s_local × s_local logits materialization — the point of ring attention
    is that s_local is big) with logsumexp-weighted merging; off-TPU or on
    non-qualifying shapes it runs the XLA online-softmax path."""
    n = jax.lax.psum(1, axis_name)
    i = jax.lax.axis_index(axis_name)
    b, sq, h, hd = q.shape
    use_flash = _flash_ok(q, k)

    # Mark the accumulators device-varying so the fori_loop carry types are
    # consistent with the (varying) K/V they merge with under shard_map.
    vary = lambda x: pcast(x, vary_axes, to="varying")  # noqa: E731
    acc0 = vary(jnp.zeros((b, sq, h, hd), dtype=jnp.float32))
    lse0 = vary(jnp.full((b, h, sq), NEG_INF, dtype=jnp.float32))
    l0 = vary(jnp.zeros((b, h, sq), dtype=jnp.float32))

    n_rep = h // k.shape[2]

    def body(t, carry):
        k_cur, v_cur, m, l, acc = carry
        j = (i - t) % n  # chunk id currently held
        mode = jnp.where(j < i, 0, jnp.where(j == i, 1, 2))
        if use_flash:
            # GQA is native to the kernel: K/V stay at kvh heads, so the
            # ring moves (and each device holds) n_rep x fewer K/V bytes.
            m, acc = _flash_chunk(q, k_cur, v_cur, mode, m, acc)
        else:
            m, l, acc = _partial_attention(
                q, _repeat_kv(k_cur, n_rep), _repeat_kv(v_cur, n_rep),
                mode, m, l, acc,
            )
        # Rotate K/V to the next device; after this, we hold chunk (j-1)%n.
        perm = [(s, (s + 1) % n) for s in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(0, n, body, (k, v, lse0, l0, acc0))
    if use_flash:
        out = acc  # flash partials are pre-normalized; weights sum to 1
    else:
        out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS,
    mesh=None,
) -> jnp.ndarray:
    """Causal ring attention; q [b, s, h, hd] with s sharded over ``sp``.

    Falls back to single-device attention when no mesh is active or the
    ``sp`` axis is trivial.
    """
    plan = sequence_parallel_plan(axis_name, mesh)
    if plan is None:
        from tpu_dra.workloads.ops.attention import attention

        return attention(q, k, v, causal=True)
    mesh, spec, batch_axes = plan
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            vary_axes=batch_axes + (axis_name,),
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call outputs can't declare their varying axes, which
        # check_vma would demand of the flash path.
        check_vma=False,
    )
    return fn(q, k, v)
