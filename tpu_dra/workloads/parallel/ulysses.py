"""Ulysses-style all-to-all sequence parallelism.

The complement of ring attention (ring_attention.py) on the long-context
axis: instead of rotating K/V chunks around the ICI ring, one
``lax.all_to_all`` re-shards the activations from sequence-sharded to
head-sharded, full-sequence attention runs locally per head group (so the
flash/pallas kernel applies unchanged), and a second all_to_all restores
sequence sharding. Two collectives per layer of O(b*s*h*d/n) each, vs the
ring's n ppermute steps — all_to_all wins when heads divide evenly and the
interconnect handles the transpose well (TPU ICI does); the ring wins at
very long sequences where even head-sharded full-sequence scores blow HBM.

Constraint: n_heads (after GQA expansion) must be divisible by the ``sp``
axis size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpu_dra.workloads.jaxcompat import shard_map
from tpu_dra.workloads.ops.attention import _repeat_kv, attention
from tpu_dra.workloads.parallel.context import sequence_parallel_plan

AXIS = "sp"


def _ulysses_local(q, k, v, *, axis_name: str):
    """Per-device body: [b, s/n, h, hd] -> attention -> [b, s/n, h, hd]."""
    # seq-sharded -> head-sharded: split heads (axis 2), gather seq (axis 1).
    q = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = attention(q, k, v, causal=True)
    # head-sharded -> seq-sharded.
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS,
    mesh=None,
) -> jnp.ndarray:
    """Causal attention with all-to-all sequence parallelism; q [b, s, h, hd]
    with s sharded over ``sp``. Falls back to single-device attention when
    no mesh is active or the axis is trivial."""
    plan = sequence_parallel_plan(axis_name, mesh)
    if plan is None:
        return attention(q, k, v, causal=True)
    mesh, spec, batch_axes = plan
    n = mesh.shape[axis_name]
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses attention needs n_heads ({q.shape[2]}) divisible by "
            f"the {axis_name} axis ({n})"
        )
    if k.shape[2] % n:
        # KV heads don't split evenly: materialize the GQA repeat up front.
        # Costs n_rep in collective volume — only the fallback.
        n_rep = q.shape[2] // k.shape[2]
        k = _repeat_kv(k, n_rep)
        v = _repeat_kv(v, n_rep)
    # else: exchange the un-repeated K/V (kvh/n heads per device) and let
    # the local attention resolve GQA by logical head grouping — n_rep x
    # less collective volume and no materialized repeat.
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
