"""Device-mesh construction and sharding rules.

TPU-first design: scale comes from ``jax.sharding.Mesh`` + NamedSharding
with XLA-inserted collectives (psum / all-gather / reduce-scatter /
ppermute over ICI) — never hand-written point-to-point sends. Axes:

- ``pp``   — pipeline parallelism (layer stages; ppermute microbatch relay)
- ``dp``   — pure data parallelism (replicated params; gradients psum)
- ``fsdp`` — data parallelism with fully-sharded params (params/optimizer
  sharded over this axis; all-gathered per layer)
- ``ep``   — expert parallelism (MoE expert dim; all-to-all dispatch)
- ``sp``   — sequence/context parallelism (ring attention over ICI)
- ``tp``   — tensor parallelism (heads / MLP hidden sharded)

Layout matters: ``tp`` innermost so its collectives ride the
fastest-varying ICI dimension; ``pp``/``dp`` outermost so cross-slice
(DCN) traffic is stage-boundary/gradient-only (the scaling-book recipe);
``ep`` sits between — its all-to-alls stay on-slice ICI.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger(__name__)

AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.sp * self.tp * self.ep * self.pp

    @classmethod
    def for_device_count(cls, n: int) -> "MeshConfig":
        """A sensible default factorization: prefer fsdp, then tp, then sp.

        Single-host v5e-4 -> fsdp=4; v5p-16 (8 chips) -> fsdp=4, tp=2;
        32 chips -> fsdp=8, tp=4 — callers with topology knowledge should
        pick explicitly instead.
        """
        if n <= 0:
            raise ValueError("need at least one device")
        tp = 1
        for cand in (4, 2):
            # Only give tp a slice of the mesh when enough devices remain
            # for a meaningful fsdp group (n strictly above cand^2).
            if n % cand == 0 and n > cand * cand:
                tp = cand
                break
        return cls(fsdp=n // tp, tp=tp)


def build_mesh(config: MeshConfig, devices: Optional[List] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if config.size != len(devices):
        raise ValueError(
            f"mesh config {config} needs {config.size} devices, have "
            f"{len(devices)}"
        )
    arr = np.array(devices).reshape(
        config.pp, config.dp, config.fsdp, config.ep, config.sp, config.tp
    )
    return Mesh(arr, AXES)


# --- sharding rules ---------------------------------------------------------

# Parameter path (regex) -> PartitionSpec. Weights shard the contraction/
# feature dims over fsdp and the parallel dims (heads, ffn hidden, vocab)
# over tp. Biases/norms replicate.
PARAM_RULES: List[Tuple[str, P]] = [
    # MoE expert weights carry a leading expert dim sharded over ep
    # (matched before the generic w_gate/w_up/w_down rules).
    (r".*experts.*(w_gate|w_up)$", P("ep", "fsdp", "tp")),  # [E, d, ffn]
    (r".*experts.*w_down$", P("ep", "tp", "fsdp")),  # [E, ffn, d]
    (r".*router.*kernel$", P("fsdp", None)),  # [d, E]
    (r".*embed.*embedding$", P("tp", "fsdp")),  # [vocab, d]
    (r".*(wq|wk|wv).*kernel$", P("fsdp", "tp")),  # [d, heads*hd]
    (r".*wo.*kernel$", P("tp", "fsdp")),  # [heads*hd, d]
    (r".*(w_gate|w_up).*kernel$", P("fsdp", "tp")),  # [d, ffn]
    (r".*w_down.*kernel$", P("tp", "fsdp")),  # [ffn, d]
    (r".*lm_head.*kernel$", P("fsdp", "tp")),  # [d, vocab]
    (r".*(norm|scale).*", P()),  # replicated
]


def param_spec(path: str, value=None) -> P:
    # int8 weight-only trees (workloads/quantize.py) replace each
    # {"kernel"} with {"kernel_q", "scale"}: the quantized kernel takes
    # the plain kernel's sharding (same [in, out] layout); the small
    # per-channel scale falls through to replicated.
    path = re.sub(r"/kernel_q$", "/kernel", path)
    for pattern, spec in PARAM_RULES:
        if re.fullmatch(pattern, path):
            # Scanned layers carry extra leading dims (layer stack, and/or
            # pipeline stage); pad the spec with Nones to match rank.
            if value is not None and hasattr(value, "ndim") and value.ndim > len(spec):
                return P(*([None] * (value.ndim - len(spec))), *spec)
            return spec
    return P()


def _flatten_path(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def param_shardings(mesh: Mesh, params) -> "jax.tree_util.PyTreeDef":
    """NamedSharding tree for a params pytree by path rules."""

    def to_sharding(path, value):
        return NamedSharding(mesh, param_spec(_flatten_path(path), value))

    return jax.tree_util.tree_map_with_path(to_sharding, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
