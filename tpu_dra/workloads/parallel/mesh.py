"""Device-mesh construction and sharding rules.

TPU-first design: scale comes from ``jax.sharding.Mesh`` + NamedSharding
with XLA-inserted collectives (psum / all-gather / reduce-scatter /
ppermute over ICI) — never hand-written point-to-point sends. Axes:

- ``pp``   — pipeline parallelism (layer stages; ppermute microbatch relay)
- ``dp``   — pure data parallelism (replicated params; gradients psum)
- ``fsdp`` — data parallelism with fully-sharded params (params/optimizer
  sharded over this axis; all-gathered per layer)
- ``ep``   — expert parallelism (MoE expert dim; all-to-all dispatch)
- ``sp``   — sequence/context parallelism (ring attention over ICI)
- ``tp``   — tensor parallelism (heads / MLP hidden sharded)

Layout matters: ``tp`` innermost so its collectives ride the
fastest-varying ICI dimension; ``pp``/``dp`` outermost so cross-slice
(DCN) traffic is stage-boundary/gradient-only (the scaling-book recipe);
``ep`` sits between — its all-to-alls stay on-slice ICI.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger(__name__)

AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.sp * self.tp * self.ep * self.pp

    @classmethod
    def for_device_count(cls, n: int) -> "MeshConfig":
        """A sensible default factorization: prefer fsdp, then tp, then sp.

        Single-host v5e-4 -> fsdp=4; v5p-16 (8 chips) -> fsdp=4, tp=2;
        32 chips -> fsdp=8, tp=4 — callers with topology knowledge should
        pick explicitly instead.
        """
        if n <= 0:
            raise ValueError("need at least one device")
        tp = 1
        for cand in (4, 2):
            # Only give tp a slice of the mesh when enough devices remain
            # for a meaningful fsdp group (n strictly above cand^2).
            if n % cand == 0 and n > cand * cand:
                tp = cand
                break
        return cls(fsdp=n // tp, tp=tp)


def build_mesh(config: MeshConfig, devices: Optional[List] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if config.size != len(devices):
        raise ValueError(
            f"mesh config {config} needs {config.size} devices, have "
            f"{len(devices)}"
        )
    arr = np.array(devices).reshape(
        config.pp, config.dp, config.fsdp, config.ep, config.sp, config.tp
    )
    return Mesh(arr, AXES)


# --- sharding rules ---------------------------------------------------------

# Parameter path (regex) -> PartitionSpec. Weights shard the contraction/
# feature dims over fsdp and the parallel dims (heads, ffn hidden, vocab)
# over tp. Biases/norms replicate.
PARAM_RULES: List[Tuple[str, P]] = [
    # MoE expert weights carry a leading expert dim sharded over ep
    # (matched before the generic w_gate/w_up/w_down rules).
    (r".*experts.*(w_gate|w_up)$", P("ep", "fsdp", "tp")),  # [E, d, ffn]
    (r".*experts.*w_down$", P("ep", "tp", "fsdp")),  # [E, ffn, d]
    (r".*router.*kernel$", P("fsdp", None)),  # [d, E]
    (r".*embed.*embedding$", P("tp", "fsdp")),  # [vocab, d]
    (r".*(wq|wk|wv).*kernel$", P("fsdp", "tp")),  # [d, heads*hd]
    (r".*wo.*kernel$", P("tp", "fsdp")),  # [heads*hd, d]
    (r".*(w_gate|w_up).*kernel$", P("fsdp", "tp")),  # [d, ffn]
    (r".*w_down.*kernel$", P("tp", "fsdp")),  # [ffn, d]
    (r".*lm_head.*kernel$", P("fsdp", "tp")),  # [d, vocab]
    (r".*(norm|scale).*", P()),  # replicated
]


def param_spec(path: str, value=None) -> P:
    # int8 weight-only trees (workloads/quantize.py) replace each
    # {"kernel"} with {"kernel_q", "scale"}: the quantized kernel takes
    # the plain kernel's sharding (same [in, out] layout); the small
    # per-channel scale falls through to replicated.
    path = re.sub(r"/kernel_q$", "/kernel", path)
    for pattern, spec in PARAM_RULES:
        if re.fullmatch(pattern, path):
            # Scanned layers carry extra leading dims (layer stack, and/or
            # pipeline stage); pad the spec with Nones to match rank.
            if value is not None and hasattr(value, "ndim") and value.ndim > len(spec):
                return P(*([None] * (value.ndim - len(spec))), *spec)
            return spec
    return P()


def _flatten_path(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def param_shardings(mesh: Mesh, params) -> "jax.tree_util.PyTreeDef":
    """NamedSharding tree for a params pytree by path rules."""

    def to_sharding(path, value):
        return NamedSharding(mesh, param_spec(_flatten_path(path), value))

    return jax.tree_util.tree_map_with_path(to_sharding, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# --- decode mesh (GSPMD named sharding for the serving path) -----------------
#
# The SNIPPETS [3] pattern: a logical 2-D (batch x model) mesh +
# NamedSharding annotations, jit/GSPMD inserting the collectives. The
# serving engine decodes the SAME program sharded across every chip a
# ComputeDomain's rendered env exposes (jax.devices() reflects
# TPU_VISIBLE_DEVICES / TPU_PROCESS_BOUNDS after CDI injection), and the
# shape ladder degrades gracefully to (1, 1) on a single chip — one code
# path from a 1-chip sub-slice claim to a full multi-chip domain.
#
# EXACTNESS CONTRACT: sharded decode must be TOKEN-IDENTICAL to
# single-chip decode (the shardbench gate), so the model axis shards only
# NON-CONTRACTED dimensions — column-parallel wq/wk/wv (heads), w_gate/
# w_up (ffn), lm_head (vocab), and the KV pools' kv-head axis. Every
# output element is still one full-length dot product; no psum ever
# splits a contraction, so fp32 summation order — and therefore every
# argmax — is bit-identical to the unsharded program. wo/w_down stay
# replicated (row-parallel sharding WOULD split their contractions);
# their inputs arrive via GSPMD all-gathers instead. The win is the
# sharded read of qkv+gate+up+lm_head — the bulk of per-step weight
# bytes — plus KV pools split over kv heads.

DECODE_AXES = ("batch", "model")

DECODE_PARAM_RULES: List[Tuple[str, P]] = [
    (r".*(wq|wk|wv).*kernel$", P(None, "model")),  # [d, heads*hd]
    (r".*(w_gate|w_up).*kernel$", P(None, "model")),  # [d, ffn]
    (r".*lm_head.*kernel$", P(None, "model")),  # [d, vocab]
    # wo / w_down / embed / norms / quant scales: replicated (see the
    # exactness contract above).
]


def decode_mesh_shape(n_devices: int, config=None) -> Tuple[int, int]:
    """(batch, model) axis sizes for ``n_devices`` chips: the SNIPPETS
    [3] ladder — (2, n/2) at 8+, (2, 2) at 4, (1, 2) at 2, (1, 1) on a
    single chip — with the model axis clamped down (largest value that
    still divides the device count AND every dimension it shards — kv
    heads, ffn, vocab — remainder folded into batch) so the sharding
    rules above always apply cleanly and no device goes idle. Stepping
    by 1 rather than halving matters on non-power-of-2 ladders: 12
    devices with 8 kv heads must land on (3, 4), not collapse through
    6 -> 3 -> 1 into a batch-only mesh."""
    if n_devices >= 8:
        b_axis, m_axis = 2, n_devices // 2
    elif n_devices >= 4:
        b_axis, m_axis = 2, 2
    elif n_devices >= 2:
        b_axis, m_axis = 1, 2
    else:
        b_axis, m_axis = 1, 1
    if config is not None:
        while m_axis > 1 and (
            n_devices % m_axis
            or config.n_kv_heads % m_axis
            or config.ffn_dim % m_axis
            or config.vocab_size % m_axis
        ):
            m_axis -= 1
        b_axis = n_devices // m_axis
    return b_axis, m_axis


def build_decode_mesh(config=None, devices: Optional[List] = None) -> Mesh:
    """(batch x model) decode mesh over the chips the rendered env
    exposes (ComputeDomain -> jax.devices()); shapes that don't tile the
    device count use the largest usable prefix."""
    devices = devices if devices is not None else jax.devices()
    b_axis, m_axis = decode_mesh_shape(len(devices), config)
    arr = np.array(devices[: b_axis * m_axis]).reshape(b_axis, m_axis)
    return Mesh(arr, DECODE_AXES)


def sharded_safe_config(config, mesh: Mesh):
    """Config adjusted for decode under GSPMD: when the mesh spans more
    than one device, force the XLA implementations of the pallas-capable
    decode ops. pallas custom calls carry no SPMD partitioning rule —
    under a real multi-device mesh XLA would replicate them, inserting
    per-step all-gathers of exactly the weight/KV shards the mesh
    splits (or fail to lower outright). On a (1, 1) mesh the config
    passes through unchanged, so single-chip runs keep the kernels."""
    import dataclasses

    if mesh.devices.size <= 1:
        return config
    # attention_impl covers the prefill/training forward too: the
    # decode paths in this repo never auto-pick the flash kernel, but a
    # model forward over decode-sharded params would — same no-SPMD-rule
    # hazard, same fix.
    return dataclasses.replace(
        config,
        attention_impl="xla",
        decode_impl="xla",
        decode_mlp_impl="xla",
        paged_decode_impl="xla",
    )


def decode_param_spec(path: str, value=None) -> P:
    """Decode-mesh PartitionSpec for one param leaf by path (int8
    weight-only ``kernel_q`` leaves take their plain kernel's spec; the
    tiny per-channel scales replicate)."""
    path = re.sub(r"/kernel_q$", "/kernel", path)
    for pattern, spec in DECODE_PARAM_RULES:
        if re.fullmatch(pattern, path):
            if (
                value is not None
                and hasattr(value, "ndim")
                and value.ndim > len(spec)
            ):
                return P(*([None] * (value.ndim - len(spec))), *spec)
            return spec
    return P()


def decode_param_shardings(mesh: Mesh, params):
    """NamedSharding tree for a decode param pytree (either layout)."""

    def to_sharding(path, value):
        return NamedSharding(mesh, decode_param_spec(_flatten_path(path), value))

    return jax.tree_util.tree_map_with_path(to_sharding, params)


def shard_decode_params(mesh: Mesh, params):
    """device_put the tree with the decode shardings (the one-call
    entry bench.py / shardbench use)."""
    return jax.device_put(params, decode_param_shardings(mesh, params))


def decode_data_sharding(mesh: Mesh, batch: int) -> NamedSharding:
    """Sharding for batch-leading decode arrays (tokens, lengths, block
    tables, active masks, q rows): split over the batch axis when it
    tiles evenly, replicated otherwise (graceful degradation — an odd
    slot count still runs)."""
    spec = P("batch") if batch % mesh.shape["batch"] == 0 else P()
    return NamedSharding(mesh, spec)


def decode_pool_sharding(
    mesh: Mesh, kv_heads: int, ndim: int
) -> NamedSharding:
    """Sharding for paged KV pools ([P, page, kvh, hd] values, [P, page,
    kvh] scales): kv-head axis over the model axis — exact (heads are
    independent until the replicated wo) — replicated when kvh doesn't
    tile."""
    if kv_heads % mesh.shape["model"] == 0:
        spec = (
            P(None, None, "model", None) if ndim == 4
            else P(None, None, "model")
        )
    else:
        spec = P()
    return NamedSharding(mesh, spec)
