"""Pipeline parallelism: GPipe-style microbatch relay over the ``pp`` axis.

Reference framing: the reference driver's scale-out axis is the
ComputeDomain (SURVEY.md §2.5); the workloads that run on DRA-allocated
slices need every sharding family, and pipeline parallelism is the one
that spans slices cheapest — only stage-boundary activations cross the
``pp`` axis, so ``pp`` maps naturally onto DCN between ICI slices
(mesh.py puts ``pp`` outermost for exactly this reason).

TPU-first design:

- **One program, jit-compiled**: the schedule is a ``lax.scan`` over
  ``n_microbatches + pp - 1`` ticks inside a ``shard_map`` over ``pp`` —
  no per-stage processes, no host-side orchestration, fully
  differentiable (the backward pass is the mirrored pipeline XLA derives
  from the scan/ppermute transpose).
- **Stage hand-off = ``lax.ppermute``**: a single collective-permute per
  tick rides the ICI/DCN ring; no send/recv programming model.
- **Static shapes**: bubble ticks run the stage on zeros (the standard
  GPipe trade) so every tick is the same XLA program.

``stage_fn`` must preserve the shape/dtype of its input block (true for
transformer layer stacks).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dra.workloads.jaxcompat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def partition_stages(layer_params: Any, n_stages: int) -> Any:
    """Reshape a scanned-layer param tree ``[L, ...]`` into stage-major
    ``[n_stages, L/n_stages, ...]`` (leading dim shardable over ``pp``)."""

    def reshape(a):
        if a.shape[0] % n_stages:
            raise ValueError(
                f"layer count {a.shape[0]} not divisible by {n_stages} stages"
            )
        return a.reshape(n_stages, a.shape[0] // n_stages, *a.shape[1:])

    return jax.tree_util.tree_map(reshape, layer_params)


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x: jnp.ndarray,
    *extra: Any,
    mesh: Mesh,
    axis: str = "pp",
    n_microbatches: int,
) -> jnp.ndarray:
    """Run ``x`` through ``pp`` pipelined stages of ``stage_fn``.

    - ``stage_params``: pytree with leading ``[pp, ...]`` stage dim (see
      :func:`partition_stages`); sharded over ``axis``.
    - ``x``: ``[batch, ...]`` input; split into ``n_microbatches`` along
      batch. ``batch % n_microbatches == 0``.
    - ``extra``: stage-invariant side inputs (e.g. RoPE tables),
      replicated.

    Returns ``stage_fn`` applied by every stage in sequence, microbatch-
    pipelined: tick ``t`` has stage ``i`` working microbatch ``t - i``
    while ``lax.ppermute`` relays activations around the stage ring.
    """
    pp = mesh.shape[axis]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by {n_microbatches} microbatches"
        )
    mb = batch // n_microbatches
    xs = x.reshape(n_microbatches, mb, *x.shape[1:])

    params_specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    n_steps = n_microbatches + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(params_specs, P(None)) + tuple(P(None) for _ in extra),
        out_specs=P(None),
        check_vma=False,
    )
    def run(sp, xs, *extra):
        # Each shard holds one stage: squeeze the local stage dim.
        sp = jax.tree_util.tree_map(lambda a: a[0], sp)
        idx = lax.axis_index(axis)

        def body(carry, t):
            state, outs = carry
            # Stage 0 feeds microbatch t (zeros in the drain bubble);
            # later stages consume the relayed activation.
            x_t = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, n_microbatches - 1), 0, keepdims=False
            )
            fed = jnp.where(t < n_microbatches, x_t, jnp.zeros_like(x_t))
            inp = jnp.where(idx == 0, fed, state)
            y = stage_fn(sp, inp, *extra)
            # The last stage finishes microbatch t-(pp-1) at tick t.
            out_t = t - (pp - 1)
            slot = jnp.clip(out_t, 0, n_microbatches - 1)
            cur = lax.dynamic_index_in_dim(outs, slot, 0, keepdims=False)
            done = (idx == pp - 1) & (out_t >= 0) & (out_t < n_microbatches)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(done, y, cur), slot, 0
            )
            state = lax.ppermute(y, axis, perm)
            return (state, outs), None

        init = (jnp.zeros_like(xs[0]), jnp.zeros_like(xs))
        (_, outs), _ = lax.scan(body, init, jnp.arange(n_steps))
        # Only the last stage holds real outputs; broadcast to all.
        return lax.psum(
            jnp.where(idx == pp - 1, outs, jnp.zeros_like(outs)), axis
        )

    out = run(stage_params, xs, *extra)
    return out.reshape(batch, *x.shape[1:])


def pipelined_llama_forward(
    config,
    params,
    tokens: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pp",
    n_microbatches: int,
) -> jnp.ndarray:
    """Llama forward with the decoder stack pipelined over ``axis``.

    Numerically identical to ``Llama(config).apply`` (same modules, same
    order); requires ``config.scan_layers`` (the stacked ``[L, ...]``
    layer params are re-cut into ``pp`` stages) and
    ``config.n_layers % pp == 0``.
    """
    import flax.linen as nn

    from tpu_dra.workloads.models.llama import (
        LlamaBlock,
        RMSNorm,
        rope_frequencies,
    )

    c = config
    if not c.scan_layers:
        raise ValueError("pipelined forward needs scan_layers=True")
    pp = mesh.shape[axis]

    x = nn.Embed(
        c.vocab_size, c.dim, dtype=c.dtype, param_dtype=c.param_dtype
    ).apply({"params": params["embed"]}, tokens)
    cos, sin = rope_frequencies(c, jnp.arange(tokens.shape[1]))

    stage_params = partition_stages(params["layers"]["block"], pp)

    def stage_fn(sp, x, cos, sin):
        def body(x, layer_params):
            y = LlamaBlock(c).apply({"params": layer_params}, x, cos, sin)
            return y, None

        x, _ = lax.scan(body, x, sp)
        return x

    x = pipeline_apply(
        stage_fn,
        stage_params,
        x,
        cos,
        sin,
        mesh=mesh,
        axis=axis,
        n_microbatches=n_microbatches,
    )

    x = RMSNorm(c.norm_eps, c.param_dtype).apply(
        {"params": params["final_norm"]}, x
    )
    logits = nn.Dense(
        c.vocab_size, use_bias=False, dtype=c.dtype, param_dtype=c.param_dtype
    ).apply({"params": params["lm_head"]}, x)
    return logits.astype(jnp.float32)
