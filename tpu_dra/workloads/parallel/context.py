"""Global mesh context.

The active :class:`jax.sharding.Mesh` is process-global state (one mesh per
training job); ring attention and other shard_map-based ops look it up here
instead of threading it through every model module.
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

_mesh: Optional[Mesh] = None


def set_global_mesh(mesh: Optional[Mesh]) -> None:
    global _mesh
    _mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _mesh
