"""Global mesh context.

The active :class:`jax.sharding.Mesh` is process-global state (one mesh per
training job); ring attention and other shard_map-based ops look it up here
instead of threading it through every model module.
"""

from __future__ import annotations

from typing import Optional, Tuple

from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

_mesh: Optional[Mesh] = None


def set_global_mesh(mesh: Optional[Mesh]) -> None:
    global _mesh
    _mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _mesh


def sequence_parallel_plan(
    axis_name: str, mesh: Optional[Mesh] = None
) -> Optional[Tuple[Mesh, P, Tuple[str, ...]]]:
    """Shared preamble for the sequence-parallel attention impls (ring,
    ulysses): resolve the active mesh and build the [batch, seq, head, dim]
    partition spec. Returns None when no mesh is active or the axis is
    trivial — the caller falls back to single-device attention."""
    mesh = mesh or get_global_mesh()
    if mesh is None or axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        return None
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.shape)
    spec = P(batch_axes or None, axis_name, None, None)
    return mesh, spec, batch_axes
