"""Parallelism: mesh construction, sharding rules, ring attention."""
