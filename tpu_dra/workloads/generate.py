"""KV-cache greedy decoding for the flagship Llama.

Serving-side companion to workloads/train.py: prefill + incremental
decode over a static-shape KV cache, fully jittable (``lax.scan`` over
decode steps, ``lax.dynamic_update_slice`` cache writes — no Python
control flow on device values, so XLA compiles one prefill and one
decode-step executable).

The decode forward is a hand-rolled replay of models/llama.py's math
over the SAME parameter tree, in either layout: scan-stacked layers or
unrolled ``layer_{i}`` subtrees (the in-place-cache fast path).
Equivalence of BOTH is pinned by
tests/test_workloads.py::test_decode_matches_full_forward:
teacher-forced decode logits must match the training forward's logits
position by position, so the implementations cannot drift silently.

No reference counterpart (the reference is a DRA driver); this is the
workload-payload layer's serving path.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dra.workloads.models.llama import (
    LlamaConfig,
    apply_rope,
    rope_frequencies,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DecodeCache:
    """KV cache; pos is the number of positions already written (same
    for every layer). Two layouts matching the model's two param
    layouts:

    - stacked (``scan_layers=True`` params): k/v are single arrays
      [L, b, max_seq, kvh, hd] scanned alongside the stacked layer
      params;
    - unrolled (``scan_layers=False`` params, the bench training
      default): k/v are L-tuples of [b, max_seq, kvh, hd] — each
      layer's buffer has a single def-use chain per step (in-place
      dynamic_update_slice then attend), which XLA aliases across
      decode-scan iterations instead of copying the whole cache every
      token (the stacked layout pays streamed xs reads + a bulk append
      against a second buffer).

    INVARIANT (stacked layout): slots at positions >= pos are ZERO.
    init_cache guarantees it and forward_chunk preserves it (each chunk
    writes exactly [pos, pos+s)); the stacked attention's split value
    contraction relies on it. Rewinding pos (speculative-decode
    rejection) or building a cache by other means breaks it silently —
    call :meth:`zero_tail` first (and :meth:`tail_is_zero` asserts the
    invariant in tests/debug runs)."""

    k: "jnp.ndarray | tuple"  # stacked array or L-tuple of per-layer arrays
    v: "jnp.ndarray | tuple"
    pos: jnp.ndarray  # scalar int32

    def tree_flatten(self):
        return (self.k, self.v, self.pos), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    def _seq_mask(self, arr: jnp.ndarray, stacked: bool) -> jnp.ndarray:
        seq_axis = 2 if stacked else 1  # [L, b, s, ...] vs [b, s, ...]
        idx = jnp.arange(arr.shape[seq_axis])
        shape = [1] * arr.ndim
        shape[seq_axis] = arr.shape[seq_axis]
        return (idx < self.pos).reshape(shape)

    def zero_tail(self) -> "DecodeCache":
        """Re-establish the zero-tail invariant after an external pos
        rewind (speculative-decode rejection) or a hand-built cache:
        returns a cache with every slot at positions >= pos zeroed.
        Jit-safe (pure mask multiply, no data-dependent shapes)."""
        stacked = not isinstance(self.k, tuple)
        if stacked:
            return DecodeCache(
                k=self.k * self._seq_mask(self.k, True).astype(self.k.dtype),
                v=self.v * self._seq_mask(self.v, True).astype(self.v.dtype),
                pos=self.pos,
            )
        return DecodeCache(
            k=tuple(a * self._seq_mask(a, False).astype(a.dtype)
                    for a in self.k),
            v=tuple(a * self._seq_mask(a, False).astype(a.dtype)
                    for a in self.v),
            pos=self.pos,
        )

    def tail_is_zero(self) -> jnp.ndarray:
        """Scalar bool: does the zero-tail invariant hold? For test
        assertions and opt-in debug checks (cheap enough to run per
        rewind: one masked reduction over the cache)."""
        stacked = not isinstance(self.k, tuple)
        arrs = (self.k, self.v) if stacked else tuple(self.k) + tuple(self.v)
        ok = jnp.bool_(True)
        for a in arrs:
            tail = a * (~self._seq_mask(a, stacked)).astype(a.dtype)
            ok = ok & (jnp.sum(jnp.abs(tail.astype(jnp.float32))) == 0)
        return ok


def init_cache(
    config: LlamaConfig, batch: int, max_seq: int, stacked: bool = True
) -> DecodeCache:
    shape = (batch, max_seq, config.n_kv_heads, config.head_dim)
    if stacked:
        return DecodeCache(
            k=jnp.zeros((config.n_layers,) + shape, config.dtype),
            v=jnp.zeros((config.n_layers,) + shape, config.dtype),
            pos=jnp.zeros((), jnp.int32),
        )
    return DecodeCache(
        k=tuple(
            jnp.zeros(shape, config.dtype) for _ in range(config.n_layers)
        ),
        v=tuple(
            jnp.zeros(shape, config.dtype) for _ in range(config.n_layers)
        ),
        pos=jnp.zeros((), jnp.int32),
    )


def _rms(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (
        x32 * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    ).astype(x.dtype)


def _mm(x: jnp.ndarray, w: dict) -> jnp.ndarray:
    """x @ kernel for either weight form: plain ``{"kernel"}`` or
    int8 weight-only ``{"kernel_q", "scale"}`` (workloads/quantize.py)
    via ops/int8mm.py — XLA's convert-fused dot by default (measured
    fastest at decode shapes), Pallas kernel opt-in."""
    if "kernel_q" in w:
        from tpu_dra.workloads.ops.int8mm import int8_matmul

        return int8_matmul(x, w["kernel_q"], w["scale"])
    return x @ w["kernel"].astype(x.dtype)


def _project_qkv(c, lp, x, cos, sin, b, s):
    """Shared front half of a decoder layer: pre-norm + roped q/k/v
    projections (identical in both cache layouts)."""
    att = lp["attention"]
    h = _rms(x, lp["attention_norm"]["scale"], c.norm_eps)
    q = _mm(h, att["wq"]).reshape(b, s, c.n_heads, c.head_dim)
    k = _mm(h, att["wk"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    v = _mm(h, att["wv"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v


def _finish_block(c, lp, x, out, b, s):
    """Shared back half: attention output projection + residual MLP
    (identical in both cache layouts)."""
    att = lp["attention"]
    out = out.reshape(b, s, c.n_heads * c.head_dim)
    x = x + _mm(out, att["wo"])
    mlp = lp["mlp"]
    h2 = _rms(x, lp["mlp_norm"]["scale"], c.norm_eps)
    gate = _mm(h2, mlp["w_gate"])
    up = _mm(h2, mlp["w_up"])
    return x + _mm(jax.nn.silu(gate) * up, mlp["w_down"])


def forward_chunk(
    config: LlamaConfig,
    params: dict,
    cache: DecodeCache,
    tokens: jnp.ndarray,
) -> Tuple[DecodeCache, jnp.ndarray]:
    """Process ``tokens`` [b, s] at absolute positions
    ``cache.pos .. cache.pos+s-1``: append K/V, attend over everything
    written so far, and return (updated cache, logits [b, s, vocab]).
    Prefill is a long chunk; a decode step is s=1. Handles both param
    layouts: scan-stacked (``scan_layers=True``) and unrolled (the
    cache layout must match — ``_generate`` wires this up)."""
    c = config
    stacked = "layers" in params
    if isinstance(cache.k, (tuple, list)) == stacked:
        raise ValueError(
            f"cache layout does not match param layout: params are "
            f"{'stacked' if stacked else 'unrolled'} but cache.k is a "
            f"{type(cache.k).__name__}; build the cache with "
            f"init_cache(..., stacked={stacked})"
        )
    b, s = tokens.shape
    max_seq = cache.k.shape[2] if stacked else cache.k[0].shape[1]
    x = params["embed"]["embedding"].astype(c.dtype)[tokens]  # [b, s, d]
    positions = cache.pos + jnp.arange(s)
    cos, sin = rope_frequencies(c, positions)  # [s, hd/2]
    # Absolute-position mask over the whole static cache: key j visible
    # to query i iff j <= pos+i. Unwritten slots sit at j >= pos+s and
    # are masked for every query.
    q_abs = positions  # [s]
    karange = jnp.arange(max_seq)
    mask = karange[None, :] <= q_abs[:, None]  # [s, max_seq]
    scale = c.head_dim ** -0.5
    n_rep = c.n_heads // c.n_kv_heads

    def block(x, layer):
        # ck/cv are the layer's cache as SCANNED INPUTS (streamed reads);
        # positions >= cache.pos are guaranteed zero (init_cache zeros
        # them and every chunk writes exactly [pos, pos+s)). The scan
        # emits only the s NEW positions' k/v — rewriting the full cache
        # as stacked scan outputs costs two whole-cache copies per decode
        # step (measured 4x the roofline step time at batch 128 on v5e).
        lp, ck, cv = layer  # ck/cv: [b, max_seq, kvh, hd]
        q, k, v = _project_qkv(c, lp, x, cos, sin, b, s)
        # GQA without materializing an n_rep-times copy of the cache
        # (the decode hot path would pay that per layer per step):
        # group query heads kv-major — head i belongs to kv group
        # i // n_rep, matching ops/attention.py _repeat_kv order — and
        # contract straight against the grouped cache.
        qg = q.reshape(b, s, c.n_kv_heads, n_rep, c.head_dim)
        # Scores against the (stale-at-[pos,pos+s)) streamed cache, then
        # overwrite the in-chunk columns with the fresh keys' scores.
        logits = jnp.einsum(
            "bqhrd,bkhd->bhrqk", qg, ck,
            preferred_element_type=jnp.float32,
        ) * scale
        chunk_scores = jnp.einsum(
            "bqhrd,bkhd->bhrqk", qg, k,
            preferred_element_type=jnp.float32,
        ) * scale
        logits = lax.dynamic_update_slice(
            logits, chunk_scores, (0, 0, 0, 0, cache.pos)
        )
        logits = jnp.where(mask[None, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        pv = probs.astype(cv.dtype)
        # Value contraction splits the same way: the streamed cache's
        # [pos, pos+s) columns are zero, so their term vanishes and the
        # fresh values enter through the sliced correction.
        out = jnp.einsum(
            "bhrqk,bkhd->bqhrd", pv, cv,
            preferred_element_type=jnp.float32,
        )
        chunk_probs = lax.dynamic_slice(
            pv, (0, 0, 0, 0, cache.pos), (b, c.n_kv_heads, n_rep, s, s)
        )
        out = out + jnp.einsum(
            "bhrqk,bkhd->bqhrd", chunk_probs, v,
            preferred_element_type=jnp.float32,
        )
        return _finish_block(c, lp, x, out.astype(c.dtype), b, s), (k, v)

    if stacked:
        x, (k_new, v_new) = lax.scan(
            block, x, (params["layers"]["block"], cache.k, cache.v)
        )
        # One bulk append outside the scan: k_new/v_new are
        # [L, b, s, kvh, hd] (s tokens per layer), written into the
        # static cache at pos.
        new_k = lax.dynamic_update_slice(
            cache.k, k_new, (0, 0, cache.pos, 0, 0)
        )
        new_v = lax.dynamic_update_slice(
            cache.v, v_new, (0, 0, cache.pos, 0, 0)
        )
        new_cache = DecodeCache(k=new_k, v=new_v, pos=cache.pos + s)
    else:
        # Unrolled layers: each layer's cache buffer is updated in place
        # (single def-use chain per step — XLA aliases it across decode
        # iterations; measured 8.3k -> on the way to roofline at batch
        # 128 on v5e vs the stacked path's bulk-append copies).
        ks, vs = list(cache.k), list(cache.v)
        for i in range(c.n_layers):
            x, ks[i], vs[i] = _block_inplace(
                c, params[f"layer_{i}"], x, ks[i], vs[i], cache.pos,
                mask, cos, sin, n_rep, b, s,
            )
        new_cache = DecodeCache(
            k=tuple(ks), v=tuple(vs), pos=cache.pos + s
        )
    x = _rms(x, params["final_norm"]["scale"], c.norm_eps)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)
    return new_cache, logits


def _block_inplace(c, lp, x, ck, cv, pos, mask, cos, sin, n_rep, b, s):
    """One unrolled decoder layer over a single-layer cache
    [b, max_seq, kvh, hd]: append this chunk's K/V in place, then attend
    over the updated buffer (the straightforward update-then-attend —
    correct here because the buffer is not simultaneously a scan input)."""
    scale = c.head_dim ** -0.5
    q, k, v = _project_qkv(c, lp, x, cos, sin, b, s)
    ck = lax.dynamic_update_slice(ck, k, (0, pos, 0, 0))
    cv = lax.dynamic_update_slice(cv, v, (0, pos, 0, 0))
    qg = q.reshape(b, s, c.n_kv_heads, n_rep, c.head_dim)
    logits = jnp.einsum(
        "bqhrd,bkhd->bhrqk", qg, ck,
        preferred_element_type=jnp.float32,
    ) * scale
    logits = jnp.where(mask[None, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum(
        "bhrqk,bkhd->bqhrd", probs.astype(cv.dtype), cv,
        preferred_element_type=jnp.float32,
    ).astype(c.dtype)
    return _finish_block(c, lp, x, out, b, s), ck, cv


def _generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    max_seq: int,
    pick,
) -> jnp.ndarray:
    """Shared prefill + scan-decode loop; ``pick(logits[b, v], i)``
    chooses the next token for step i."""
    b, s = prompt.shape
    max_seq = max_seq or (s + max_new_tokens)
    # All static at trace time: fail loudly instead of letting a full
    # cache clamp dynamic_update_slice writes into silent garbage.
    assert max_new_tokens >= 1, "max_new_tokens must be >= 1"
    assert max_seq >= s + max_new_tokens, (
        f"cache too small: max_seq={max_seq} < "
        f"prompt {s} + max_new_tokens {max_new_tokens}"
    )
    cache = init_cache(config, b, max_seq, stacked="layers" in params)
    cache, logits = forward_chunk(config, params, cache, prompt)
    first = pick(logits[:, -1], 0).astype(prompt.dtype)

    def step(carry, i):
        cache, tok = carry
        cache, logits = forward_chunk(
            config, params, cache, tok[:, None]
        )
        nxt = pick(logits[:, -1], i).astype(tok.dtype)
        return (cache, nxt), nxt

    (_, _), rest = lax.scan(
        step, (cache, first), jnp.arange(1, max_new_tokens)
    )
    generated = jnp.concatenate(
        [first[:, None], rest.swapaxes(0, 1)], axis=1
    )
    return jnp.concatenate([prompt, generated], axis=1)


def greedy_generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    max_seq: int = 0,
) -> jnp.ndarray:
    """Greedy-decode ``max_new_tokens`` after ``prompt`` [b, s]; returns
    [b, s + max_new_tokens]. Jit-friendly: one traced prefill + a
    ``lax.scan`` of single-token steps."""
    return _generate(
        config, params, prompt, max_new_tokens, max_seq,
        pick=lambda logits, _i: jnp.argmax(logits, axis=-1),
    )


def sample_generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    rng: jnp.ndarray,
    temperature: float = 1.0,
    top_k: int = 0,
    max_seq: int = 0,
) -> jnp.ndarray:
    """Temperature / top-k sampling over the same cache machinery.
    ``top_k=0`` samples the full distribution; ``top_k=1`` or
    ``temperature=0`` degenerate to greedy."""
    assert 0 <= top_k <= config.vocab_size, (
        f"top_k={top_k} out of range for vocab {config.vocab_size}"
    )
    if temperature <= 0.0 or top_k == 1:
        return greedy_generate(
            config, params, prompt, max_new_tokens, max_seq
        )

    def pick(logits, i):
        step_rng = jax.random.fold_in(rng, i)
        scaled = logits / temperature
        if top_k > 0:
            kth = lax.top_k(scaled, top_k)[0][:, -1:]
            scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
        return jax.random.categorical(step_rng, scaled, axis=-1)

    return _generate(
        config, params, prompt, max_new_tokens, max_seq, pick=pick
    )
