"""KV-cache decoding for the flagship Llama (the serving path).

Serving-side companion to workloads/train.py: prefill + incremental
decode over a static-shape KV cache, fully jittable (``lax.scan`` over
decode steps, ``lax.dynamic_update_slice`` cache writes — no Python
control flow on device values, so XLA compiles one prefill and one
decode-step executable).

Decode-roofline design (the r6 serving rework — docs/serving.md):

- the KV cache can be stored **int8** with per-(token, head) scales
  (quantize.quantize_kv): ~2x less KV traffic per step, dequantized on
  the fly inside the attention contraction — no bf16 KV copy ever
  exists;
- every s=1 step goes through the **fused decode attention** op
  (ops/attention.py decode_attention): GQA-native single-query online
  softmax split over the cache length. No ``_repeat_kv`` copy, no
  ``[b, h, 1, max_seq]`` fp32 score tensor, and the contraction stops at
  the last live position instead of paying full-``max_seq`` compute at
  small ``pos`` (the length-aware mask — made safe by the zero-tail
  invariant below);
- sampling is **fused into the decode scan**: temperature/top-k run on
  an exact two-stage top-k and draw from the k-entry candidate set, so
  sampled decode compiles to the same single scan as greedy instead of
  re-entering XLA per token (``sample_generate_unfused`` keeps the old
  per-token loop as the parity oracle).

The decode forward is a hand-rolled replay of models/llama.py's math
over the SAME parameter tree, in either layout: scan-stacked layers or
unrolled ``layer_{i}`` subtrees (the in-place-cache fast path).
Equivalence of BOTH (bf16 and int8-KV) is pinned by
tests/test_workloads.py::test_decode_matches_full_forward:
teacher-forced decode logits must match the training forward's logits
position by position, so the implementations cannot drift silently.

No reference counterpart (the reference is a DRA driver); this is the
workload-payload layer's serving path.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dra.workloads.models.llama import (
    LlamaConfig,
    apply_rope,
    rope_frequencies,
)
from tpu_dra.workloads.ops.attention import decode_attention
from tpu_dra.workloads.ops.decode_mlp import decode_mlp
from tpu_dra.workloads.quantize import quantize_kv

KV_QUANT_MODES = ("none", "int8")
WEIGHT_QUANT_MODES = ("none", "int8")


def _maybe_quantize_params(params: dict, weight_quant: str) -> dict:
    """int8 weight-only as a first-class knob on the WHOLE decode path
    (prefill, per-step projections/MLP, logits head — everything that
    goes through _mm), matching the engine's EngineConfig.weight_quant.
    Under jit the quantization happens at trace time against the traced
    params; for a long-lived server, pre-quantize once
    (quantize.quantize_params) and pass the quantized tree instead."""
    if weight_quant == "none":
        return params
    if weight_quant not in WEIGHT_QUANT_MODES:
        raise ValueError(
            f"unknown weight_quant {weight_quant!r}; expected one of "
            f"{WEIGHT_QUANT_MODES}"
        )
    from tpu_dra.workloads.quantize import quantize_params

    return quantize_params(params)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DecodeCache:
    """KV cache; pos is the number of positions already written (same
    for every layer). Two layouts matching the model's two param
    layouts:

    - stacked (``scan_layers=True`` params): k/v are single arrays
      [L, b, max_seq, kvh, hd] scanned alongside the stacked layer
      params;
    - unrolled (``scan_layers=False`` params, the bench training
      default): k/v are L-tuples of [b, max_seq, kvh, hd] — each
      layer's buffer has a single def-use chain per step (in-place
      dynamic_update_slice then attend), which XLA aliases across
      decode-scan iterations instead of copying the whole cache every
      token (the stacked layout pays streamed xs reads + a bulk append
      against a second buffer).

    Storage is the model dtype by default, or int8 with per-(token,
    head) f32 scales (``k_scale``/``v_scale``: [L, b, max_seq, kvh]
    stacked, L-tuples of [b, max_seq, kvh] unrolled) when built with
    ``init_cache(..., kv_quant="int8")`` — quantize.quantize_kv rows,
    dequantized on the fly inside the attention contraction.

    INVARIANT (stacked layout): slots at positions >= pos are ZERO —
    including the scale arrays. init_cache guarantees it and
    forward_chunk preserves it (each chunk writes exactly [pos, pos+s));
    the stacked attention's split value contraction relies on it.
    Rewinding pos (speculative-decode rejection) or building a cache by
    other means breaks it silently — call :meth:`zero_tail` first (and
    :meth:`tail_is_zero` asserts the invariant in tests/debug runs).
    The s=1 decode step itself is tail-proof either way: decode
    attention's length mask never admits a position >= pos."""

    k: "jnp.ndarray | tuple"  # stacked array or L-tuple of per-layer arrays
    v: "jnp.ndarray | tuple"
    pos: jnp.ndarray  # scalar int32
    k_scale: "jnp.ndarray | tuple | None" = None  # int8 mode only
    v_scale: "jnp.ndarray | tuple | None" = None

    def tree_flatten(self):
        return (self.k, self.v, self.pos, self.k_scale, self.v_scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def stacked(self) -> bool:
        return not isinstance(self.k, (tuple, list))

    def _seq_mask(self, arr: jnp.ndarray, stacked: bool) -> jnp.ndarray:
        seq_axis = 2 if stacked else 1  # [L, b, s, ...] vs [b, s, ...]
        idx = jnp.arange(arr.shape[seq_axis])
        shape = [1] * arr.ndim
        shape[seq_axis] = arr.shape[seq_axis]
        return (idx < self.pos).reshape(shape)

    def _arrays(self):
        """(stacked?, list of (field, value)) over every non-None buffer —
        k/v and, in int8 mode, their scale arrays."""
        fields = [("k", self.k), ("v", self.v)]
        if self.quantized:
            fields += [("k_scale", self.k_scale), ("v_scale", self.v_scale)]
        return self.stacked, fields

    def zero_tail(self) -> "DecodeCache":
        """Re-establish the zero-tail invariant after an external pos
        rewind (speculative-decode rejection) or a hand-built cache:
        returns a cache with every slot at positions >= pos zeroed —
        values AND scales. Jit-safe (pure mask multiply, no
        data-dependent shapes)."""
        stacked, fields = self._arrays()

        def wipe(a):
            return a * self._seq_mask(a, stacked).astype(a.dtype)

        out = {
            name: wipe(a) if stacked else tuple(wipe(x) for x in a)
            for name, a in fields
        }
        return DecodeCache(pos=self.pos, **out)

    def tail_is_zero(self) -> jnp.ndarray:
        """Scalar bool: does the zero-tail invariant hold? For test
        assertions and opt-in debug checks (cheap enough to run per
        rewind: one masked reduction over the cache)."""
        stacked, fields = self._arrays()
        arrs = []
        for _, a in fields:
            arrs.extend([a] if stacked else list(a))
        ok = jnp.bool_(True)
        for a in arrs:
            tail = a * (~self._seq_mask(a, stacked)).astype(a.dtype)
            ok = ok & (jnp.sum(jnp.abs(tail.astype(jnp.float32))) == 0)
        return ok


def init_cache(
    config: LlamaConfig,
    batch: int,
    max_seq: int,
    stacked: bool = True,
    kv_quant: str = "none",
) -> DecodeCache:
    if kv_quant not in KV_QUANT_MODES:
        raise ValueError(
            f"unknown kv_quant {kv_quant!r}; expected one of {KV_QUANT_MODES}"
        )
    quant = kv_quant == "int8"
    kv_dtype = jnp.int8 if quant else config.dtype
    shape = (batch, max_seq, config.n_kv_heads, config.head_dim)
    sshape = (batch, max_seq, config.n_kv_heads)
    if stacked:
        lead = (config.n_layers,)
        return DecodeCache(
            k=jnp.zeros(lead + shape, kv_dtype),
            v=jnp.zeros(lead + shape, kv_dtype),
            pos=jnp.zeros((), jnp.int32),
            k_scale=jnp.zeros(lead + sshape, jnp.float32) if quant else None,
            v_scale=jnp.zeros(lead + sshape, jnp.float32) if quant else None,
        )
    L = config.n_layers
    return DecodeCache(
        k=tuple(jnp.zeros(shape, kv_dtype) for _ in range(L)),
        v=tuple(jnp.zeros(shape, kv_dtype) for _ in range(L)),
        pos=jnp.zeros((), jnp.int32),
        k_scale=tuple(jnp.zeros(sshape, jnp.float32) for _ in range(L))
        if quant else None,
        v_scale=tuple(jnp.zeros(sshape, jnp.float32) for _ in range(L))
        if quant else None,
    )


def unroll_params(params: dict) -> dict:
    """Stacked (``scan_layers=True``) param tree -> the unrolled
    ``layer_{i}`` layout, by slicing every ``[L, ...]`` leaf of the
    scanned block per layer. Identity for already-unrolled trees. The
    serving engine (workloads/engine.py) steps layers in Python over
    per-layer page pools, so it normalizes to this layout once at
    construction — the same per-layer in-place idiom the unrolled decode
    fast path uses."""
    if "layers" not in params:
        return params
    block = params["layers"]["block"]
    n_layers = jax.tree_util.tree_leaves(block)[0].shape[0]
    out = {k: v for k, v in params.items() if k != "layers"}
    for i in range(n_layers):
        out[f"layer_{i}"] = jax.tree_util.tree_map(
            lambda leaf, i=i: leaf[i], block
        )
    return out


def _rms(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (
        x32 * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    ).astype(x.dtype)


def _mm(x: jnp.ndarray, w: dict) -> jnp.ndarray:
    """x @ kernel for either weight form: plain ``{"kernel"}`` or
    int8 weight-only ``{"kernel_q", "scale"}`` (workloads/quantize.py)
    via ops/int8mm.py — XLA's convert-fused dot by default (measured
    fastest at decode shapes), Pallas kernel opt-in."""
    if "kernel_q" in w:
        from tpu_dra.workloads.ops.int8mm import int8_matmul

        return int8_matmul(x, w["kernel_q"], w["scale"])
    return x @ w["kernel"].astype(x.dtype)


def _project_qkv(c, lp, x, cos, sin, b, s):
    """Shared front half of a decoder layer: pre-norm + roped q/k/v
    projections (identical in both cache layouts)."""
    att = lp["attention"]
    h = _rms(x, lp["attention_norm"]["scale"], c.norm_eps)
    q = _mm(h, att["wq"]).reshape(b, s, c.n_heads, c.head_dim)
    k = _mm(h, att["wk"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    v = _mm(h, att["wv"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v


def _finish_block(c, lp, x, out, b, s):
    """Shared back half: attention output projection + residual MLP
    (identical in both cache layouts). The s=1 decode step routes its
    norm+MLP chain through the fused block (ops/decode_mlp.py) — the
    pallas streaming kernel on TPU, the identical xla op chain
    elsewhere."""
    att = lp["attention"]
    out = out.reshape(b, s, c.n_heads * c.head_dim)
    x = x + _mm(out, att["wo"])
    mlp = lp["mlp"]
    if s == 1:
        return decode_mlp(
            x[:, 0], lp["mlp_norm"]["scale"], mlp, c.norm_eps,
            impl=c.decode_mlp_impl, block_f=c.decode_mlp_block_f,
        )[:, None]
    h2 = _rms(x, lp["mlp_norm"]["scale"], c.norm_eps)
    gate = _mm(h2, mlp["w_gate"])
    up = _mm(h2, mlp["w_up"])
    return x + _mm(jax.nn.silu(gate) * up, mlp["w_down"])


def _key_scale_cols(s: jnp.ndarray) -> jnp.ndarray:
    """[b, max_seq, kvh] per-key scale -> [b, kvh, 1, 1, max_seq]
    broadcastable against [b, kvh, n_rep, s, max_seq] chunk scores."""
    return s.transpose(0, 2, 1)[:, :, None, None, :]


def _attend_chunk_scores(c, qg, ck, ks, b, s):
    """Chunk queries against a full single-layer cache buffer: fp32
    scores with on-the-fly int8 dequant (the int8->dtype convert fuses
    into the dot feed; the per-key scale multiplies score columns, so no
    dequantized KV copy exists)."""
    kc = ck.astype(c.dtype) if ks is not None else ck
    logits = jnp.einsum(
        "bqhrd,bkhd->bhrqk", qg, kc,
        preferred_element_type=jnp.float32,
    ) * (c.head_dim ** -0.5)
    if ks is not None:
        logits = logits * _key_scale_cols(ks)
    return logits


def _attend_chunk_values(c, probs, cv, vs):
    """fp32 probabilities x cache values with on-the-fly dequant: the
    per-key v scale folds into the probabilities (fp32) before the value
    contraction."""
    if vs is not None:
        pv = (probs * _key_scale_cols(vs)).astype(c.dtype)
        vc = cv.astype(c.dtype)
    else:
        pv = probs.astype(cv.dtype)
        vc = cv
    return jnp.einsum(
        "bhrqk,bkhd->bqhrd", pv, vc,
        preferred_element_type=jnp.float32,
    )


def _write_cache(ck, cv, ks, vs, k, v, pos):
    """Append a fresh [b, s, kvh, hd] K/V chunk at ``pos`` — quantizing
    in flight when the cache is int8 (ks/vs not None)."""
    if ks is not None:
        kq, ksc = quantize_kv(k)
        vq, vsc = quantize_kv(v)
        ck = lax.dynamic_update_slice(ck, kq, (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, vq, (0, pos, 0, 0))
        ks = lax.dynamic_update_slice(ks, ksc, (0, pos, 0))
        vs = lax.dynamic_update_slice(vs, vsc, (0, pos, 0))
    else:
        ck = lax.dynamic_update_slice(ck, k, (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, pos, 0, 0))
    return ck, cv, ks, vs


def forward_chunk(
    config: LlamaConfig,
    params: dict,
    cache: DecodeCache,
    tokens: jnp.ndarray,
) -> Tuple[DecodeCache, jnp.ndarray]:
    """Process ``tokens`` [b, s] at absolute positions
    ``cache.pos .. cache.pos+s-1``: append K/V, attend over everything
    written so far, and return (updated cache, logits [b, s, vocab]).
    Prefill is a long chunk; a decode step is s=1 and dispatches to the
    fused decode-attention op. Handles both param layouts: scan-stacked
    (``scan_layers=True``) and unrolled (the cache layout must match —
    ``_generate`` wires this up) — each in bf16 or int8-KV storage."""
    c = config
    stacked = "layers" in params
    if isinstance(cache.k, (tuple, list)) == stacked:
        raise ValueError(
            f"cache layout does not match param layout: params are "
            f"{'stacked' if stacked else 'unrolled'} but cache.k is a "
            f"{type(cache.k).__name__}; build the cache with "
            f"init_cache(..., stacked={stacked})"
        )
    quant = cache.quantized
    b, s = tokens.shape
    max_seq = cache.k.shape[2] if stacked else cache.k[0].shape[1]
    x = params["embed"]["embedding"].astype(c.dtype)[tokens]  # [b, s, d]
    positions = cache.pos + jnp.arange(s)
    cos, sin = rope_frequencies(c, positions)  # [s, hd/2]
    # Absolute-position mask over the whole static cache: key j visible
    # to query i iff j <= pos+i. Unwritten slots sit at j >= pos+s and
    # are masked for every query. (Prefill chunks only — the s=1 decode
    # step's masking lives inside decode_attention's length bound.)
    q_abs = positions  # [s]
    karange = jnp.arange(max_seq)
    mask = karange[None, :] <= q_abs[:, None]  # [s, max_seq]
    n_rep = c.n_heads // c.n_kv_heads

    def block(x, layer):
        # ck/cv are the layer's cache as SCANNED INPUTS (streamed reads);
        # positions >= cache.pos are guaranteed zero (init_cache zeros
        # them and every chunk writes exactly [pos, pos+s)). The scan
        # emits only the s NEW positions' k/v — rewriting the full cache
        # as stacked scan outputs costs two whole-cache copies per decode
        # step (measured 4x the roofline step time at batch 128 on v5e).
        if quant:
            lp, ck, cv, ks, vs = layer
        else:
            lp, ck, cv = layer
            ks = vs = None
        q, k, v = _project_qkv(c, lp, x, cos, sin, b, s)
        if s == 1:
            # Fused decode step: the streamed cache is stale at the
            # current position, so the fresh token's K/V ride in exact
            # (extra_k/extra_v) while the cache part is length-bounded
            # at pos. GQA-native, no [b, h, max_seq] fp32 scores.
            out = decode_attention(
                q[:, 0], ck, cv, cache.pos + 1,
                k_scale=ks, v_scale=vs,
                extra_k=k[:, 0], extra_v=v[:, 0],
                impl=c.decode_impl, block_k=c.decode_block_k,
            )[:, None]  # [b, 1, h, hd]
            out = out.astype(c.dtype)
        else:
            # GQA without materializing an n_rep-times copy of the cache:
            # group query heads kv-major — head i belongs to kv group
            # i // n_rep, matching ops/attention.py _repeat_kv order —
            # and contract straight against the grouped cache. Scores
            # against the (stale-at-[pos,pos+s)) streamed cache, then
            # overwrite the in-chunk columns with the fresh keys' scores.
            qg = q.reshape(b, s, c.n_kv_heads, n_rep, c.head_dim)
            scale = c.head_dim ** -0.5
            logits = _attend_chunk_scores(c, qg, ck, ks, b, s)
            chunk_scores = jnp.einsum(
                "bqhrd,bkhd->bhrqk", qg, k,
                preferred_element_type=jnp.float32,
            ) * scale
            logits = lax.dynamic_update_slice(
                logits, chunk_scores, (0, 0, 0, 0, cache.pos)
            )
            logits = jnp.where(mask[None, None, None], logits, -1e30)
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            # Value contraction splits the same way: the streamed cache's
            # [pos, pos+s) columns are zero (values AND scales), so their
            # term vanishes and the fresh values enter through the sliced
            # correction — in the fresh chunk's exact dtype, unquantized.
            out = _attend_chunk_values(c, probs, cv, vs)
            chunk_probs = lax.dynamic_slice(
                probs.astype(v.dtype),
                (0, 0, 0, 0, cache.pos),
                (b, c.n_kv_heads, n_rep, s, s),
            )
            out = out + jnp.einsum(
                "bhrqk,bkhd->bqhrd", chunk_probs, v,
                preferred_element_type=jnp.float32,
            )
            out = out.astype(c.dtype)
        if quant:
            kq, ksc = quantize_kv(k)
            vq, vsc = quantize_kv(v)
            ys = (kq, ksc, vq, vsc)
        else:
            ys = (k, v)
        return _finish_block(c, lp, x, out, b, s), ys

    if stacked:
        xs = (params["layers"]["block"], cache.k, cache.v)
        if quant:
            xs = xs + (cache.k_scale, cache.v_scale)
        x, ys = lax.scan(block, x, xs)
        # One bulk append outside the scan: the ys are [L, b, s, ...]
        # (s tokens per layer), written into the static cache at pos.
        if quant:
            k_new, ks_new, v_new, vs_new = ys
            new_cache = DecodeCache(
                k=lax.dynamic_update_slice(
                    cache.k, k_new, (0, 0, cache.pos, 0, 0)
                ),
                v=lax.dynamic_update_slice(
                    cache.v, v_new, (0, 0, cache.pos, 0, 0)
                ),
                pos=cache.pos + s,
                k_scale=lax.dynamic_update_slice(
                    cache.k_scale, ks_new, (0, 0, cache.pos, 0)
                ),
                v_scale=lax.dynamic_update_slice(
                    cache.v_scale, vs_new, (0, 0, cache.pos, 0)
                ),
            )
        else:
            k_new, v_new = ys
            new_cache = DecodeCache(
                k=lax.dynamic_update_slice(
                    cache.k, k_new, (0, 0, cache.pos, 0, 0)
                ),
                v=lax.dynamic_update_slice(
                    cache.v, v_new, (0, 0, cache.pos, 0, 0)
                ),
                pos=cache.pos + s,
            )
    else:
        # Unrolled layers: each layer's cache buffer is updated in place
        # (single def-use chain per step — XLA aliases it across decode
        # iterations; measured 8.3k -> on the way to roofline at batch
        # 128 on v5e vs the stacked path's bulk-append copies).
        ks_l = list(cache.k_scale) if quant else [None] * c.n_layers
        vs_l = list(cache.v_scale) if quant else [None] * c.n_layers
        k_l, v_l = list(cache.k), list(cache.v)
        for i in range(c.n_layers):
            x, k_l[i], v_l[i], ks_l[i], vs_l[i] = _block_inplace(
                c, params[f"layer_{i}"], x, k_l[i], v_l[i], ks_l[i],
                vs_l[i], cache.pos, mask, cos, sin, n_rep, b, s,
            )
        new_cache = DecodeCache(
            k=tuple(k_l), v=tuple(v_l), pos=cache.pos + s,
            k_scale=tuple(ks_l) if quant else None,
            v_scale=tuple(vs_l) if quant else None,
        )
    x = _rms(x, params["final_norm"]["scale"], c.norm_eps)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)
    return new_cache, logits


def _block_inplace(c, lp, x, ck, cv, ks, vs, pos, mask, cos, sin, n_rep,
                   b, s):
    """One unrolled decoder layer over a single-layer cache
    [b, max_seq, kvh, hd] (+ scale buffers when int8): append this
    chunk's K/V in place — quantizing in flight — then attend over the
    updated buffer (the straightforward update-then-attend — correct
    here because the buffer is not simultaneously a scan input). The
    s=1 step attends through the fused decode-attention op."""
    q, k, v = _project_qkv(c, lp, x, cos, sin, b, s)
    ck, cv, ks, vs = _write_cache(ck, cv, ks, vs, k, v, pos)
    if s == 1:
        out = decode_attention(
            q[:, 0], ck, cv, pos + 1, k_scale=ks, v_scale=vs,
            impl=c.decode_impl, block_k=c.decode_block_k,
        )[:, None].astype(c.dtype)
    else:
        qg = q.reshape(b, s, c.n_kv_heads, n_rep, c.head_dim)
        logits = _attend_chunk_scores(c, qg, ck, ks, b, s)
        logits = jnp.where(mask[None, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        out = _attend_chunk_values(c, probs, cv, vs).astype(c.dtype)
    return _finish_block(c, lp, x, out, b, s), ck, cv, ks, vs


# --- sampling ---------------------------------------------------------------

# Two-stage top-k chunk width: the vocab splits into _TOPK_CHUNK-wide
# segments, each segment contributes its own top-k, and the final top-k
# runs over the (vocab/_TOPK_CHUNK)*k candidates. Exact for any input —
# every global top-k element is a top-k element of its segment — while
# replacing one huge partial sort with narrow ones (32k vocab, k=40:
# 32768-wide sort -> 32x 1024-wide + one 1280-wide).
_TOPK_CHUNK = 1024


def topk_exact(x: jnp.ndarray, k: int) -> tuple:
    """lax.top_k semantics ([b, vocab] -> values/indices [b, k], values
    descending, ties to the lower index) via the two-stage split when
    the shape allows, one direct lax.top_k otherwise."""
    vocab = x.shape[-1]
    if vocab % _TOPK_CHUNK or vocab <= _TOPK_CHUNK or k > _TOPK_CHUNK:
        return lax.top_k(x, k)
    n = vocab // _TOPK_CHUNK
    xr = x.reshape(x.shape[0], n, _TOPK_CHUNK)
    seg_v, seg_i = lax.top_k(xr, k)  # [b, n, k]
    cand_v = seg_v.reshape(x.shape[0], n * k)
    cand_i = (
        seg_i + (jnp.arange(n) * _TOPK_CHUNK)[None, :, None]
    ).reshape(x.shape[0], n * k)
    fin_v, fin_pos = lax.top_k(cand_v, k)
    return fin_v, jnp.take_along_axis(cand_i, fin_pos, axis=-1)


def sample_token(
    logits: jnp.ndarray,
    rng: jnp.ndarray,
    temperature: float,
    top_k: int,
) -> jnp.ndarray:
    """Fused temperature/top-k sampler: [b, vocab] logits -> [b] token
    ids. With top_k > 0 the categorical draw runs over the k-entry
    candidate set (not the full vocab) and maps back through the top-k
    indices — same distribution as masking the vocab to the top k, at a
    fraction of the per-step cost. Scan-body safe: static shapes only."""
    scaled = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        vals, idx = topk_exact(scaled, top_k)
        choice = jax.random.categorical(rng, vals, axis=-1)
        return jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    return jax.random.categorical(rng, scaled, axis=-1)


def _generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    max_seq: int,
    pick,
    kv_quant: str = "none",
    weight_quant: str = "none",
) -> jnp.ndarray:
    """Shared prefill + scan-decode loop; ``pick(logits[b, v], i)``
    chooses the next token for step i."""
    params = _maybe_quantize_params(params, weight_quant)
    b, s = prompt.shape
    if not max_seq:
        # Auto-sized caches round up to a 64 granule: decode attention
        # needs a block size dividing max_seq, and an awkward length
        # (prime, odd) would collapse the chunk to ~1 key per loop
        # iteration. Padded slots cost cache memory only — the length
        # mask keeps them out of every contraction.
        max_seq = -(-(s + max_new_tokens) // 64) * 64
    # All static at trace time: fail loudly instead of letting a full
    # cache clamp dynamic_update_slice writes into silent garbage.
    assert max_new_tokens >= 1, "max_new_tokens must be >= 1"
    assert max_seq >= s + max_new_tokens, (
        f"cache too small: max_seq={max_seq} < "
        f"prompt {s} + max_new_tokens {max_new_tokens}"
    )
    cache = init_cache(
        config, b, max_seq, stacked="layers" in params, kv_quant=kv_quant
    )
    cache, logits = forward_chunk(config, params, cache, prompt)
    first = pick(logits[:, -1], 0).astype(prompt.dtype)

    def step(carry, i):
        cache, tok = carry
        cache, logits = forward_chunk(
            config, params, cache, tok[:, None]
        )
        nxt = pick(logits[:, -1], i).astype(tok.dtype)
        return (cache, nxt), nxt

    (_, _), rest = lax.scan(
        step, (cache, first), jnp.arange(1, max_new_tokens)
    )
    generated = jnp.concatenate(
        [first[:, None], rest.swapaxes(0, 1)], axis=1
    )
    return jnp.concatenate([prompt, generated], axis=1)


def greedy_generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    max_seq: int = 0,
    kv_quant: str = "none",
    weight_quant: str = "none",
) -> jnp.ndarray:
    """Greedy-decode ``max_new_tokens`` after ``prompt`` [b, s]; returns
    [b, s + max_new_tokens]. Jit-friendly: one traced prefill + a
    ``lax.scan`` of single-token steps. ``kv_quant="int8"`` stores the
    cache int8 with per-(token, head) scales; ``weight_quant="int8"``
    runs every matmul on the path (projections, MLP, logits) over the
    int8 weight-only tree."""
    return _generate(
        config, params, prompt, max_new_tokens, max_seq,
        pick=lambda logits, _i: jnp.argmax(logits, axis=-1),
        kv_quant=kv_quant, weight_quant=weight_quant,
    )


def sample_generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    rng: jnp.ndarray,
    temperature: float = 1.0,
    top_k: int = 0,
    max_seq: int = 0,
    kv_quant: str = "none",
    weight_quant: str = "none",
) -> jnp.ndarray:
    """Temperature / top-k sampling over the same cache machinery, with
    the sampler FUSED into the decode scan body (sample_token): sampled
    decode compiles to the same single scan as greedy — no per-token XLA
    re-entry, no full-vocab categorical. ``top_k=0`` samples the full
    distribution; ``top_k=1`` or ``temperature=0`` degenerate to
    greedy."""
    assert 0 <= top_k <= config.vocab_size, (
        f"top_k={top_k} out of range for vocab {config.vocab_size}"
    )
    if temperature <= 0.0 or top_k == 1:
        return greedy_generate(
            config, params, prompt, max_new_tokens, max_seq,
            kv_quant=kv_quant, weight_quant=weight_quant,
        )

    def pick(logits, i):
        return sample_token(
            logits, jax.random.fold_in(rng, i), temperature, top_k
        )

    return _generate(
        config, params, prompt, max_new_tokens, max_seq, pick=pick,
        kv_quant=kv_quant, weight_quant=weight_quant,
    )


def sample_generate_unfused(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    rng: jnp.ndarray,
    temperature: float = 1.0,
    top_k: int = 0,
    max_seq: int = 0,
    kv_quant: str = "none",
    weight_quant: str = "none",
) -> jnp.ndarray:
    """The pre-fusion serving loop: one XLA entry per generated token (a
    host round-trip between steps). Kept as the parity oracle for the
    fused path — same fold_in schedule, same sample_token math — so a
    fixed key must produce TOKEN-IDENTICAL output to sample_generate
    (pinned by tests/test_workloads.py::test_fused_sampler_parity)."""
    assert 0 <= top_k <= config.vocab_size
    if temperature <= 0.0 or top_k == 1:
        return greedy_generate(
            config, params, prompt, max_new_tokens, max_seq,
            kv_quant=kv_quant, weight_quant=weight_quant,
        )
    params = _maybe_quantize_params(params, weight_quant)
    b, s = prompt.shape
    if not max_seq:
        # Same 64-granule auto-sizing as _generate: the parity contract
        # is bit-level, so the cache (and the decode block size derived
        # from it) must match exactly.
        max_seq = -(-(s + max_new_tokens) // 64) * 64
    assert max_new_tokens >= 1 and max_seq >= s + max_new_tokens
    cache = init_cache(
        config, b, max_seq, stacked="layers" in params, kv_quant=kv_quant
    )
    cache, logits = forward_chunk(config, params, cache, prompt)
    tok = sample_token(
        logits[:, -1], jax.random.fold_in(rng, 0), temperature, top_k
    ).astype(prompt.dtype)
    out = [tok]
    for i in range(1, max_new_tokens):
        cache, logits = forward_chunk(config, params, cache, tok[:, None])
        tok = sample_token(
            logits[:, -1], jax.random.fold_in(rng, i), temperature, top_k
        ).astype(prompt.dtype)
        out.append(tok)
    return jnp.concatenate([prompt] + [t[:, None] for t in out], axis=1)
