"""KV-cache greedy decoding for the flagship Llama.

Serving-side companion to workloads/train.py: prefill + incremental
decode over a static-shape KV cache, fully jittable (``lax.scan`` over
decode steps, ``lax.dynamic_update_slice`` cache writes — no Python
control flow on device values, so XLA compiles one prefill and one
decode-step executable).

The decode forward is a hand-rolled replay of models/llama.py's math
over the SAME parameter tree (scan-stacked layers). Equivalence is
pinned by tests/test_workloads.py::test_decode_matches_full_forward:
teacher-forced decode logits must match the training forward's logits
position by position, so the two implementations cannot drift silently.

No reference counterpart (the reference is a DRA driver); this is the
workload-payload layer's serving path.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dra.workloads.models.llama import (
    LlamaConfig,
    apply_rope,
    rope_frequencies,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DecodeCache:
    """Per-layer stacked KV cache: k/v [L, b, max_seq, kvh, hd]; pos is
    the number of positions already written (same for every layer)."""

    k: jnp.ndarray
    v: jnp.ndarray
    pos: jnp.ndarray  # scalar int32

    def tree_flatten(self):
        return (self.k, self.v, self.pos), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)


def init_cache(
    config: LlamaConfig, batch: int, max_seq: int
) -> DecodeCache:
    shape = (
        config.n_layers, batch, max_seq, config.n_kv_heads, config.head_dim
    )
    return DecodeCache(
        k=jnp.zeros(shape, config.dtype),
        v=jnp.zeros(shape, config.dtype),
        pos=jnp.zeros((), jnp.int32),
    )


def _rms(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (
        x32 * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    ).astype(x.dtype)


def forward_chunk(
    config: LlamaConfig,
    params: dict,
    cache: DecodeCache,
    tokens: jnp.ndarray,
) -> Tuple[DecodeCache, jnp.ndarray]:
    """Process ``tokens`` [b, s] at absolute positions
    ``cache.pos .. cache.pos+s-1``: append K/V, attend over everything
    written so far, and return (updated cache, logits [b, s, vocab]).
    Prefill is a long chunk; a decode step is s=1. Requires the
    scan-stacked parameter layout (``scan_layers=True``, the default)."""
    c = config
    assert "layers" in params, "decode needs scan_layers=True param layout"
    b, s = tokens.shape
    max_seq = cache.k.shape[2]
    x = params["embed"]["embedding"].astype(c.dtype)[tokens]  # [b, s, d]
    positions = cache.pos + jnp.arange(s)
    cos, sin = rope_frequencies(c, positions)  # [s, hd/2]
    # Absolute-position mask over the whole static cache: key j visible
    # to query i iff j <= pos+i. Unwritten slots sit at j >= pos+s and
    # are masked for every query.
    q_abs = positions  # [s]
    karange = jnp.arange(max_seq)
    mask = karange[None, :] <= q_abs[:, None]  # [s, max_seq]
    scale = c.head_dim ** -0.5
    n_rep = c.n_heads // c.n_kv_heads

    def block(x, layer):
        lp, ck, cv = layer  # ck/cv: [b, max_seq, kvh, hd]
        att = lp["attention"]
        h = _rms(x, lp["attention_norm"]["scale"], c.norm_eps)
        q = (h @ att["wq"]["kernel"].astype(c.dtype)).reshape(
            b, s, c.n_heads, c.head_dim
        )
        k = (h @ att["wk"]["kernel"].astype(c.dtype)).reshape(
            b, s, c.n_kv_heads, c.head_dim
        )
        v = (h @ att["wv"]["kernel"].astype(c.dtype)).reshape(
            b, s, c.n_kv_heads, c.head_dim
        )
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        ck = lax.dynamic_update_slice(ck, k, (0, cache.pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, cache.pos, 0, 0))
        # GQA without materializing an n_rep-times copy of the cache
        # (the decode hot path would pay that per layer per step):
        # group query heads kv-major — head i belongs to kv group
        # i // n_rep, matching ops/attention.py _repeat_kv order — and
        # contract straight against the grouped cache.
        qg = q.reshape(b, s, c.n_kv_heads, n_rep, c.head_dim)
        logits = jnp.einsum(
            "bqhrd,bkhd->bhrqk", qg, ck,
            preferred_element_type=jnp.float32,
        ) * scale
        logits = jnp.where(mask[None, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        out = jnp.einsum(
            "bhrqk,bkhd->bqhrd", probs.astype(cv.dtype), cv,
            preferred_element_type=jnp.float32,
        ).astype(c.dtype)
        out = out.reshape(b, s, c.n_heads * c.head_dim)
        x = x + out @ att["wo"]["kernel"].astype(c.dtype)
        mlp = lp["mlp"]
        h2 = _rms(x, lp["mlp_norm"]["scale"], c.norm_eps)
        gate = h2 @ mlp["w_gate"]["kernel"].astype(c.dtype)
        up = h2 @ mlp["w_up"]["kernel"].astype(c.dtype)
        x = x + (jax.nn.silu(gate) * up) @ mlp["w_down"]["kernel"].astype(
            c.dtype
        )
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(
        block, x, (params["layers"]["block"], cache.k, cache.v)
    )
    x = _rms(x, params["final_norm"]["scale"], c.norm_eps)
    logits = (x @ params["lm_head"]["kernel"].astype(c.dtype)).astype(
        jnp.float32
    )
    new_cache = DecodeCache(k=new_k, v=new_v, pos=cache.pos + s)
    return new_cache, logits


def _generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    max_seq: int,
    pick,
) -> jnp.ndarray:
    """Shared prefill + scan-decode loop; ``pick(logits[b, v], i)``
    chooses the next token for step i."""
    b, s = prompt.shape
    max_seq = max_seq or (s + max_new_tokens)
    # All static at trace time: fail loudly instead of letting a full
    # cache clamp dynamic_update_slice writes into silent garbage.
    assert max_new_tokens >= 1, "max_new_tokens must be >= 1"
    assert max_seq >= s + max_new_tokens, (
        f"cache too small: max_seq={max_seq} < "
        f"prompt {s} + max_new_tokens {max_new_tokens}"
    )
    cache = init_cache(config, b, max_seq)
    cache, logits = forward_chunk(config, params, cache, prompt)
    first = pick(logits[:, -1], 0).astype(prompt.dtype)

    def step(carry, i):
        cache, tok = carry
        cache, logits = forward_chunk(
            config, params, cache, tok[:, None]
        )
        nxt = pick(logits[:, -1], i).astype(tok.dtype)
        return (cache, nxt), nxt

    (_, _), rest = lax.scan(
        step, (cache, first), jnp.arange(1, max_new_tokens)
    )
    generated = jnp.concatenate(
        [first[:, None], rest.swapaxes(0, 1)], axis=1
    )
    return jnp.concatenate([prompt, generated], axis=1)


def greedy_generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    max_seq: int = 0,
) -> jnp.ndarray:
    """Greedy-decode ``max_new_tokens`` after ``prompt`` [b, s]; returns
    [b, s + max_new_tokens]. Jit-friendly: one traced prefill + a
    ``lax.scan`` of single-token steps."""
    return _generate(
        config, params, prompt, max_new_tokens, max_seq,
        pick=lambda logits, _i: jnp.argmax(logits, axis=-1),
    )


def sample_generate(
    config: LlamaConfig,
    params: dict,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    rng: jnp.ndarray,
    temperature: float = 1.0,
    top_k: int = 0,
    max_seq: int = 0,
) -> jnp.ndarray:
    """Temperature / top-k sampling over the same cache machinery.
    ``top_k=0`` samples the full distribution; ``top_k=1`` or
    ``temperature=0`` degenerate to greedy."""
    assert 0 <= top_k <= config.vocab_size, (
        f"top_k={top_k} out of range for vocab {config.vocab_size}"
    )
    if temperature <= 0.0 or top_k == 1:
        return greedy_generate(
            config, params, prompt, max_new_tokens, max_seq
        )

    def pick(logits, i):
        step_rng = jax.random.fold_in(rng, i)
        scaled = logits / temperature
        if top_k > 0:
            kth = lax.top_k(scaled, top_k)[0][:, -1:]
            scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
        return jax.random.categorical(step_rng, scaled, axis=-1)

    return _generate(
        config, params, prompt, max_new_tokens, max_seq, pick=pick
    )
