"""Client side of per-process chip multiplexing.

Workload processes in a shared-claim container cooperate through the
claim's control daemon (:mod:`tpu_dra.plugin.multiplexd`): acquire the
chip lease before running device work, release it after. CDI injects
``TPU_MULTIPLEX_SOCKET_DIR`` + ``TPU_PROCESS_MULTIPLEXING=true`` into
multiplexed containers, so ``auto_lease()`` is a no-op everywhere else —
workloads can call it unconditionally.

    from tpu_dra.workloads.multiplex_client import auto_lease

    with auto_lease() as lease:
        ...  # device work; lease is None when not multiplexed
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra.plugin.multiplexd import SOCKET_NAME


@dataclass
class Lease:
    chips: List[str] = field(default_factory=list)
    hbm_limits: Dict[str, str] = field(default_factory=dict)
    max_hold_seconds: float = 0.0


class MultiplexClient:
    def __init__(self, socket_dir: str, client_name: Optional[str] = None):
        self.socket_path = os.path.join(socket_dir, SOCKET_NAME)
        self.client_name = client_name or f"pid-{os.getpid()}"
        self._sock: Optional[socket.socket] = None
        self._file = None
        # Times maybe_yield() actually rotated the lease (released and
        # re-acquired because a peer was waiting at the quantum).
        self.rotations = 0

    def _rpc(self, msg: dict) -> dict:
        if self._sock is None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.connect(self.socket_path)
            self._file = self._sock.makefile("rb")
        self._sock.sendall(json.dumps(msg).encode() + b"\n")
        line = self._file.readline()
        if not line:
            raise ConnectionError("multiplex daemon closed the connection")
        return json.loads(line)

    def acquire(self) -> Lease:
        """Blocks until this process holds the chip lease."""
        resp = self._rpc({"op": "acquire", "client": self.client_name})
        if not resp.get("ok"):
            raise RuntimeError(f"lease acquire failed: {resp}")
        self._acquired_at = time.monotonic()
        body = resp["lease"]
        return Lease(
            chips=body.get("chips", []),
            hbm_limits=body.get("hbmLimits", {}),
            max_hold_seconds=body.get("maxHoldSeconds", 0.0),
        )

    def maybe_yield(self, lease: Lease) -> Lease:
        """Cooperative time-slice rotation: call between work steps. When
        this process has held the chip past the lease quantum AND another
        client is waiting, release and re-acquire (FIFO puts us behind the
        waiters); otherwise keep the lease. The quantum comes from the
        claim's time-slice interval (or compute-share %) via the daemon —
        this is where a ``sharing: timeSlicing`` claim actually changes
        scheduling behavior."""
        if lease.max_hold_seconds <= 0:
            return lease
        held = time.monotonic() - getattr(self, "_acquired_at", 0.0)
        if held < lease.max_hold_seconds:
            return lease
        if self.status().get("waiting", 0) == 0:
            # Alone on the chip: restart the quantum rather than paying a
            # pointless release/acquire round-trip.
            self._acquired_at = time.monotonic()
            return lease
        self.release()
        lease = self.acquire()
        self.rotations += 1
        return lease

    def release(self) -> None:
        resp = self._rpc({"op": "release"})
        if not resp.get("ok"):
            # The daemon no longer considers us the holder (revoked or
            # double-released) — surface it, silent success would let the
            # workload re-enter device work on stale assumptions.
            raise RuntimeError(f"lease release refused: {resp}")

    def status(self) -> dict:
        return self._rpc({"op": "status"})

    def close(self) -> None:
        if self._sock is not None:
            # Closing the connection releases anything we hold server-side.
            self._sock.close()
            self._sock = None
            self._file = None

    @contextlib.contextmanager
    def lease(self):
        lease = self.acquire()
        try:
            yield lease
        finally:
            self.release()


@contextlib.contextmanager
def auto_lease(environ=os.environ):
    """Hold the chip lease for the block iff this process runs in a
    multiplexed container; yields the Lease or None."""
    if environ.get("TPU_PROCESS_MULTIPLEXING") != "true":
        yield None
        return
    client = MultiplexClient(environ["TPU_MULTIPLEX_SOCKET_DIR"])
    try:
        with client.lease() as lease:
            yield lease
    finally:
        client.close()
