"""Client side of per-process chip multiplexing.

Workload processes in a shared-claim container cooperate through the
claim's control daemon (:mod:`tpu_dra.plugin.multiplexd`): acquire the
chip lease before running device work, release it after. CDI injects
``TPU_MULTIPLEX_SOCKET_DIR`` + ``TPU_PROCESS_MULTIPLEXING=true`` into
multiplexed containers, so ``auto_lease()`` is a no-op everywhere else —
workloads can call it unconditionally.

    from tpu_dra.workloads.multiplex_client import auto_lease

    with auto_lease() as lease:
        ...  # device work; lease is None when not multiplexed
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra.plugin.multiplexd import SOCKET_NAME


@dataclass
class Lease:
    chips: List[str] = field(default_factory=list)
    hbm_limits: Dict[str, str] = field(default_factory=dict)
    max_hold_seconds: float = 0.0


class LeaseCooldownError(RuntimeError):
    """Acquire refused: this client was revoked for hogging and is in its
    post-revocation cooldown. ``retry_after`` says when to try again."""

    def __init__(self, retry_after: float, resp: dict):
        super().__init__(f"lease refused for {retry_after}s: {resp}")
        self.retry_after = retry_after


class MultiplexClient:
    def __init__(self, socket_dir: str, client_name: Optional[str] = None):
        self.socket_path = os.path.join(socket_dir, SOCKET_NAME)
        self.client_name = client_name or f"pid-{os.getpid()}"
        self._sock: Optional[socket.socket] = None
        self._file = None
        # Times maybe_yield() actually rotated the lease (released and
        # re-acquired because a peer was waiting at the quantum).
        self.rotations = 0
        # Set when the daemon revoked our lease (async "revoked" event);
        # cleared on the next acquire/release.
        self.revoked = False
        # Lifetime count of revocations this client suffered.
        self.revocations = 0

    def _rpc(self, msg: dict) -> dict:
        if self._sock is None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.connect(self.socket_path)
            self._file = self._sock.makefile("rb")
        self._sock.sendall(json.dumps(msg).encode() + b"\n")
        while True:
            line = self._file.readline()
            if not line:
                raise ConnectionError("multiplex daemon closed the connection")
            obj = json.loads(line)
            # Async server→client pushes (revocation notices) may arrive
            # interleaved with responses; fold them into client state and
            # keep reading for the actual response.
            if "event" in obj:
                self._handle_event(obj)
                continue
            return obj

    def _handle_event(self, obj: dict) -> None:
        if obj.get("event") == "revoked":
            self.revoked = True
            self.revocations += 1

    def acquire(self) -> Lease:
        """Blocks until this process holds the chip lease. Raises
        :class:`LeaseCooldownError` when refused because a prior hold was
        revoked (the daemon names the retry-after)."""
        resp = self._rpc({"op": "acquire", "client": self.client_name})
        if not resp.get("ok"):
            if "retryAfterSeconds" in resp:
                raise LeaseCooldownError(resp["retryAfterSeconds"], resp)
            raise RuntimeError(f"lease acquire failed: {resp}")
        self.revoked = False
        self._acquired_at = time.monotonic()
        body = resp["lease"]
        return Lease(
            chips=body.get("chips", []),
            hbm_limits=body.get("hbmLimits", {}),
            max_hold_seconds=body.get("maxHoldSeconds", 0.0),
        )

    def maybe_yield(self, lease: Lease) -> Lease:
        """Cooperative time-slice rotation: call between work steps. When
        this process has held the chip past the lease quantum AND another
        client is waiting, release and re-acquire (FIFO puts us behind the
        waiters); otherwise keep the lease. The quantum comes from the
        claim's time-slice interval (or compute-share %) via the daemon —
        this is where a ``sharing: timeSlicing`` claim actually changes
        scheduling behavior."""
        if lease.max_hold_seconds <= 0:
            return lease
        held = time.monotonic() - getattr(self, "_acquired_at", 0.0)
        if not self.revoked and held < lease.max_hold_seconds:
            return lease
        if self.revoked:
            # The daemon already took the lease (we out-held the quantum,
            # e.g. one slow step); nothing to release — re-acquire, waiting
            # out the cooldown if the daemon imposes one.
            self.revoked = False
            lease = self._acquire_through_cooldown()
            self.rotations += 1
            return lease
        waiting = self.status().get("waiting", 0)
        if self.revoked:
            # The status() read drained a revocation event: the lease is
            # already gone, skip the release.
            self.revoked = False
            lease = self._acquire_through_cooldown()
            self.rotations += 1
            return lease
        if waiting == 0:
            # Alone on the chip: restart the quantum rather than paying a
            # pointless release/acquire round-trip.
            self._acquired_at = time.monotonic()
            return lease
        self.release()
        # A revocation can land between the status() read and the release
        # (the daemon's sweeper races us at the quantum boundary); the
        # re-acquire must wait out any cooldown rather than leak a
        # LeaseCooldownError from a cooperative rotation.
        lease = self._acquire_through_cooldown()
        self.rotations += 1
        return lease

    def _acquire_through_cooldown(self) -> Lease:
        while True:
            try:
                return self.acquire()
            except LeaseCooldownError as e:
                time.sleep(min(e.retry_after, 5.0))

    def release(self) -> None:
        was_revoked, self.revoked = self.revoked, False
        resp = self._rpc({"op": "release"})
        if not resp.get("ok"):
            if was_revoked or self.revoked:
                # The daemon revoked us before the release landed; the
                # lease is gone, which is exactly what release wants.
                self.revoked = False
                return
            # The daemon no longer considers us the holder (revoked or
            # double-released) — surface it, silent success would let the
            # workload re-enter device work on stale assumptions.
            raise RuntimeError(f"lease release refused: {resp}")

    def status(self) -> dict:
        return self._rpc({"op": "status"})

    def close(self) -> None:
        if self._sock is not None:
            # Closing the connection releases anything we hold server-side.
            self._sock.close()
            self._sock = None
            self._file = None

    @contextlib.contextmanager
    def lease(self):
        lease = self.acquire()
        try:
            yield lease
        finally:
            self.release()


@contextlib.contextmanager
def auto_lease(environ=os.environ):
    """Hold the chip lease for the block iff this process runs in a
    multiplexed container; yields the Lease or None."""
    if environ.get("TPU_PROCESS_MULTIPLEXING") != "true":
        yield None
        return
    client = MultiplexClient(environ["TPU_MULTIPLEX_SOCKET_DIR"])
    try:
        with client.lease() as lease:
            yield lease
    finally:
        client.close()
