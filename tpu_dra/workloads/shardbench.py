"""Mesh-sharded decode CPU smoke — ``make shardbench`` (wired into
``ci``).

A hardware-free gate on the ISSUE 8 sharded serving path: the SNIPPETS
[3] GSPMD pattern (a (batch x model) mesh + NamedSharding, jit inserting
the collectives) must run the SAME decode program across a multi-chip
mesh and produce TOKEN-IDENTICAL output to the single-chip program —
the exactness contract workloads/parallel/mesh.py documents (the model
axis shards only non-contracted dimensions, so no psum ever reorders an
fp32 reduction). Asserts:

1. the decode mesh ladder degrades gracefully: 1 device -> (1, 1),
   2 devices -> (1, 2), and the model axis clamps to divide the model's
   kv heads / ffn / vocab;
2. **greedy path parity**: ``greedy_generate`` over decode-sharded
   params on the (1, 2) mesh is token-identical to the unsharded run
   (and to the trivially-sharded (1, 1) mesh);
3. **engine parity**: a full continuous-batching engine trace with
   ``EngineConfig(sharded=True)`` — params, KV page pools, and batch
   arrays NamedSharded — completes token-identical to the unsharded
   engine, including a sampled (temperature/top-k) configuration;
4. the sharded params actually ARE sharded: at least one kernel's
   sharding spec names the model axis (a silent fall-through to
   replicated-everything would void the scaling claim).

Prints one JSON line; exits nonzero on any violation — the same
contract as bench.py legs, so CI sees a regression before a TPU run
does. On real hardware the same wiring records ``decode_sharded_tok_s``
in bench.py's ``--leg-decode`` (docs/serving.md "Decode roofline").
"""

from __future__ import annotations

import json
import sys
import time


def main(argv=None) -> int:
    import dataclasses

    from tpu_dra.workloads import force_cpu_devices

    force_cpu_devices(2)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dra.workloads.engine import Engine, EngineConfig, Request
    from tpu_dra.workloads.generate import greedy_generate
    from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama
    from tpu_dra.workloads.parallel import mesh as meshlib

    report = {"ok": False}
    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=8)

    # (1) ladder + clamp.
    assert meshlib.decode_mesh_shape(1, cfg) == (1, 1)
    assert meshlib.decode_mesh_shape(2, cfg) == (1, 2)
    # 8 devices would want model=4, but TINY_LLAMA has 2 kv heads: the
    # model axis must clamp to 2 and fold the rest into batch.
    assert meshlib.decode_mesh_shape(8, cfg) == (4, 2)
    devices = jax.devices()
    assert len(devices) >= 2, f"need >= 2 cpu devices, got {len(devices)}"
    mesh1 = meshlib.build_decode_mesh(cfg, devices[:1])
    mesh2 = meshlib.build_decode_mesh(cfg, devices[:2])
    report["mesh_shapes"] = [dict(mesh1.shape), dict(mesh2.shape)]
    assert dict(mesh2.shape) == {"batch": 1, "model": 2}

    # (4) the sharding rules engage (not replicated-everything).
    shardings = meshlib.decode_param_shardings(mesh2, params)
    leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec")
    )
    assert any("model" in str(s.spec) for s in leaves), (
        "no param leaf is sharded over the model axis"
    )

    # (2) greedy path parity across (none, (1,1), (1,2)).
    prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    new_tokens = 12
    fn = jax.jit(
        lambda p, t: greedy_generate(cfg, p, t, max_new_tokens=new_tokens)
    )
    base = np.asarray(fn(params, prompt))
    for mesh in (mesh1, mesh2):
        sp = meshlib.shard_decode_params(mesh, params)
        t0 = time.monotonic()
        out = np.asarray(fn(sp, prompt))
        dt = time.monotonic() - t0
        label = f"{mesh.shape['batch']}x{mesh.shape['model']}"
        assert np.array_equal(base, out), (
            f"sharded greedy decode diverged from single-chip on {label}"
        )
        report[f"greedy_parity_{label}"] = True
        report[f"greedy_seconds_{label}"] = round(dt, 2)

    # (3) engine parity, greedy and sampled, over a mixed-length trace.
    def trace(seed=3, n=6):
        rng = np.random.default_rng(seed)
        return [
            Request(
                rid=f"r{i}",
                prompt=rng.integers(
                    1, cfg.vocab_size, int(rng.integers(2, 12))
                ).astype(np.int32),
                max_new_tokens=int(rng.integers(1, 8)),
            )
            for i in range(n)
        ]

    def ec(**kw):
        base_kw = dict(
            page_size=4, max_slots=3, max_pages_per_seq=10,
            scan_chunk=3, prefill_chunk=5,
        )
        base_kw.update(kw)
        return EngineConfig(**base_kw)

    for name, kw in (
        ("greedy", {}),
        ("sampled", {"temperature": 0.8, "top_k": 8, "sample_seed": 5}),
    ):
        plain = Engine(cfg, params, ec(**kw)).run(trace())
        sharded_eng = Engine(cfg, params, ec(sharded=True, **kw))
        assert sharded_eng.mesh is not None
        sharded = sharded_eng.run(trace())
        assert set(plain) == set(sharded)
        mismatches = [
            rid for rid in plain
            if not np.array_equal(plain[rid].tokens, sharded[rid].tokens)
        ]
        assert not mismatches, (
            f"sharded {name} engine diverged from unsharded on "
            f"{mismatches}"
        )
        report[f"engine_parity_{name}"] = len(plain)

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
