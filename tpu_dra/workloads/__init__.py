"""JAX/XLA workloads the driver schedules (and benchmarks against).

This is the *payload* side of the TPU re-imagining: the reference driver's
smoke/perf loads are CUDA binaries (nvbandwidth, nbody —
demo/specs/imex/nvbandwidth-test-job.yaml, quickstart/gpu-test5.yaml); ours
are JAX programs designed TPU-first:

- ``models/``   — the flagship Llama-3 family (flax), bf16, GQA + RoPE +
  SwiGLU, scan-over-layers for compile time
- ``ops/``      — pallas TPU kernels (flash attention) with XLA fallbacks
- ``parallel/`` — mesh construction from the driver-injected bootstrap env,
  parameter/activation sharding rules (dp/fsdp/sp/tp), ring attention for
  sequence parallelism over ICI
- ``train.py``  — pjit'd training step with rematerialization
- ``smoke.py``  — the pmap psum multi-chip smoke test (BASELINE config 2)
"""
