"""JAX/XLA workloads the driver schedules (and benchmarks against).

This is the *payload* side of the TPU re-imagining: the reference driver's
smoke/perf loads are CUDA binaries (nvbandwidth, nbody —
demo/specs/imex/nvbandwidth-test-job.yaml, quickstart/gpu-test5.yaml); ours
are JAX programs designed TPU-first:

- ``models/``   — the flagship Llama-3 family (flax), bf16, GQA + RoPE +
  SwiGLU, scan-over-layers for compile time
- ``ops/``      — pallas TPU kernels (flash attention) with XLA fallbacks
- ``parallel/`` — mesh construction from the driver-injected bootstrap env,
  parameter/activation sharding rules (dp/fsdp/sp/tp), ring attention for
  sequence parallelism over ICI
- ``train.py``  — pjit'd training step with rematerialization
- ``smoke.py``  — the pmap psum multi-chip smoke test (BASELINE config 2)
"""


def apply_forced_platform(environ=None) -> None:
    """Honor ``TPU_DRA_FORCE_PLATFORM=<platform>[:N]`` (e.g. ``cpu:1``):
    re-pin the jax backend before first use. Env vars alone are not
    enough on hosts whose interpreter startup already imported jax
    against a real accelerator (sitecustomize + device tunnel); the
    minicluster's workload-image runtime profile sets this — kind's
    equivalent is simply not mounting the TPU into the container.
    Called at the top of every workload main()."""
    import os

    spec = (environ or os.environ).get("TPU_DRA_FORCE_PLATFORM", "")
    if not spec:
        return
    platform, _, n = spec.partition(":")
    import jax
    from jax.extend.backend import clear_backends

    clear_backends()
    jax.config.update("jax_platforms", platform)
    if n and platform == "cpu":
        jax.config.update("jax_num_cpu_devices", int(n))
