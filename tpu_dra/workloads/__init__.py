"""JAX/XLA workloads the driver schedules (and benchmarks against).

This is the *payload* side of the TPU re-imagining: the reference driver's
smoke/perf loads are CUDA binaries (nvbandwidth, nbody —
demo/specs/imex/nvbandwidth-test-job.yaml, quickstart/gpu-test5.yaml); ours
are JAX programs designed TPU-first:

- ``models/``   — the flagship Llama-3 family (flax), bf16, GQA + RoPE +
  SwiGLU, scan-over-layers for compile time
- ``ops/``      — pallas TPU kernels (flash attention) with XLA fallbacks
- ``parallel/`` — mesh construction from the driver-injected bootstrap env,
  parameter/activation sharding rules (dp/fsdp/sp/tp), ring attention for
  sequence parallelism over ICI
- ``train.py``  — pjit'd training step with rematerialization
- ``smoke.py``  — the pmap psum multi-chip smoke test (BASELINE config 2)
"""


def force_cpu_devices(n: int) -> None:
    """Re-pin jax onto ``n`` virtual CPU devices even when the
    interpreter already imported jax (sitecustomize + device tunnel).
    Newer JAX exposes this as the ``jax_num_cpu_devices`` config option;
    older JAX only has the XLA flag spelling, which works as long as no
    backend consumed XLA_FLAGS yet (XLA parses it once per process) —
    when it cannot take effect, fail loudly rather than leave the caller
    sharding over 1 device."""
    import os

    import jax
    from jax.extend.backend import clear_backends

    # Probed BEFORE clear_backends, without creating one (device_count()
    # would both initialize a backend — breaking a later
    # jax.distributed.initialize() — and consume XLA_FLAGS).
    backend_was_initialized = bool(
        getattr(
            getattr(jax, "_src", None) and jax._src.xla_bridge,
            "_backends",
            None,
        )
    )
    clear_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:
        flags = os.environ.get("XLA_FLAGS", "")
        flags = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={int(n)}"
        ).strip()
        if backend_was_initialized:
            # XLA parses XLA_FLAGS once per process: a backend built
            # before this call pinned the old value, so the env write
            # above cannot take effect. Fail loudly rather than leave the
            # caller silently sharding over 1 device.
            raise RuntimeError(
                "this JAX has no jax_num_cpu_devices option and a backend "
                "was already initialized, so the XLA_FLAGS fallback cannot "
                "take effect (XLA parses it once per process). Set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={int(n)} "
                "before starting the interpreter."
            )


def apply_forced_platform(environ=None) -> None:
    """Honor ``TPU_DRA_FORCE_PLATFORM=<platform>[:N]`` (e.g. ``cpu:1``):
    re-pin the jax backend before first use. Env vars alone are not
    enough on hosts whose interpreter startup already imported jax
    against a real accelerator (sitecustomize + device tunnel); the
    minicluster's workload-image runtime profile sets this — kind's
    equivalent is simply not mounting the TPU into the container.
    Called at the top of every workload main()."""
    import os

    spec = (environ or os.environ).get("TPU_DRA_FORCE_PLATFORM", "")
    if not spec:
        return
    platform, _, n = spec.partition(":")
    import jax
    from jax.extend.backend import clear_backends

    if n and platform == "cpu":
        force_cpu_devices(int(n))
        return
    clear_backends()
    jax.config.update("jax_platforms", platform)
