"""Speculative-decoding + prefix-sharing + batched-prefill CPU smoke —
``make specbench`` (wired into ``ci``), the hardware-free gate on the
ISSUE 15 serving-engine optimizations.

Hard contract asserts (exit nonzero on any violation — the same shape
as every other bench smoke, so CI sees a regression before a TPU run
does):

1. **spec == oracle token identity, greedy AND sampled**: the
   speculative engine (n-gram draft + one jitted K+1-position verify
   per iteration) must be TOKEN-IDENTICAL to the unfused per-token /
   contiguous-page oracle on a lookup-friendly trace (real acceptance)
   AND on a rejection-heavy random trace (the rewind path under fire)
   AND with an adversarial always-wrong draft source — a proposer can
   only affect speed, never tokens;
2. **rewind hygiene**: after a rejection-heavy run, the page allocator
   is leak-free and every non-scratch page is fully zeroed (rejected
   draft K/V was rewound: boundary tails re-zeroed in place, dropped
   pages through the batch zero path);
3. **COW prompt fleet**: N sequences sharing one system prompt
   (prefix_id + incref + copy-on-write) allocate a fraction of the
   private fleet's peak pages — the saving is asserted against the
   shared prefix's page count, with token identity and zero leaks
   checked inside the fleet helper;
4. **batched prefill beats serial TTFT**: the same admission burst
   through the bucket-packing schedule must cut first-token p50 vs the
   one-sequence-per-iteration schedule.

The timed spec-vs-nonspec throughput gate lives in ``bench.py
--leg-serve`` (hard on TPU, warning on CPU drill sizes /
``BENCH_ALLOW_SERVE_GAP=1`` — per-chunk host dispatch swamps the tiny
CPU matmuls, so only on-chip ratios mean anything).
"""

from __future__ import annotations

import json
import sys

import numpy as np


def _model():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    return cfg, params


def _lookup_reqs(cfg, n=5, seed=3, max_new=20):
    """Repetitive prompts: the n-gram proposer has structure to hit."""
    from tpu_dra.workloads.engine import Request

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        motif = rng.integers(1, cfg.vocab_size, 6).astype(np.int32)
        out.append(
            Request(
                rid=f"l{i}", prompt=np.tile(motif, 4)[:22],
                max_new_tokens=max_new,
            )
        )
    return out


def _random_reqs(cfg, n=6, seed=11):
    """Structureless prompts: near-zero acceptance — every verify pass
    exercises the rewind."""
    from tpu_dra.workloads.engine import Request

    rng = np.random.default_rng(seed)
    return [
        Request(
            rid=f"r{i}",
            prompt=rng.integers(
                1, cfg.vocab_size, int(rng.integers(4, 15))
            ).astype(np.int32),
            max_new_tokens=int(rng.integers(3, 12)),
        )
        for i in range(n)
    ]


def _assert_identical(got, want, label):
    assert set(got) == set(want), (
        f"{label}: completion sets differ: {set(got) ^ set(want)}"
    )
    bad = [
        rid for rid in got
        if not np.array_equal(got[rid].tokens, want[rid].tokens)
    ]
    assert not bad, f"{label}: tokens diverged from the oracle on {bad}"


def main(argv=None) -> int:
    import dataclasses

    from tpu_dra.workloads import paged_kv
    from tpu_dra.workloads.engine import Engine, EngineConfig
    from tpu_dra.workloads.enginebench import (
        run_prefill_ttft_pair,
        run_prefix_fleet,
    )
    from tpu_dra.workloads.ops import attention as A
    from tpu_dra.workloads.specdraft import StaticDraft

    report = {"ok": False}
    cfg, params = _model()

    def ec(**kw):
        base = dict(
            page_size=4, max_slots=3, max_pages_per_seq=16,
            scan_chunk=3, prefill_chunk=8,
        )
        base.update(kw)
        return EngineConfig(**base)

    def rerun(reqs_fn, config, **engine_kw):
        eng = Engine(cfg, params, config, **engine_kw)
        return eng.run(reqs_fn()), eng

    # (1a) greedy spec parity on the lookup-friendly trace, with real
    # acceptance (a 0-acceptance run would vacuously "verify" nothing).
    # NOTE: _LAST_MULTIQUERY_IMPL can't detect a dead verify path —
    # batched prefill dispatches the same multiquery op. The verify
    # pass having actually run is asserted through spec_proposed /
    # spec_accepted below (only _spec_tick moves them).
    A._LAST_MULTIQUERY_IMPL = None
    spec, eng = rerun(lambda: _lookup_reqs(cfg), ec(spec_k=4))
    assert A._LAST_MULTIQUERY_IMPL is not None, (
        "the engine never dispatched the multiquery op at all"
    )
    oracle, _ = rerun(
        lambda: _lookup_reqs(cfg), ec(fused=False, contiguous=True)
    )
    _assert_identical(spec, oracle, "greedy lookup")
    rate = eng.spec_accepted / max(eng.spec_proposed, 1)
    assert eng.spec_proposed > 0 and rate > 0.2, (
        f"lookup trace acceptance {rate:.3f} over {eng.spec_proposed} "
        f"proposals — the n-gram proposer is not engaging"
    )
    report["lookup_accept_rate"] = round(rate, 4)
    report["lookup_proposed"] = eng.spec_proposed

    # (1b) sampled spec parity — the (seed, serial, position) schedule
    # makes acceptance exact under sampling too.
    samp = dict(temperature=0.8, top_k=8, sample_seed=11)
    sspec, _ = rerun(lambda: _lookup_reqs(cfg), ec(spec_k=4, **samp))
    soracle, _ = rerun(
        lambda: _lookup_reqs(cfg),
        ec(fused=False, contiguous=True, **samp),
    )
    _assert_identical(sspec, soracle, "sampled lookup")

    # (1c) rejection-heavy trace (random prompts): parity + (2) rewind
    # hygiene — leak-free allocator, fully-zeroed pool.
    rspec, reng = rerun(lambda: _random_reqs(cfg), ec(spec_k=4))
    roracle, _ = rerun(
        lambda: _random_reqs(cfg), ec(fused=False, contiguous=True)
    )
    _assert_identical(rspec, roracle, "rejection-heavy")
    rej_rate = reng.spec_accepted / max(reng.spec_proposed, 1)
    alloc = reng.allocator
    assert alloc.free_pages == alloc.num_pages - 1, (
        "rejection-heavy spec run leaked pages"
    )
    assert alloc.reserved_pages == 0, "reservation leak"
    assert paged_kv.pages_are_zero(
        reng.cache, list(range(1, alloc.num_pages))
    ), "rewind left unzeroed pages (zero-tail invariant)"
    report["rejection_accept_rate"] = round(rej_rate, 4)

    # (1d) adversarial proposer: always-wrong drafts cost throughput,
    # never tokens.
    wrong = StaticDraft(np.zeros(8, np.int32) + 1)
    adv, _ = rerun(
        lambda: _random_reqs(cfg, seed=17), ec(spec_k=3),
        draft_source=wrong,
    )
    aoracle, _ = rerun(
        lambda: _random_reqs(cfg, seed=17),
        ec(fused=False, contiguous=True),
    )
    _assert_identical(adv, aoracle, "adversarial draft")

    # (3) COW prompt fleet: pages saved vs the private twin (token
    # identity + leak/zero asserted inside the helper).
    fleet_n = 6
    fl = run_prefix_fleet(
        cfg, params, fleet_n=fleet_n, prompt_len=17, max_new=6,
        page_size=4, vocab=cfg.vocab_size,
    )
    n_full = (17 - 1) // 4  # page-aligned shared prefix pages
    want_saved = (fleet_n - 1) * n_full
    assert fl["prefix_pages_saved"] >= want_saved - 1, (
        f"COW fleet saved {fl['prefix_pages_saved']} pages; expected "
        f"~{want_saved} ((N-1) x shared prefix pages) — sharing is "
        f"not engaging"
    )
    assert fl["prefix_attached"] >= fleet_n - 1, (
        f"only {fl['prefix_attached']} of {fleet_n - 1} followers "
        f"attached via incref"
    )
    report["prefix_pages_saved"] = fl["prefix_pages_saved"]
    report["prefix_private_peak"] = fl["private_peak_pages"]
    report["prefix_shared_peak"] = fl["shared_peak_pages"]

    # (4) batched prefill beats the serialized schedule on first-token
    # p50 for the same admission burst.
    pair = run_prefill_ttft_pair(
        cfg, params,
        EngineConfig(
            page_size=4, max_slots=6, max_pages_per_seq=10,
            scan_chunk=3, prefill_chunk=16,
        ),
        burst_n=6, prompt_len=12, vocab=cfg.vocab_size,
    )
    assert pair["batched_ttft_p50_ms"] < pair["serial_ttft_p50_ms"], (
        f"batched prefill p50 {pair['batched_ttft_p50_ms']} ms did not "
        f"beat serial {pair['serial_ttft_p50_ms']} ms"
    )
    report["prefill_batched_ttft_p50_ms"] = pair["batched_ttft_p50_ms"]
    report["prefill_serial_ttft_p50_ms"] = pair["serial_ttft_p50_ms"]

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
