"""Fused (chunked) next-token cross-entropy.

The naive loss materializes fp32 logits for the whole batch —
``[b, s, vocab]`` is ~800 MB at bench shapes (b=6, s=1024, v=32k) — and
the autodiff residuals keep them live through the backward pass, so the
LM head dominates HBM pressure on a 16 GiB chip. This computes the same
``mean(logsumexp(logits) - logits[target])`` streamed over sequence
chunks under a ``lax.scan``: only ``[b, chunk, vocab]`` logits exist at
a time, and ``jax.checkpoint`` on the chunk body recomputes them in the
backward pass instead of saving them.

No reference counterpart (the reference is a DRA driver, not a trainer);
the technique is the standard blockwise-loss companion to flash
attention (same rationale as ops/attention.py's streaming softmax).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _padded_len(seq: int, chunk: int) -> int:
    """seq rounded up to a whole number of chunks."""
    return ((seq + chunk - 1) // chunk) * chunk


def fused_next_token_xent(
    x: jnp.ndarray,
    kernel: jnp.ndarray,
    tokens: jnp.ndarray,
    chunk: int = 256,
) -> jnp.ndarray:
    """Mean next-token cross entropy without whole-sequence logits.

    x       [b, s, d]  final hidden states (compute dtype, e.g. bf16)
    kernel  [d, vocab] LM-head weight (taken straight from the param
                       tree so gradients flow to it)
    tokens  [b, s]     int token ids; position i is scored against
                       tokens[i+1], the final position is masked out
    """
    b, s, d = x.shape
    # Shapes are static at trace time, so a plain assert fails loudly:
    # s == 1 has no next token to score and the 1/(b*(s-1)) normalizer
    # would silently produce inf/NaN.
    assert s >= 2, f"fused_next_token_xent needs seq >= 2, got {s}"
    # Uniform chunks with a masked tail: predict tokens[:, 1:] from
    # x[:, :-1] by shifting targets left and zero-weighting the last
    # position, then zero-pad the sequence up to a whole number of
    # chunks (zero weight again) so every scan step has the same static
    # shape at the REQUESTED chunk size — no divisor fallback that
    # could degenerate to chunk=1 on awkward sequence lengths.
    c = min(chunk, s)
    padded = _padded_len(s, c)
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((b, 1 + padded - s), tokens.dtype)],
        axis=1,
    )
    weights = jnp.concatenate(
        [
            jnp.ones((b, s - 1), jnp.float32),
            jnp.zeros((b, 1 + padded - s), jnp.float32),
        ],
        axis=1,
    )
    if padded != s:
        x = jnp.concatenate(
            [x, jnp.zeros((b, padded - s, d), x.dtype)], axis=1
        )
    n = padded // c
    xc = x.reshape(b, n, c, d).transpose(1, 0, 2, 3)  # [n, b, c, d]
    tc = targets.reshape(b, n, c).transpose(1, 0, 2)
    wc = weights.reshape(b, n, c).transpose(1, 0, 2)

    k = kernel.astype(x.dtype)

    @jax.checkpoint
    def chunk_loss(xk, tk, wk):
        # Same numerics as the unfused head: matmul in compute dtype,
        # softmax statistics in fp32 (llama.py casts logits to fp32).
        logits = (xk @ k).astype(jnp.float32)  # [b, c, vocab]
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tk[..., None], axis=-1)[..., 0]
        return jnp.sum((lse - tgt) * wk)

    def body(acc, xtw):
        return acc + chunk_loss(*xtw), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xc, tc, wc))
    return total / (b * (s - 1))
