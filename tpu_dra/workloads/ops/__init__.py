"""TPU kernels (pallas) and their XLA fallbacks."""
