"""Fused decode MLP+norm block (the non-attention half of the roofline).

BENCH_r05 pinned the open decode gap: the fused step runs 3.16x the
bf16 HBM floor while the ATTENTION inside it is already at 1.046x its
own floor — the waste is everything around attention, and the largest
single slab is the MLP (gate/up/down are ~2/3 of per-layer weight
bytes). This module fuses the decode step's ``rms-norm -> gate/up ->
silu*mul -> down -> +residual`` chain for the s=1 case:

- **pallas** (TPU): one kernel, grid over ffn blocks. The normalized
  activation is computed ONCE into VMEM scratch; each grid step streams
  a ``[d, block_f]`` slab of w_gate/w_up and the matching ``[block_f,
  d]`` slab of w_down through VMEM, accumulating the down-projection in
  an fp32 scratch and writing ``x + acc`` on the last step. Nothing in
  the chain round-trips through HBM between the norm and the residual
  add — weight bytes are read exactly once, which is the roofline's
  floor assumption;
- **xla** (CPU tests, fallback): the EXACT op sequence generate.py's
  decode step has always run (same _rms / matmul ordering), so
  dispatching through this module changes nothing numerically off-TPU —
  every existing parity oracle (paged-vs-unpaged engine trace,
  teacher-forced decode-vs-forward) keeps its bit-level meaning;
- **reference**: naive fp32, the numerics oracle for the kernel's
  interpret-mode tests.

int8 weight-only trees ({"kernel_q", "scale"} leaves) take the xla path
(ops/int8mm.py handles the in-flight dequant); the pallas kernel covers
the plain-kernel layouts. Dispatch mirrors decode_attention: ``impl``
"auto" | "pallas" | "xla" | "reference", with a trace-time
``_LAST_DECODE_MLP_IMPL`` probe decodebench asserts on.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dra.workloads.ops.attention import flash_platform_ok

_LAST_DECODE_MLP_IMPL = None  # set at trace time; decodebench asserts

# One w_gate/w_up/w_down slab triple must fit VMEM with headroom for the
# activation scratch and double-buffering (see attention.py's budget).
_VMEM_MLP_BUDGET_BYTES = 8 * 1024 * 1024


def _kernels(mlp: dict):
    """(w_gate, w_up, w_down) plain kernels, or None when the tree is
    int8 weight-only (or otherwise not bare 2D kernels)."""
    try:
        ws = tuple(mlp[n]["kernel"] for n in ("w_gate", "w_up", "w_down"))
    except (KeyError, TypeError):
        return None
    if any(w.ndim != 2 for w in ws):
        return None
    return ws


def _matmul(x: jnp.ndarray, w: dict) -> jnp.ndarray:
    """generate._mm's dispatch, inlined to keep the layer DAG acyclic
    (generate imports this module): plain {"kernel"} or int8 weight-only
    {"kernel_q", "scale"} through ops/int8mm.py."""
    if "kernel_q" in w:
        from tpu_dra.workloads.ops.int8mm import int8_matmul

        return int8_matmul(x, w["kernel_q"], w["scale"])
    return x @ w["kernel"].astype(x.dtype)


def _rms(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    # Byte-for-byte the op sequence of generate._rms: the xla path must
    # preserve the decode step's existing numerics exactly.
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (
        x32 * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    ).astype(x.dtype)


def _xla_decode_mlp(x, norm_scale, mlp, eps):
    h = _rms(x, norm_scale, eps)
    gate = _matmul(h, mlp["w_gate"])
    up = _matmul(h, mlp["w_up"])
    return x + _matmul(jax.nn.silu(gate) * up, mlp["w_down"])


def reference_decode_mlp(x, norm_scale, mlp, eps):
    """Naive fp32 oracle (plain kernels only)."""
    wg, wu, wd = _kernels(mlp)
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    h = x32 * lax.rsqrt(var + eps) * norm_scale.astype(jnp.float32)
    gate = h @ wg.astype(jnp.float32)
    up = h @ wu.astype(jnp.float32)
    out = (jax.nn.silu(gate) * up) @ wd.astype(jnp.float32)
    return (x32 + out).astype(x.dtype)


def _decode_mlp_kernel(x_ref, s_ref, wg_ref, wu_ref, wd_ref, o_ref,
                       xn_ref, acc_ref, *, eps: float, num_blocks: int):
    """One ffn-block program: partial gate/up/silu/down over a
    ``block_f`` slab, accumulated in fp32 scratch. The normalized
    activation is computed once (first step) into VMEM scratch; the
    output block (constant index map) stays VMEM-resident across the
    whole grid and is written once, on the last step."""
    import jax.experimental.pallas as pl

    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        x32 = x_ref[...].astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        xn_ref[...] = (
            x32 * lax.rsqrt(var + eps)
            * s_ref[...].astype(jnp.float32)
        ).astype(xn_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    h = xn_ref[...]
    gate = jnp.dot(h, wg_ref[...], preferred_element_type=jnp.float32)
    up = jnp.dot(h, wu_ref[...], preferred_element_type=jnp.float32)
    act = (jax.nn.silu(gate) * up).astype(h.dtype)
    acc_ref[...] += jnp.dot(
        act, wd_ref[...], preferred_element_type=jnp.float32
    )

    @pl.when(j == num_blocks - 1)
    def _flush():
        o_ref[...] = (
            x_ref[...].astype(jnp.float32) + acc_ref[...]
        ).astype(o_ref.dtype)


def _pick_block_f(ffn: int, d: int, itemsize: int,
                  target: int) -> "int | None":
    """Largest LANE-ALIGNED (multiple of 128) divisor of ffn at most
    ``target`` whose three weight slabs (two [d, bf] + one [bf, d]) fit
    the VMEM budget, or None when no such width exists (the dispatcher
    then keeps the xla path). Alignment is load-bearing: mosaic rejects
    a [d, bf] BlockSpec whose trailing dim is neither 128-aligned nor
    the full dimension — e.g. ffn 11008's largest plain divisor <= 512
    is 344, which compiles nowhere."""
    cap = _VMEM_MLP_BUDGET_BYTES // max(3 * d * itemsize, 1)
    best = None
    for bf in range(128, min(ffn, target, cap) + 1, 128):
        if ffn % bf == 0:
            best = bf
    return best


def _pallas_decode_mlp(x, norm_scale, wg, wu, wd, eps, block_f):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, d = x.shape
    ffn = wg.shape[1]
    bf = _pick_block_f(ffn, d, wg.dtype.itemsize, block_f)
    if bf is None:
        raise ValueError(
            f"no lane-aligned ffn block <= {block_f} divides ffn {ffn} "
            f"within the VMEM budget; use impl='xla' (auto does)"
        )
    num_blocks = ffn // bf

    whole = lambda j: (0, 0)  # noqa: E731
    col_block = lambda j: (0, j)  # noqa: E731
    row_block = lambda j: (j, 0)  # noqa: E731

    kernel = functools.partial(
        _decode_mlp_kernel, eps=eps, num_blocks=num_blocks
    )
    return pl.pallas_call(
        kernel,
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec((b, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((d, bf), col_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((d, bf), col_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((bf, d), row_block, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, d), whole, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, d), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((b, d), x.dtype),
            pltpu.VMEM((b, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, norm_scale.reshape(1, d), wg, wu, wd)


def _interpret() -> bool:
    from tpu_dra.workloads.ops import attention as A

    return A._INTERPRET


def _mlp_pallas_ok(x, mlp, block_f: int) -> bool:
    ws = _kernels(mlp)
    if ws is None or not flash_platform_ok():
        return False
    d = x.shape[-1]
    # Lane alignment for the streamed slabs — including a viable
    # lane-aligned ffn block width; tiny CPU-test dims fall back to the
    # (numerically identical today) xla path.
    return (
        d % 128 == 0
        and ws[0].shape[1] % 128 == 0
        and _pick_block_f(
            ws[0].shape[1], d, ws[0].dtype.itemsize, block_f
        ) is not None
    )


def decode_mlp(
    x: jnp.ndarray,
    norm_scale: jnp.ndarray,
    mlp: dict,
    eps: float,
    impl: str = "auto",
    block_f: int = 512,
) -> jnp.ndarray:
    """The decode step's full post-attention block for a [b, d] token
    batch: ``x + w_down(silu(w_gate(rms(x))) * w_up(rms(x)))``.

    ``mlp`` is the layer's param subtree ({"w_gate", "w_up", "w_down"},
    plain or int8 weight-only leaves). impl "auto" picks the pallas
    kernel on TPU for plain-kernel trees and the xla chain otherwise;
    the xla chain is op-for-op the path generate.py always ran, so
    off-TPU numerics are unchanged by dispatching through here.
    """
    if x.ndim != 2:
        raise ValueError(f"decode_mlp expects [b, d] tokens, got {x.shape}")
    if impl == "auto":
        impl = "pallas" if _mlp_pallas_ok(x, mlp, block_f) else "xla"
    global _LAST_DECODE_MLP_IMPL
    _LAST_DECODE_MLP_IMPL = impl
    if impl == "pallas":
        ws = _kernels(mlp)
        if ws is None:
            raise ValueError(
                "the pallas decode MLP kernel needs plain 2D kernels "
                "(int8 weight-only trees take impl='xla' or 'auto')"
            )
        return _pallas_decode_mlp(
            x, norm_scale, *ws, eps=eps, block_f=block_f
        )
    if impl == "xla":
        return _xla_decode_mlp(x, norm_scale, mlp, eps)
    if impl == "reference":
        return reference_decode_mlp(x, norm_scale, mlp, eps)
    raise ValueError(f"unknown decode mlp impl: {impl!r}")
