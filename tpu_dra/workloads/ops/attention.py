"""Attention: Pallas TPU flash kernel + XLA reference, one dispatcher.

TPU-first design notes:

- the flash kernel tiles queries over the grid and runs an **online
  softmax** over KV blocks entirely in VMEM, with fp32 accumulators and a
  causal block-skip (fully-masked KV blocks are never touched) — the
  standard flash schedule mapped onto MXU 128-lane tiling;
- GQA is resolved *outside* the kernel by logical head grouping (no K/V
  materialized repeat: we reshape queries to [kv_head, group, ...] so the
  kernel contracts each KV head against its query group);
- backward is a pair of flash kernels (dq over q-blocks; dk/dv over
  kv-blocks) reusing the forward's saved logsumexp — no s×s
  materialization in either direction, with the same causal block-skip;
  shapes the kernels don't cover (sq != skv) fall back to an XLA-recompute
  VJP;
- everything falls back to the XLA reference off-TPU (CPU tests, the
  driver's virtual-device dryrun) — same numerics, fp32 softmax. Setting
  ``_INTERPRET = True`` runs the pallas kernels in interpreter mode on any
  backend (numerics tests without a TPU).
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax import lax

log = logging.getLogger(__name__)

NEG_INF = -1e30

# exp2 softmax domain (r5): the VPU's transcendental unit computes 2^x;
# exp(x) lowers to exp2(x * log2e) — one extra vector multiply per
# element per KV block. Folding log2e into the QK scale makes the online
# softmax run natively in base 2 and saves that multiply on the two s²
# exp paths (fwd p, bwd p-rebuild). lse stays NATURAL-log at the public
# boundary (ring attention's merge math and the XLA fallback expect it).
LOG2_E = 1.4426950408889634
LN_2 = 0.6931471805599453

# Run pallas kernels in interpreter mode (works on CPU; for tests).
_INTERPRET = False


def _repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[b, s, kv_heads, hd] -> [b, s, kv_heads * n_rep, hd] (logical)."""
    if n_rep == 1:
        return x
    b, s, kvh, hd = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, kvh, n_rep, hd)
    ).reshape(b, s, kvh * n_rep, hd)


def reference_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
) -> jnp.ndarray:
    """XLA attention. q: [b, sq, h, hd]; k/v: [b, skv, kvh, hd]."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, skv = q.shape[1], k.shape[1]
        # Offset supports q being a suffix of the kv sequence (decode).
        mask = (
            jnp.arange(skv)[None, :]
            <= (jnp.arange(sq)[:, None] + (skv - sq))
        )
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# --- pallas flash kernel ----------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  sq: int, skv: int, causal: bool, scale: float):
    """One (batch*head, q-block) program: online softmax over KV blocks.
    Also emits the per-row logsumexp, the residual the backward kernels
    rebuild softmax probabilities from.

    MXU dtype discipline (all three kernels): matmul INPUTS stay in the
    model dtype (bf16) with fp32 accumulation via preferred_element_type
    — a pre-cast to fp32 would demote every dot to fp32 MXU throughput
    for bit-identical products (bf16 values multiply exactly into the
    fp32 accumulator either way). Softmax statistics and accumulators
    are fp32; probabilities round back to the model dtype only as PV/dS
    matmul inputs (standard flash numerics). Measured on v5e (1B bench
    model, hd=64): +2.4% end-to-end tok/s at seq 2048 over fp32-input
    kernels."""
    import jax.experimental.pallas as pl

    q = q_ref[0]  # [block_q, hd], model dtype
    block_q = q.shape[0]
    # Grid dim 1 walks the n_rep query heads of this KV head back-to-back;
    # the causal position only depends on the within-sequence block index.
    qi = pl.program_id(1) % (sq // block_q)
    q_offset = qi * block_q + (skv - sq)  # global position of q row 0

    m0 = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), dtype=jnp.float32)

    num_kv_blocks = skv // block_k
    if causal:
        # Skip KV blocks entirely above the causal frontier.
        last_q_row = q_offset + block_q - 1
        num_visible = jnp.minimum(last_q_row // block_k + 1, num_kv_blocks)
    else:
        num_visible = num_kv_blocks

    def body(ki, carry, masked):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :]
        # Base-2 softmax domain: log2e folds into the scale, so the s²
        # exponentials are native exp2 (see LOG2_E note at the top).
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * (
            scale * LOG2_E
        )
        if masked:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    # When every q-tile's causal frontier is exactly ONE kv block (equal
    # tiles, non-negative block-aligned suffix offset — statically
    # known; skv >= sq guarantees num_visible >= 1 so the tail index is
    # never negative), run the strictly-below-diagonal blocks mask-free
    # in the loop and the single diagonal block straight-line after it.
    # (A two-LOOP split was measured 36% slower: back-to-back
    # dynamic-bound fori_loops defeat Mosaic's pipelining; a loop +
    # straight-line tail does not.)
    diag_one = (
        causal and block_q == block_k
        and skv >= sq and (skv - sq) % block_k == 0
    )
    if diag_one:
        carry = jax.lax.fori_loop(
            0, num_visible - 1, lambda ki, c: body(ki, c, masked=False),
            (m0, l0, acc0),
        )
        m, l, acc = body(num_visible - 1, carry, masked=True)
    else:
        m, l, acc = jax.lax.fori_loop(
            0, num_visible, lambda ki, c: body(ki, c, masked=causal),
            (m0, l0, acc0),
        )
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # m is a base-2 max; convert the logsumexp back to natural log.
    lse_ref[0, 0] = (m + jnp.log2(jnp.maximum(l, 1e-30))) * LN_2


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, sq: int, skv: int,
                         causal: bool, scale: float):
    """dQ for one (batch*head, q-block) program: stream KV blocks, rebuild
    P from the saved logsumexp, accumulate dS·K. delta is the flash-bwd
    rowsum(dO ⊙ O) term."""
    import jax.experimental.pallas as pl

    q = q_ref[0]  # model dtype; scale folds into s post-dot
    do = do_ref[0]
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    block_q = q.shape[0]
    qi = pl.program_id(1) % (sq // block_q)
    q_offset = qi * block_q + (skv - sq)

    num_kv_blocks = skv // block_k
    if causal:
        last_q_row = q_offset + block_q - 1
        num_visible = jnp.minimum(last_q_row // block_k + 1, num_kv_blocks)
    else:
        num_visible = num_kv_blocks

    lse2 = lse * LOG2_E  # natural-log residual -> base-2 domain

    def body(ki, acc, masked):
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * (
            scale * LOG2_E
        )
        if masked:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(k_blk.dtype)
        return acc + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    acc0 = jnp.zeros(q.shape, jnp.float32)
    # Same mask-free loop + straight-line masked diagonal tail as the
    # forward kernel (see the diag_one note there, incl. the skv >= sq
    # guard that keeps the tail index non-negative).
    diag_one = (
        causal and block_q == block_k
        and skv >= sq and (skv - sq) % block_k == 0
    )
    if diag_one:
        acc = jax.lax.fori_loop(
            0, num_visible - 1, lambda ki, a: body(ki, a, masked=False),
            acc0,
        )
        acc = body(num_visible - 1, acc, masked=True)
    else:
        acc = jax.lax.fori_loop(
            0, num_visible, lambda ki, a: body(ki, a, masked=causal), acc0
        )
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, acc_dk_ref, acc_dv_ref, *,
                          block_q: int, chunk_rows: int, num_chunks: int,
                          sq: int, skv: int, causal: bool, scale: float):
    """dK/dV for one (batch*kv_head, kv-block, q-chunk) program.

    The grouped q rows (all n_rep query heads of this KV head, rep-major)
    are tiled through the innermost grid dimension in `chunk_rows`-row
    chunks, so VMEM holds one chunk of Q/dO at a time — not the whole
    [n_rep*sq, hd] plane (which overflows VMEM for long GQA sequences).
    Partial dK/dV accumulate across chunks in fp32 VMEM scratch; the
    output block is written once, on the last chunk."""
    import jax.experimental.pallas as pl

    k_blk = k_ref[0]  # model dtype; fp32 only in stats + accumulators
    v_blk = v_ref[0]
    block_k = k_blk.shape[0]
    ki = pl.program_id(1)
    t = pl.program_id(2)
    k_start = ki * block_k

    @pl.when(t == 0)
    def _init():
        acc_dk_ref[...] = jnp.zeros(acc_dk_ref.shape, jnp.float32)
        acc_dv_ref[...] = jnp.zeros(acc_dv_ref.shape, jnp.float32)

    # chunk_rows divides sq, so a chunk never straddles two query heads;
    # its first row's within-sequence position only needs the mod.
    seq0 = (t * chunk_rows) % sq
    num_sub = chunk_rows // block_q
    if causal:
        # First within-sequence q row that can see this kv block.
        first_row = jnp.maximum(k_start - (skv - sq), 0)
        u_start = jnp.clip((first_row - seq0) // block_q, 0, num_sub)
    else:
        u_start = 0

    def body(u, carry):
        acc_dk, acc_dv = carry
        row0 = u * block_q
        q = q_ref[0, pl.ds(row0, block_q), :]
        do = do_ref[0, pl.ds(row0, block_q), :]
        lse2 = lse_ref[0, 0, pl.ds(row0, block_q)] * LOG2_E
        delta = delta_ref[0, 0, pl.ds(row0, block_q)]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * (
            scale * LOG2_E
        )
        if causal:
            q_offset = seq0 + row0 + (skv - sq)
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        acc_dv = acc_dv + jnp.dot(
            p.T.astype(do.dtype), do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        acc_dk = acc_dk + jnp.dot(
            ds.T.astype(q.dtype), q, preferred_element_type=jnp.float32
        )
        return acc_dk, acc_dv

    zeros = jnp.zeros(k_blk.shape, jnp.float32)
    acc_dk, acc_dv = jax.lax.fori_loop(u_start, num_sub, body, (zeros, zeros))
    acc_dk_ref[...] += acc_dk
    acc_dv_ref[...] += acc_dv

    @pl.when(t == num_chunks - 1)
    def _flush():
        # ds is the gradient wrt the SCALED logits (scale folds into s
        # post-dot, keeping q in bf16 for the MXU), so dK = scale·dSᵀ·Q
        # needs the factor here.
        dk_ref[0] = (acc_dk_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = acc_dv_ref[...].astype(dv_ref.dtype)


def _pick_chunk_rows(sq: int, block_q: int, target: int = 1024) -> int:
    """Largest multiple of block_q ≤ target that divides sq (so a chunk of
    grouped rep-major q rows never straddles two query heads)."""
    r = max(block_q, (min(sq, target) // block_q) * block_q)
    while r > block_q and sq % r:
        r -= block_q
    return r if sq % r == 0 else block_q


def _group_q(x: jnp.ndarray, kvh: int) -> jnp.ndarray:
    """[b, s, h, hd] -> [b*kvh, n_rep*s, hd]: the n_rep query heads of one
    KV head are stacked along the row axis, so a single grid row shares one
    K/V load across the whole GQA group — no K/V duplication anywhere."""
    b, s, h, hd = x.shape
    n_rep = h // kvh
    return (
        x.transpose(0, 2, 1, 3)
        .reshape(b * kvh, n_rep * s, hd)
    )


def _ungroup_q(x: jnp.ndarray, b: int, h: int, s: int) -> jnp.ndarray:
    hd = x.shape[-1]
    return x.reshape(b, h, s, hd).transpose(0, 2, 1, 3)


def _group_kv(x: jnp.ndarray) -> jnp.ndarray:
    """[b, skv, kvh, hd] -> [b*kvh, skv, hd]."""
    b, s, kvh, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * kvh, s, hd)


def _ungroup_kv(x: jnp.ndarray, b: int, kvh: int) -> jnp.ndarray:
    _, s, hd = x.shape
    return x.reshape(b, kvh, s, hd).transpose(0, 2, 1, 3)


def _flash_attention_fwd_impl(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool,
    block_q: int, block_k: int,
):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, hd = q.shape
    _, skv, kvh, _ = k.shape
    n_rep = h // kvh
    scale = hd**-0.5

    qg = _group_q(q, kvh)  # [b*kvh, n_rep*sq, hd]
    kg = _group_kv(k)
    vg = _group_kv(v)

    q_block = lambda i, j: (i, j, 0)  # noqa: E731
    whole_kv = lambda i, j: (i, 0, 0)  # noqa: E731
    row_block = lambda i, j: (i, 0, j)  # noqa: E731

    grid = (kg.shape[0], n_rep * (sq // block_q))
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, sq=sq, skv=skv, causal=causal,
        scale=scale,
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(qg.shape, q.dtype),
            # [bg, 1, n_rep*sq]: mosaic wants the last two block dims
            # aligned to (8, 128) or full-size; a singleton axis satisfies
            # that where a [bg, rows] row-block could not.
            jax.ShapeDtypeStruct((qg.shape[0], 1, qg.shape[1]), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, hd), q_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, hd), whole_kv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, hd), whole_kv, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, hd), q_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), row_block, memory_space=pltpu.VMEM),
        ],
        interpret=_INTERPRET,
    )(qg, kg, vg)
    return _ungroup_q(out, b, h, sq), lse


def _flash_attention_bwd_impl(
    q, k, v, out, lse, g, causal: bool, block_q: int, block_k: int,
    g_lse=None,
):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, hd = q.shape
    _, skv, kvh, _ = k.shape
    n_rep = h // kvh
    scale = hd**-0.5

    qg = _group_q(q, kvh)
    kg = _group_kv(k)
    vg = _group_kv(v)
    dog = _group_q(g, kvh).astype(jnp.float32)
    og = _group_q(out, kvh).astype(jnp.float32)
    delta = jnp.sum(dog * og, axis=-1)[:, None, :]  # [b*kvh, 1, n_rep*sq]
    if g_lse is not None:
        # A logsumexp cotangent folds into the delta term: dlse/ds_ij =
        # p_ij, so ds = p*(dp - delta) + g_lse*p = p*(dp - (delta - g_lse))
        # — both backward kernels stay untouched.
        delta = delta - g_lse.astype(jnp.float32)

    q_block = lambda i, j: (i, j, 0)  # noqa: E731
    whole_kv = lambda i, j: (i, 0, 0)  # noqa: E731
    row_block = lambda i, j: (i, 0, j)  # noqa: E731

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_k=block_k, sq=sq, skv=skv,
            causal=causal, scale=scale,
        ),
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        grid=(kg.shape[0], n_rep * (sq // block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), q_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, hd), whole_kv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, skv, hd), whole_kv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, hd), q_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), row_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), row_block, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, hd), q_block, memory_space=pltpu.VMEM
        ),
        interpret=_INTERPRET,
    )(qg, kg, vg, dog.astype(q.dtype), lse, delta)

    chunk_rows = _pick_chunk_rows(sq, block_q)
    num_chunks = (n_rep * sq) // chunk_rows
    kv_block3 = lambda i, j, t: (i, j, 0)  # noqa: E731
    q_chunk3 = lambda i, j, t: (i, t, 0)  # noqa: E731
    row_chunk3 = lambda i, j, t: (i, 0, t)  # noqa: E731

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, chunk_rows=chunk_rows,
            num_chunks=num_chunks, sq=sq, skv=skv, causal=causal, scale=scale,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(kg.shape, k.dtype),
            jax.ShapeDtypeStruct(vg.shape, v.dtype),
        ],
        grid=(kg.shape[0], skv // block_k, num_chunks),
        in_specs=[
            pl.BlockSpec((1, block_k, hd), kv_block3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, hd), kv_block3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk_rows, hd), q_chunk3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, chunk_rows, hd), q_chunk3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, chunk_rows), row_chunk3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, chunk_rows), row_chunk3,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, hd), kv_block3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, hd), kv_block3, memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(kg, vg, qg, dog.astype(q.dtype), lse, delta)

    return (
        _ungroup_q(dq, b, h, sq),
        _ungroup_kv(dk, b, kvh),
        _ungroup_kv(dv, b, kvh),
    )


def _lse_to_bhs(lse, b: int, h: int, sq: int):
    """Grouped [b*kvh, 1, n_rep*sq] -> public [b, h, sq] (row order is
    (kvh, n_rep, sq), which flattens exactly to (h, sq))."""
    return lse.reshape(b, h, sq)


def _lse_from_bhs(g_lse, kvh: int):
    b, h, sq = g_lse.shape
    return g_lse.reshape(b * kvh, 1, (h // kvh) * sq)


def reference_attention_with_lse(q, k, v, causal: bool):
    """XLA (out, logsumexp[b,h,sq]) — the fallback/oracle for the joint
    flash primitive."""
    n_rep = q.shape[2] // k.shape[2]
    kr = _repeat_kv(k, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, kr, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, skv = q.shape[1], kr.shape[1]
        mask = (
            jnp.arange(skv)[None, :]
            <= (jnp.arange(sq)[:, None] + (skv - sq))
        )
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    # Reuse the logits: probs from the already-computed lse, one PV einsum
    # (identical numerics to reference_attention at half the cost).
    probs = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    vr = _repeat_kv(v, n_rep)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(vr.dtype), vr,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype), lse


# The forward and dq kernels pin the whole K/V plane of one KV head in
# VMEM; past this many bytes of pinned K+V the pallas path must not be
# chosen (TPU VMEM is ~16 MiB/core; leave headroom for q blocks, outputs
# and double-buffering).
_VMEM_KV_BUDGET_BYTES = 8 * 1024 * 1024


def flash_vmem_ok(k: jnp.ndarray) -> bool:
    """True when one KV head's full K+V plane fits the VMEM budget the
    flash kernels pin per grid program."""
    _, skv, _, hd = k.shape
    return 2 * skv * hd * k.dtype.itemsize <= _VMEM_KV_BUDGET_BYTES


def _validate_flash_shapes(q, k, block_q, block_k):
    b, sq, h, hd = q.shape
    bk, skv, kvh, hdk = k.shape
    if sq % block_q or skv % block_k:
        raise ValueError(
            f"flash attention needs sq % block_q == 0 and skv % block_k == 0;"
            f" got sq={sq} block_q={block_q} skv={skv} block_k={block_k}"
            " (trailing rows would be silently uncomputed)"
        )
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    if hd % 64 or hd != hdk:
        raise ValueError(f"head dim must be a multiple of 64; got {hd}/{hdk}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_with_lse(q, k, v, causal, block_q, block_k):
    """(out, logsumexp[b, h, sq]) with full custom-VJP support for BOTH
    outputs — the building block for ring attention's chunk merging."""
    _validate_flash_shapes(q, k, block_q, block_k)
    b, sq, h, _ = q.shape
    out, lse = _flash_attention_fwd_impl(q, k, v, causal, block_q, block_k)
    return out, _lse_to_bhs(lse, b, h, sq)


def _flash_lse_fwd(q, k, v, causal, block_q, block_k):
    _validate_flash_shapes(q, k, block_q, block_k)
    b, sq, h, _ = q.shape
    out, lse = _flash_attention_fwd_impl(q, k, v, causal, block_q, block_k)
    return (out, _lse_to_bhs(lse, b, h, sq)), (q, k, v, out, lse)


def _flash_lse_bwd(causal, block_q, block_k, residuals, cts):
    q, k, v, out, lse = residuals
    g_out, g_lse = cts
    kvh = k.shape[2]
    if (q.shape[1] == k.shape[1] and q.shape[1] % block_k == 0
            and flash_vmem_ok(k)):
        return _flash_attention_bwd_impl(
            q, k, v, out, lse, g_out, causal, block_q, block_k,
            g_lse=_lse_from_bhs(g_lse, kvh),
        )
    # Shapes the bwd kernels don't cover (decode suffix q, ragged blocks):
    # recompute through the XLA reference — identical fp32 softmax.
    _, vjp = jax.vjp(
        lambda q, k, v: reference_attention_with_lse(q, k, v, causal), q, k, v
    )
    return vjp((g_out, g_lse))


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _flash_attention(q, k, v, causal, block_q, block_k):
    # Out-only view: the lse output is simply unused (its cotangent is
    # zero, which _flash_attention_bwd_impl folds away for free).
    return flash_attention_with_lse(q, k, v, causal, block_q, block_k)[0]


def flash_platform_ok() -> bool:
    """Can pallas kernels run here? (TPU, or any backend under interpreter
    mode.) Shared by the attention dispatcher and ring attention."""
    if _INTERPRET:
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _pallas_ok(q, k, block_q, block_k) -> bool:
    if not flash_platform_ok():
        return False
    # The dispatcher's contract: any shape the kernels would reject loudly
    # takes the XLA path instead (one predicate set, not two copies).
    try:
        _validate_flash_shapes(q, k, block_q, block_k)
    except ValueError:
        return False
    return flash_vmem_ok(k)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    impl: str = "auto",
    block_q: int = 256,
    block_k: int = 256,
) -> jnp.ndarray:
    """q: [b, sq, heads, hd]; k/v: [b, skv, kv_heads, hd] -> [b, sq, heads, hd].

    impl: "auto" | "pallas" | "xla".
    """
    if impl == "auto":
        impl = "pallas" if _pallas_ok(q, k, block_q, block_k) else "xla"
    if impl == "pallas":
        return _flash_attention(q, k, v, causal, block_q, block_k)
    return reference_attention(q, k, v, causal)


# --- fused decode attention (single-query serving path) ---------------------
#
# The decode hot path is one query row per sequence against a static
# [b, max_seq, kvh, hd] cache of which only the first `length` positions
# are live. The generic paths above pay for what decode does not need:
# reference_attention materializes an n_rep-repeated K/V copy plus a
# [b, h, 1, max_seq] fp32 score/prob tensor per layer per token, and
# always contracts the full max_seq extent regardless of `length`.
#
# decode_attention is the GQA-native replacement: queries are grouped
# kv-major (head i -> group i // n_rep, the _repeat_kv order) and
# contracted straight against the ungrouped cache, with a flash-decode
# style online softmax split over the cache length so
#   - no repeated K/V and no full-length fp32 score tensor exist, and
#   - compute stops at the last block that contains a live position
#     (the length-aware mask: the zero-tail invariant documented on
#     DecodeCache means slots >= length hold nothing worth reading).
#
# The cache may be int8 (quantize.quantize_kv): per-(token, head) scales
# ride along and dequantization happens inside the contraction — scores
# multiply by k_scale per key column, probabilities by v_scale before the
# value dot — so no dequantized KV copy is ever materialized.
#
# Dispatch mirrors attention(): "pallas" is a single-query kernel (one
# grid program per (batch, kv head), scalar-prefetched length bounding
# the KV loop), "xla" is a dynamic-trip-count chunked loop with the same
# online-softmax math, "reference" is the naive masked softmax oracle.

_LAST_DECODE_IMPL = None  # set at trace time; decodebench asserts on it


def _group_scale(s: "jnp.ndarray | None"):
    """[b, skv, kvh] per-key scale -> [b, kvh, 1, skv] broadcastable
    against grouped [b, kvh, n_rep, skv] scores (None passes through)."""
    return None if s is None else s.transpose(0, 2, 1)[:, :, None, :]


def reference_decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    length: jnp.ndarray,
    k_scale=None,
    v_scale=None,
    extra_k=None,
    extra_v=None,
) -> jnp.ndarray:
    """Naive fp32 oracle. q: [b, h, hd]; k/v: [b, skv, kvh, hd] (model
    dtype, or int8 with [b, skv, kvh] scales). Keys [0, cache_len) are
    live, where cache_len = length - 1 when ``extra_k``/``extra_v``
    ([b, kvh, hd]) carry the newest token's K/V out-of-cache (the
    stacked-layout decode step, whose streamed cache is stale at the
    current position) and cache_len = length otherwise."""
    b, h, hd = q.shape
    kvh = k.shape[2]
    n_rep = h // kvh
    scale = hd ** -0.5
    cache_len = length - (0 if extra_k is None else 1)
    qg = q.reshape(b, kvh, n_rep, hd).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    logits = jnp.einsum("bhrd,bkhd->bhrk", qg, kf) * scale
    if k_scale is not None:
        logits = logits * _group_scale(k_scale)
    mask = jnp.arange(k.shape[1])[None, None, None, :] < cache_len
    logits = jnp.where(mask, logits, NEG_INF)
    if extra_k is not None:
        el = jnp.einsum(
            "bhrd,bhd->bhr", qg, extra_k.astype(jnp.float32)
        )[..., None] * scale
        logits = jnp.concatenate([logits, el], axis=-1)
    probs = jax.nn.softmax(logits, axis=-1)
    pc = probs[..., : k.shape[1]]
    if v_scale is not None:
        pc = pc * _group_scale(v_scale)
    out = jnp.einsum("bhrk,bkhd->bhrd", pc, vf)
    if extra_v is not None:
        # probs[..., -1:] is [b, kvh, n_rep, 1]; broadcast against the
        # rep axis of extra_v [b, kvh, 1, hd].
        out = out + probs[..., -1:] * extra_v.astype(jnp.float32)[:, :, None, :]
    return out.reshape(b, h, hd).astype(q.dtype)


def _xla_decode_attention(
    q, k, v, length, k_scale, v_scale, extra_k, extra_v, block_k: int,
):
    """Length-aware chunked online softmax (the XLA serving path): a
    dynamic-trip-count loop over KV blocks stops at the last block with a
    live position, carrying fp32 (m, l, acc) — the only per-step score
    state is [b, kvh, n_rep, block_k], never [b, h, max_seq] fp32."""
    b, h, hd = q.shape
    skv, kvh = k.shape[1], k.shape[2]
    n_rep = h // kvh
    scale = hd ** -0.5
    cache_len = length - (0 if extra_k is None else 1)
    num_blocks = lax.div(cache_len + (block_k - 1), block_k)
    qg = q.reshape(b, kvh, n_rep, hd)

    m0 = jnp.full((b, kvh, n_rep), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, n_rep), jnp.float32)
    acc0 = jnp.zeros((b, kvh, n_rep, hd), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        start = i * block_k
        kb = lax.dynamic_slice(k, (0, start, 0, 0), (b, block_k, kvh, hd))
        vb = lax.dynamic_slice(v, (0, start, 0, 0), (b, block_k, kvh, hd))
        s = jnp.einsum(
            "bhrd,bkhd->bhrk", qg, kb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        if k_scale is not None:
            ksb = lax.dynamic_slice(k_scale, (0, start, 0), (b, block_k, kvh))
            s = s * _group_scale(ksb)
        cols = start + jnp.arange(block_k)
        s = jnp.where(cols[None, None, None, :] < cache_len, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if v_scale is not None:
            vsb = lax.dynamic_slice(v_scale, (0, start, 0), (b, block_k, kvh))
            p = p * _group_scale(vsb)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhrk,bkhd->bhrd", p.astype(qg.dtype), vb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    if extra_k is not None:
        # The newest token's K/V enter as one exact (unquantized) online
        # update — no cache copy, no concat.
        se = jnp.einsum(
            "bhrd,bhd->bhr", qg, extra_k.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        m_new = jnp.maximum(m, se)
        alpha = jnp.exp(m - m_new)
        pe = jnp.exp(se - m_new)
        l = l * alpha + pe
        acc = acc * alpha[..., None] + (
            pe[..., None] * extra_v.astype(jnp.float32)[:, :, None]
        )
        m = m_new
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, h, hd).astype(q.dtype)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, *rest, block_k: int,
                   skv: int, scale: float, quantized: bool):
    """One (batch * kv_head) program: online softmax of the n_rep grouped
    query rows over KV blocks, loop-bounded by the scalar-prefetched live
    length (blocks past the last live position are never touched — the
    kernel-side form of the length-aware mask). int8 caches dequantize in
    flight: k_scale multiplies the score columns, v_scale the
    probabilities, so only int8 bytes cross HBM."""
    import jax.experimental.pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref = rest
    else:
        o_ref = rest[0]
    q = q_ref[0]  # [n_rep, hd], model dtype
    length = len_ref[0]
    num_visible = lax.div(length + (block_k - 1), block_k)

    n_rep = q.shape[0]
    m0 = jnp.full((n_rep,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_rep,), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(ki * block_k, block_k), :]
        vb = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jnp.dot(
            q, kb.astype(q.dtype).T, preferred_element_type=jnp.float32
        ) * scale
        if quantized:
            s = s * ks_ref[0, 0, pl.ds(ki * block_k, block_k)][None, :]
        cols = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if quantized:
            p = p * vs_ref[0, 0, pl.ds(ki * block_k, block_k)][None, :]
        acc_new = acc * alpha[:, None] + jnp.dot(
            p.astype(q.dtype), vb.astype(q.dtype),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_visible, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _pallas_decode_attention(q, k, v, length, k_scale, v_scale, block_k):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, hd = q.shape
    skv, kvh = k.shape[1], k.shape[2]
    n_rep = h // kvh
    quantized = k_scale is not None

    qg = q.reshape(b, kvh, n_rep, hd).reshape(b * kvh, n_rep, hd)
    kg = _group_kv(k)  # [b*kvh, skv, hd]
    vg = _group_kv(v)
    length_arr = jnp.full((1,), length, jnp.int32)

    # Index maps under PrefetchScalarGridSpec also receive the prefetched
    # scalar refs after the grid indices; this one only needs the head.
    head_block = lambda i, *_: (i, 0, 0)  # noqa: E731
    in_specs = [
        pl.BlockSpec((1, n_rep, hd), head_block, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, skv, hd), head_block, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, skv, hd), head_block, memory_space=pltpu.VMEM),
    ]
    args = [qg, kg, vg]
    if quantized:
        # [b*kvh, 1, skv]: the singleton axis keeps the block 2D for
        # mosaic (same trick as the flash kernels' lse rows).
        args.append(_group_kv(k_scale[..., None])[:, None, :, 0])
        args.append(_group_kv(v_scale[..., None])[:, None, :, 0])
        in_specs.extend([
            pl.BlockSpec((1, 1, skv), head_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, skv), head_block, memory_space=pltpu.VMEM),
        ])
    kernel = functools.partial(
        _decode_kernel, block_k=block_k, skv=skv, scale=hd ** -0.5,
        quantized=quantized,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * kvh,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, n_rep, hd), head_block, memory_space=pltpu.VMEM
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b * kvh, n_rep, hd), q.dtype),
        interpret=_INTERPRET,
    )(length_arr, *args)
    return out.reshape(b, h, hd)


def _decode_block_k(skv: int, block_k: int) -> int:
    """Largest divisor of skv at most block_k (the chunked paths index
    blocks at i*block_k, so block_k must divide skv or the tail block
    would read out of bounds). Trace-time only. Awkward cache lengths
    (primes) necessarily degrade toward 1 — generate._generate rounds
    auto-sized caches up to a 64 granule so the serving path never hits
    that (padded slots are inert under the length mask)."""
    for bk in range(min(block_k, skv), 0, -1):
        if skv % bk == 0:
            return bk
    return 1


def _decode_pallas_ok(k, skv: int, hd: int, block_k: int,
                      extra_k) -> bool:
    if extra_k is not None or not flash_platform_ok():
        return False  # stacked-layout stale caches take the XLA path
    if hd % 64 or skv % block_k:
        return False
    return flash_vmem_ok(k)


def decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    length,
    k_scale=None,
    v_scale=None,
    extra_k=None,
    extra_v=None,
    impl: str = "auto",
    block_k: int = 256,
) -> jnp.ndarray:
    """Fused single-query GQA attention over a static KV cache.

    q: [b, h, hd] (ONE query per sequence — the decode step);
    k/v: [b, max_seq, kvh, hd] cache, model dtype or int8 with
    per-(token, head) ``k_scale``/``v_scale`` [b, max_seq, kvh];
    length: traced int32 scalar — keys at positions >= length are dead
    and are neither read (full blocks) nor admitted (masked tail block);
    extra_k/extra_v: [b, kvh, hd] newest-token K/V not yet in the cache
    (position length-1) — the stacked layout's streamed-cache decode;
    impl: "auto" | "pallas" | "xla" | "reference" (naive fp32 oracle).

    Returns [b, h, hd] in q's dtype.
    """
    b, h, hd = q.shape
    if k.shape[0] != b or v.shape != k.shape or k.shape[3] != hd:
        raise ValueError(
            f"decode cache shape mismatch: q {q.shape} vs k {k.shape} "
            f"v {v.shape}"
        )
    kvh = k.shape[2]
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be provided together")
    if (extra_k is None) != (extra_v is None):
        raise ValueError("extra_k and extra_v must be provided together")
    skv = k.shape[1]
    bk = _decode_block_k(skv, block_k)
    if impl == "auto":
        impl = (
            "pallas"
            if _decode_pallas_ok(k, skv, hd, bk, extra_k)
            else "xla"
        )
    global _LAST_DECODE_IMPL
    _LAST_DECODE_IMPL = impl
    if impl == "pallas":
        if extra_k is not None:
            raise ValueError(
                "the pallas decode kernel does not take extra_k/extra_v "
                "(stacked-layout stale caches); use impl='xla' or 'auto'"
            )
        return _pallas_decode_attention(q, k, v, length, k_scale, v_scale, bk)
    if impl == "xla":
        return _xla_decode_attention(
            q, k, v, length, k_scale, v_scale, extra_k, extra_v, bk
        )
    if impl == "reference":
        return reference_decode_attention(
            q, k, v, length, k_scale, v_scale, extra_k, extra_v
        )
    raise ValueError(f"unknown decode attention impl: {impl!r}")


# --- paged (block-table) attention: the serving-engine path ------------------
#
# The serving engine (workloads/engine.py) stores KV in a shared POOL of
# fixed-size pages ([num_pages, page_size, kvh, hd] per layer) instead of
# one contiguous [b, max_seq, ...] buffer per sequence: each sequence owns
# a BLOCK TABLE of page ids, so a batch of wildly different lengths pays
# HBM for exactly the pages it has filled — no per-sequence max_seq
# padding allocation, no copy when sequences join/leave the batch.
#
# paged_decode_attention is the length-aware single-query op over that
# layout: the same online-softmax block loop as _xla_decode_attention,
# except each KV block is GATHERED through the per-sequence block table
# (`k_pages[tables[:, i]]`) instead of sliced from a contiguous buffer,
# and `lengths` is a PER-SEQUENCE vector — the loop runs to the longest
# live sequence's last page and every shorter sequence's dead columns are
# masked. Fully-masked blocks contribute exactly zero to (m, l, acc)
# (exp(NEG_INF - m) underflows to 0.0, alpha stays 1.0), so the math is
# BIT-IDENTICAL to running each sequence alone with block_k == page_size
# — the exact-parity contract the engine's paged-vs-unpaged oracle test
# pins (a contiguous layout is just a block table whose pages happen to
# be physically consecutive).
#
# paged_prefill_attention is the chunked-prefill companion: s queries of
# ONE sequence at absolute positions [pos, pos+s) against its own block
# table, causal within the chunk (write-then-attend like _block_inplace:
# the chunk's K/V pages are already written when it runs). int8 pools
# dequantize in flight exactly like the contiguous paths — gathered
# k_scale pages multiply score columns, v_scale pages the probabilities.

_LAST_PAGED_IMPL = None  # set at trace time; enginebench asserts on it


def reference_paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    k_scale=None,
    v_scale=None,
) -> jnp.ndarray:
    """Naive fp32 oracle: gather EVERY table entry into a contiguous
    per-sequence view and run a masked softmax. q: [b, h, hd];
    k_pages/v_pages: [P, page, kvh, hd] pools; tables: [b, max_pages]
    int32; lengths: [b] int32 (keys [0, lengths[i]) of sequence i are
    live). Tests only — materializes [b, max_pages*page, ...]."""
    b, h, hd = q.shape
    page, kvh = k_pages.shape[1], k_pages.shape[2]
    n_rep = h // kvh
    max_pages = tables.shape[1]
    skv = max_pages * page

    def flat(pool):  # [b, max_pages*page, kvh, ...]
        g = jnp.take(pool, tables, axis=0)  # [b, max_pages, page, kvh, ...]
        return g.reshape((b, skv) + pool.shape[2:])

    kf = flat(k_pages).astype(jnp.float32)
    vf = flat(v_pages).astype(jnp.float32)
    qg = q.reshape(b, kvh, n_rep, hd).astype(jnp.float32)
    logits = jnp.einsum("bhrd,bkhd->bhrk", qg, kf) * (hd ** -0.5)
    if k_scale is not None:
        logits = logits * _group_scale(flat(k_scale))
    mask = jnp.arange(skv)[None, None, None, :] < lengths[:, None, None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    # A fully-dead row (lengths == 0) softmaxes NEG_INF uniformly; zero it
    # so dead slots return exactly 0 like the online path.
    probs = jnp.where(mask, probs, 0.0)
    if v_scale is not None:
        probs = probs * _group_scale(flat(v_scale))
    out = jnp.einsum("bhrk,bkhd->bhrd", probs, vf)
    return out.reshape(b, h, hd).astype(q.dtype)


def _xla_paged_decode_attention(
    q, k_pages, v_pages, tables, lengths, k_scale, v_scale
):
    """Length-aware block-table walk (the serving path): a dynamic-trip-
    count loop over page-sized KV blocks, each gathered through the
    per-sequence block table, carrying fp32 (m, l, acc). Trip count stops
    at the longest live sequence's last page; shorter sequences' dead
    columns (and dead slots entirely) are masked to an exact zero
    contribution."""
    b, h, hd = q.shape
    page, kvh = k_pages.shape[1], k_pages.shape[2]
    n_rep = h // kvh
    scale = hd ** -0.5
    qg = q.reshape(b, kvh, n_rep, hd)
    num_blocks = lax.div(jnp.max(lengths) + (page - 1), page)

    m0 = jnp.full((b, kvh, n_rep), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, n_rep), jnp.float32)
    acc0 = jnp.zeros((b, kvh, n_rep, hd), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        pids = jnp.take(tables, i, axis=1)  # [b]
        kb = jnp.take(k_pages, pids, axis=0)  # [b, page, kvh, hd]
        vb = jnp.take(v_pages, pids, axis=0)
        s = jnp.einsum(
            "bhrd,bkhd->bhrk", qg, kb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        if k_scale is not None:
            s = s * _group_scale(jnp.take(k_scale, pids, axis=0))
        cols = i * page + jnp.arange(page)
        s = jnp.where(
            cols[None, None, None, :] < lengths[:, None, None, None],
            s, NEG_INF,
        )
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if v_scale is not None:
            p = p * _group_scale(jnp.take(v_scale, pids, axis=0))
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhrk,bkhd->bhrd", p.astype(qg.dtype), vb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    # A slot with NO live key (length 0) never raises m above NEG_INF,
    # so its masked scores exponentiate to exp(0) = 1 and `out` becomes
    # an average of whatever its table's pages hold — zero it explicitly
    # (the documented dead-slot contract). Live slots pass through the
    # where bit-unchanged, preserving the contiguous-path bit-identity.
    out = jnp.where(lengths[:, None, None, None] > 0, out, 0.0)
    return out.reshape(b, h, hd).astype(q.dtype)


def _paged_decode_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, *rest,
                         page: int, max_pages: int, scale: float,
                         quantized: bool):
    """One (sequence, table-entry) program of the pallas paged-decode
    path. The grid's second dimension walks the sequence's block table;
    the PER-SEQUENCE length and the table itself are SCALAR-PREFETCHED,
    so the page id feeds the BlockSpec index map and the K/V page DMA
    starts before the kernel body runs (the gather never goes through a
    VMEM-resident table). (m, l, acc) carry across table entries in VMEM
    scratch; entries past the sequence's last live page are skipped
    (their index map re-targets the previous page, so no new DMA is
    issued either). The block math is the SAME online-softmax update as
    _xla_paged_decode_attention; interpret-mode parity is pinned at ulp
    level by tests/test_paged_kv.py (bit-equality across the two
    compiled graphs is at the mercy of backend fusion — the XLA gather
    path remains the engine's bit-level oracle)."""
    import jax.experimental.pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    i = pl.program_id(1)
    length = len_ref[pl.program_id(0)]
    num_visible = lax.div(length + (page - 1), page)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    @pl.when(i < num_visible)
    def _block():
        q = q_ref[0]  # [h, hd], model dtype
        h, hd = q.shape
        kb = k_ref[0]  # [page, kvh, hd]
        vb = v_ref[0]
        kvh = kb.shape[1]
        qg = q.reshape(kvh, h // kvh, hd)
        s = jnp.einsum(
            "hrd,khd->hrk", qg, kb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        if quantized:
            ksb = ks_ref[0]  # [page, kvh]
            s = s * ksb.T[:, None, :]
        cols = i * page + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(cols < length, s, NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)
        if quantized:
            vsb = vs_ref[0]
            p = p * vsb.T[:, None, :]
        acc_ref[...] = acc_ref[...] * alpha[..., None] + jnp.einsum(
            "hrk,khd->hrd", p.astype(qg.dtype), vb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(i == max_pages - 1)
    def _flush():
        # A dead slot (length 0) never runs a block: acc stays 0 and
        # 0 / 1e-30 is exactly 0.0 — the documented dead-slot contract,
        # with no explicit where needed.
        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)[..., None]
        kvh, n_rep, hd = out.shape
        o_ref[0] = out.reshape(kvh * n_rep, hd).astype(o_ref.dtype)


def _pallas_paged_decode_attention(
    q, k_pages, v_pages, tables, lengths, k_scale, v_scale
):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, hd = q.shape
    page, kvh = k_pages.shape[1], k_pages.shape[2]
    n_rep = h // kvh
    max_pages = tables.shape[1]
    quantized = k_scale is not None

    def _page_of(s, i, len_ref, tbl_ref):
        # Entries past the last live page re-target the LAST live page
        # (clamped index): pallas skips the DMA when consecutive block
        # indices coincide, so dead trips cost neither bandwidth nor
        # compute (the kernel body is pl.when-guarded too). A 0-length
        # sequence clamps to entry 0 — always a valid pool page (the
        # engine fills unused table rows with the scratch page).
        last = jnp.maximum(
            lax.div(len_ref[s] + (page - 1), page) - 1, 0
        )
        return tbl_ref[s, jnp.minimum(i, last)]

    q_map = lambda s, i, *_: (s, 0, 0)  # noqa: E731
    kv_map = lambda s, i, *refs: (_page_of(s, i, *refs), 0, 0, 0)  # noqa: E731
    sc_map = lambda s, i, *refs: (_page_of(s, i, *refs), 0, 0)  # noqa: E731

    in_specs = [
        pl.BlockSpec((1, h, hd), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, page, kvh, hd), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, page, kvh, hd), kv_map, memory_space=pltpu.VMEM),
    ]
    args = [q, k_pages, v_pages]
    if quantized:
        in_specs.extend([
            pl.BlockSpec((1, page, kvh), sc_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, page, kvh), sc_map, memory_space=pltpu.VMEM),
        ])
        args.extend([k_scale, v_scale])
    kernel = functools.partial(
        _paged_decode_kernel, page=page, max_pages=max_pages,
        scale=hd ** -0.5, quantized=quantized,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # lengths [b], tables [b, max_pages]
            grid=(b, max_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, h, hd), q_map, memory_space=pltpu.VMEM
            ),
            scratch_shapes=[
                pltpu.VMEM((kvh, n_rep), jnp.float32),
                pltpu.VMEM((kvh, n_rep), jnp.float32),
                pltpu.VMEM((kvh, n_rep, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, hd), q.dtype),
        interpret=_INTERPRET,
    )(lengths.astype(jnp.int32), tables.astype(jnp.int32), *args)


def _paged_pallas_ok(k_pages, hd: int) -> bool:
    """May the pallas paged-decode kernel run here? Platform plus the
    head-dim lane constraint; one page of K+V (+scales) trivially fits
    VMEM for any sane page size, so no budget check is needed."""
    return flash_platform_ok() and hd % 64 == 0


def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    k_scale=None,
    v_scale=None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Single-query GQA attention over a paged KV pool.

    q: [b, h, hd] (one query per sequence slot);
    k_pages/v_pages: [num_pages, page_size, kvh, hd] shared pools (model
    dtype, or int8 with [num_pages, page_size, kvh] ``k_scale``/
    ``v_scale`` pools);
    tables: [b, max_pages_per_seq] int32 block tables — entry j of row i
    is the pool page holding sequence i's positions [j*page, (j+1)*page);
    lengths: [b] int32 traced — keys at positions >= lengths[i] are dead
    for sequence i (a 0 length makes the slot contribute exactly zero);
    impl: "auto" | "pallas" | "xla" | "reference".

    Returns [b, h, hd] in q's dtype. The block loop is bit-identical to
    ``decode_attention(..., impl="xla", block_k=page_size)`` over the
    equivalent contiguous cache — the engine's parity tests rely on it.
    "pallas" is the scalar-prefetched block-table kernel (auto picks it
    on TPU): the per-sequence table feeds the BlockSpec index map, so
    page DMA is issued ahead of the kernel body. It runs the SAME block
    update as the "xla" path — agreement is pinned at ulp level (the
    two compile to different graphs, and backend fusion choices differ
    by a last-place bit on some inputs); the "xla" gather path stays
    the BIT-level parity oracle against the contiguous op.
    """
    b, h, hd = q.shape
    if k_pages.shape != v_pages.shape or k_pages.shape[3] != hd:
        raise ValueError(
            f"paged cache shape mismatch: q {q.shape} vs k_pages "
            f"{k_pages.shape} v_pages {v_pages.shape}"
        )
    kvh = k_pages.shape[2]
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be provided together")
    if tables.shape[0] != b or lengths.shape != (b,):
        raise ValueError(
            f"tables {tables.shape} / lengths {lengths.shape} do not "
            f"match batch {b}"
        )
    if impl == "auto":
        impl = "pallas" if _paged_pallas_ok(k_pages, hd) else "xla"
    global _LAST_PAGED_IMPL
    _LAST_PAGED_IMPL = impl
    if impl == "pallas":
        return _pallas_paged_decode_attention(
            q, k_pages, v_pages, tables, lengths, k_scale, v_scale
        )
    if impl == "xla":
        return _xla_paged_decode_attention(
            q, k_pages, v_pages, tables, lengths, k_scale, v_scale
        )
    if impl == "reference":
        return reference_paged_decode_attention(
            q, k_pages, v_pages, tables, lengths, k_scale, v_scale
        )
    raise ValueError(f"unknown paged decode attention impl: {impl!r}")


def paged_prefill_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    table: jnp.ndarray,
    pos,
    k_scale=None,
    v_scale=None,
) -> jnp.ndarray:
    """Chunked-prefill attention for ONE sequence over its block table.

    q: [s, h, hd] — the chunk's queries at absolute positions
    [pos, pos+s); k_pages/v_pages: the shared pools (the chunk's own K/V
    pages are already written — write-then-attend, like the unrolled
    in-place path); table: [max_pages] int32; pos: traced int32 scalar.
    Causal: key j is visible to query i iff j <= pos + i. Returns
    [s, h, hd] in q's dtype.
    """
    s_len, h, hd = q.shape
    page, kvh = k_pages.shape[1], k_pages.shape[2]
    n_rep = h // kvh
    scale = hd ** -0.5
    qg = q.reshape(s_len, kvh, n_rep, hd)
    q_abs = pos + jnp.arange(s_len)  # [s]
    num_blocks = lax.div(pos + s_len + (page - 1), page)

    m0 = jnp.full((kvh, n_rep, s_len), NEG_INF, jnp.float32)
    l0 = jnp.zeros((kvh, n_rep, s_len), jnp.float32)
    acc0 = jnp.zeros((kvh, n_rep, s_len, hd), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        pid = jnp.take(table, i)
        kb = jnp.take(k_pages, pid, axis=0)  # [page, kvh, hd]
        vb = jnp.take(v_pages, pid, axis=0)
        s = jnp.einsum(
            "qhrd,khd->hrqk", qg, kb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        if k_scale is not None:
            ksb = jnp.take(k_scale, pid, axis=0)  # [page, kvh]
            s = s * ksb.T[:, None, None, :]
        cols = i * page + jnp.arange(page)
        mask = cols[None, :] <= q_abs[:, None]  # [s, page]
        s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if v_scale is not None:
            vsb = jnp.take(v_scale, pid, axis=0)
            p = p * vsb.T[:, None, None, :]
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "hrqk,khd->hrqd", p.astype(qg.dtype), vb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [kvh, n_rep, s, hd]
    return (
        out.transpose(2, 0, 1, 3).reshape(s_len, h, hd).astype(q.dtype)
    )


# --- multi-query (batched suffix) attention: verify step + batched prefill ---
#
# Speculative verification (ISSUE 15) evaluates K+1 query positions per
# SEQUENCE in one pass — the draft tokens' K/V are already written into
# the sequences' pages (write-then-attend, like chunked prefill), and
# query i of sequence b sits at absolute position pos[b] + i, attending
# causally over everything at or before it. Batched chunked prefill is
# the SAME computation with per-sequence chunk starts: both ride
# paged_multiquery_attention, so one op (and one parity contract)
# covers the verify step and the multi-sequence prefill bucket.
#
# The block walk is the per-sequence-table gather of
# _xla_paged_decode_attention extended to s queries: fully-masked
# blocks contribute exactly zero to (m, l, acc) and the per-query math
# is the SAME online-softmax update as paged_prefill_attention — so a
# single row of the batch is bit-comparable to the single-sequence
# chunk op, which is what the engine's spec-vs-oracle token-identity
# contract rests on.

_LAST_MULTIQUERY_IMPL = None  # set at trace time; specbench asserts on it


def reference_paged_multiquery_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,
    pos: jnp.ndarray,
    k_scale=None,
    v_scale=None,
) -> jnp.ndarray:
    """Naive fp32 oracle: materialize every sequence's cache through
    its table and run a masked softmax per query. q: [b, s, h, hd];
    tables: [b, max_pages]; pos: [b] — query i of sequence b is at
    absolute position pos[b] + i and sees keys at positions <= its own.
    Tests only."""
    b, s, h, hd = q.shape
    page, kvh = k_pages.shape[1], k_pages.shape[2]
    n_rep = h // kvh
    max_pages = tables.shape[1]
    skv = max_pages * page

    def flat(pool):
        g = jnp.take(pool, tables, axis=0)
        return g.reshape((b, skv) + pool.shape[2:])

    kf = flat(k_pages).astype(jnp.float32)
    vf = flat(v_pages).astype(jnp.float32)
    qg = q.reshape(b, s, kvh, n_rep, hd).astype(jnp.float32)
    logits = jnp.einsum("bshrd,bkhd->bhrsk", qg, kf) * (hd ** -0.5)
    if k_scale is not None:
        logits = logits * flat(k_scale).transpose(0, 2, 1)[:, :, None, None, :]
    q_abs = pos[:, None] + jnp.arange(s)[None]  # [b, s]
    mask = (
        jnp.arange(skv)[None, None, None, None, :]
        <= q_abs[:, None, None, :, None]
    )
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = jnp.where(mask, probs, 0.0)
    if v_scale is not None:
        probs = probs * flat(v_scale).transpose(0, 2, 1)[:, :, None, None, :]
    out = jnp.einsum("bhrsk,bkhd->bhrsd", probs, vf)
    return (
        out.transpose(0, 3, 1, 2, 4).reshape(b, s, h, hd).astype(q.dtype)
    )


def _xla_paged_multiquery_attention(
    q, k_pages, v_pages, tables, pos, k_scale, v_scale
):
    """Length-aware block-table walk over s queries per sequence: the
    same dynamic-trip-count gather loop as _xla_paged_decode_attention,
    carrying fp32 (m, l, acc) per query. The trip count stops at the
    last page any sequence's final query can see; a sequence whose own
    frontier is earlier sees its later blocks fully masked — an exact
    zero contribution, so each row is independent of its batchmates."""
    b, s, h, hd = q.shape
    page, kvh = k_pages.shape[1], k_pages.shape[2]
    n_rep = h // kvh
    scale = hd ** -0.5
    qg = q.reshape(b, s, kvh, n_rep, hd)
    q_abs = pos[:, None] + jnp.arange(s)[None]  # [b, s]
    num_blocks = lax.div(jnp.max(pos) + s + (page - 1), page)

    m0 = jnp.full((b, kvh, n_rep, s), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, n_rep, s), jnp.float32)
    acc0 = jnp.zeros((b, kvh, n_rep, s, hd), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        pids = jnp.take(tables, i, axis=1)  # [b]
        kb = jnp.take(k_pages, pids, axis=0)  # [b, page, kvh, hd]
        vb = jnp.take(v_pages, pids, axis=0)
        sc = jnp.einsum(
            "bshrd,bkhd->bhrsk", qg, kb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        if k_scale is not None:
            ksb = jnp.take(k_scale, pids, axis=0)  # [b, page, kvh]
            sc = sc * ksb.transpose(0, 2, 1)[:, :, None, None, :]
        cols = i * page + jnp.arange(page)
        mask = cols[None, None, None, None, :] <= q_abs[:, None, None, :, None]
        sc = jnp.where(mask, sc, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
        p = jnp.exp(sc - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if v_scale is not None:
            vsb = jnp.take(v_scale, pids, axis=0)
            p = p * vsb.transpose(0, 2, 1)[:, :, None, None, :]
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhrsk,bkhd->bhrsd", p.astype(qg.dtype), vb.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    # Every query admits at least the key at its own position (the
    # causal mask includes q_abs, which num_blocks always covers), so l
    # is strictly positive and no dead-row zeroing is needed.
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return (
        out.transpose(0, 3, 1, 2, 4).reshape(b, s, h, hd).astype(q.dtype)
    )


def paged_multiquery_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,
    pos: jnp.ndarray,
    k_scale=None,
    v_scale=None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Causal multi-query GQA attention over a paged KV pool, batched
    over sequences with PER-SEQUENCE chunk starts.

    q: [b, s, h, hd] — s queries per sequence; query i of sequence b is
    at absolute position pos[b] + i (its K/V, like the whole chunk's,
    is already written: write-then-attend);
    k_pages/v_pages: the shared pools (model dtype, or int8 with
    [num_pages, page_size, kvh] scale pools);
    tables: [b, max_pages_per_seq] int32 block tables;
    pos: [b] int32 traced — the chunk's first absolute position per
    sequence.

    Serves BOTH the speculative verify step (pos = current lengths,
    s = spec_k + 1) and the batched-prefill bucket (pos = per-sequence
    prefill cursors). impl: "auto" | "xla" | "reference" — per-row math
    is the same online-softmax block walk as paged_prefill_attention,
    with appended fully-masked blocks contributing exactly zero.
    """
    b, s, h, hd = q.shape
    if k_pages.shape != v_pages.shape or k_pages.shape[3] != hd:
        raise ValueError(
            f"paged cache shape mismatch: q {q.shape} vs k_pages "
            f"{k_pages.shape} v_pages {v_pages.shape}"
        )
    kvh = k_pages.shape[2]
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be provided together")
    if tables.shape[0] != b or pos.shape != (b,):
        raise ValueError(
            f"tables {tables.shape} / pos {pos.shape} do not match "
            f"batch {b}"
        )
    if impl == "auto":
        impl = "xla"
    global _LAST_MULTIQUERY_IMPL
    _LAST_MULTIQUERY_IMPL = impl
    if impl == "xla":
        return _xla_paged_multiquery_attention(
            q, k_pages, v_pages, tables, pos, k_scale, v_scale
        )
    if impl == "reference":
        return reference_paged_multiquery_attention(
            q, k_pages, v_pages, tables, pos, k_scale, v_scale
        )
    raise ValueError(
        f"unknown paged multiquery attention impl: {impl!r}"
    )
