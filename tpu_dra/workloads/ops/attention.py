"""Attention: Pallas TPU flash kernel + XLA reference, one dispatcher.

TPU-first design notes:

- the flash kernel tiles queries over the grid and runs an **online
  softmax** over KV blocks entirely in VMEM, with fp32 accumulators and a
  causal block-skip (fully-masked KV blocks are never touched) — the
  standard flash schedule mapped onto MXU 128-lane tiling;
- GQA is resolved *outside* the kernel by logical head grouping (no K/V
  materialized repeat: we reshape queries to [kv_head, group, ...] so the
  kernel contracts each KV head against its query group);
- backward uses recompute (jax.custom_vjp around the kernel with the XLA
  reference's VJP) — the standard memory/FLOPs trade on TPU where remat is
  cheap relative to HBM;
- everything falls back to the XLA reference off-TPU (CPU tests, the
  driver's virtual-device dryrun) — same numerics, fp32 softmax.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp

log = logging.getLogger(__name__)

NEG_INF = -1e30


def _repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[b, s, kv_heads, hd] -> [b, s, kv_heads * n_rep, hd] (logical)."""
    if n_rep == 1:
        return x
    b, s, kvh, hd = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, kvh, n_rep, hd)
    ).reshape(b, s, kvh * n_rep, hd)


def reference_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
) -> jnp.ndarray:
    """XLA attention. q: [b, sq, h, hd]; k/v: [b, skv, kvh, hd]."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, skv = q.shape[1], k.shape[1]
        # Offset supports q being a suffix of the kv sequence (decode).
        mask = (
            jnp.arange(skv)[None, :]
            <= (jnp.arange(sq)[:, None] + (skv - sq))
        )
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# --- pallas flash kernel ----------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, sq: int, skv: int,
                  causal: bool, scale: float):
    """One (batch*head, q-block) program: online softmax over KV blocks."""
    import jax.experimental.pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, hd]
    block_q = q.shape[0]
    qi = pl.program_id(1)
    q_offset = qi * block_q + (skv - sq)  # global position of q row 0

    m0 = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), dtype=jnp.float32)

    num_kv_blocks = skv // block_k
    if causal:
        # Skip KV blocks entirely above the causal frontier.
        last_q_row = q_offset + block_q - 1
        num_visible = jnp.minimum(last_q_row // block_k + 1, num_kv_blocks)
    else:
        num_visible = num_kv_blocks

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_visible, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_attention_fwd_impl(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool,
    block_q: int, block_k: int,
) -> jnp.ndarray:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, hd = q.shape
    _, skv, kvh, _ = k.shape
    n_rep = h // kvh
    scale = hd**-0.5

    # Fold batch and KV-head into the grid; queries grouped per KV head so
    # GQA needs no repeated K/V in memory.
    qg = q.transpose(0, 2, 1, 3).reshape(b * kvh, n_rep * sq, hd)
    kg = k.transpose(0, 2, 1, 3).reshape(b * kvh, skv, hd)
    vg = v.transpose(0, 2, 1, 3).reshape(b * kvh, skv, hd)
    # Each query group member is an independent sequence; run grid over
    # (b*kvh*n_rep, q blocks) by viewing qg as [b*kvh*n_rep, sq, hd].
    qg = qg.reshape(b * kvh * n_rep, sq, hd)

    grid = (qg.shape[0], sq // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, sq=sq, skv=skv, causal=causal,
        scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, block_q, hd), lambda i, j: (i, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, skv, hd), lambda i, j: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, skv, hd), lambda i, j: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, hd), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM
        ),
    )(qg, _kv_for_groups(kg, n_rep), _kv_for_groups(vg, n_rep))
    out = out.reshape(b, kvh * n_rep, sq, hd).transpose(0, 2, 1, 3)
    return out


def _kv_for_groups(kv: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[b*kvh, skv, hd] -> [b*kvh*n_rep, skv, hd] — a broadcast view the
    BlockSpec indexes per program; XLA keeps this as a cheap gather."""
    if n_rep == 1:
        return kv
    bkv, skv, hd = kv.shape
    return jnp.broadcast_to(
        kv[:, None, :, :], (bkv, n_rep, skv, hd)
    ).reshape(bkv * n_rep, skv, hd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, block_q, block_k):
    return _flash_attention_fwd_impl(q, k, v, causal, block_q, block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out = _flash_attention_fwd_impl(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, residuals, g):
    # Recompute-based backward through the XLA reference (numerically
    # identical softmax; flash bwd kernel is a later optimization).
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: reference_attention(q, k, v, causal), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _pallas_ok(q, k, block_q, block_k) -> bool:
    try:
        if jax.devices()[0].platform != "tpu":
            return False
    except Exception:
        return False
    b, sq, h, hd = q.shape
    _, skv, kvh, _ = k.shape
    return (
        sq % block_q == 0
        and skv % block_k == 0
        and hd % 128 == 0
        and h % kvh == 0
    )


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    impl: str = "auto",
    block_q: int = 256,
    block_k: int = 256,
) -> jnp.ndarray:
    """q: [b, sq, heads, hd]; k/v: [b, skv, kv_heads, hd] -> [b, sq, heads, hd].

    impl: "auto" | "pallas" | "xla".
    """
    if impl == "auto":
        impl = "pallas" if _pallas_ok(q, k, block_q, block_k) else "xla"
    if impl == "pallas":
        return _flash_attention(q, k, v, causal, block_q, block_k)
    return reference_attention(q, k, v, causal)
