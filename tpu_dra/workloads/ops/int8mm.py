"""Weight-only int8 matmul: XLA path + an opt-in Pallas TPU kernel.

Measured on v5e, 1B model, batch-128 decode (r4): the plain XLA
dequant-matmul (`x @ w_q.astype(bf16) * scale`) wins — 9.36k tok/s vs
8.98k bf16 baseline — because XLA:TPU already fuses the int8->bf16
convert into the dot's operand feed instead of materializing bf16
weights. The Pallas kernel below does the same convert per-tile in VMEM
but LOSES at this shape (8.34k @ 512 tiles, 8.12k @ 1024 tiles): a
decode step issues ~112 skinny [128, K] x [K, N] calls whose per-call
overhead outweighs any streaming advantage. The kernel stays opt-in
(``TPU_DRA_INT8_KERNEL=1``) as the tuning surface for shapes where a
single big quantized matmul dominates; the dispatcher defaults to XLA.

Kernel schedule: grid (M/bm, N/bn, K/bk), K innermost ("arbitrary"
semantics) accumulating into a VMEM fp32 scratch; per-output-channel
scales apply once on the final K step. Off-TPU and non-tiling shapes use
the XLA path; ``_INTERPRET = True`` runs the kernel in interpreter mode
for hardware-free numerics tests.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

# Run the pallas kernel in interpreter mode (works on CPU; for tests).
_INTERPRET = False

# Kernel opt-in resolved ONCE at import: the choice is traced into the
# jit cache, so flipping the env var later in-process could never take
# effect anyway — capturing it here makes that explicit instead of
# silently reading a stale value at trace time.
_KERNEL_OPTED_IN = os.environ.get("TPU_DRA_INT8_KERNEL") == "1"

_BM, _BN, _BK = 128, 1024, 1024


def _xla_int8_matmul(x: jnp.ndarray, w_q: jnp.ndarray,
                     scale: jnp.ndarray) -> jnp.ndarray:
    y = x @ w_q.astype(x.dtype)
    return y * scale.astype(x.dtype)


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk: int):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(
        x_ref[...],
        w_ref[...].astype(x_ref.dtype),  # int8 -> compute dtype, in VMEM
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(2) == nk - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("bm", "bn", "bk", "interpret")
)
def _pallas_int8_matmul(x, w_q, scale, bm=_BM, bn=_BN, bk=_BK,
                        interpret=False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    _, n = w_q.shape
    nm, nn, nk = m // bm, n // bn, k // bk
    return pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, t: (i, t),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, t: (t, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j, t: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, t: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        # CompilerParams was named TPUCompilerParams on older pallas.
        compiler_params=getattr(
            pltpu, "CompilerParams",
            getattr(pltpu, "TPUCompilerParams", None),
        )(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x, w_q, scale)


def int8_matmul(x: jnp.ndarray, w_q: jnp.ndarray,
                scale: jnp.ndarray) -> jnp.ndarray:
    """``x @ dequant(w_q, scale)`` over arbitrary leading dims of x.
    x [..., K]; w_q int8 [K, N]; scale [1, N] -> [..., N]."""
    lead = x.shape[:-1]
    k = x.shape[-1]
    n = w_q.shape[1]
    m = 1
    for d in lead:
        m *= d
    tiles = m % _BM == 0 and n % _BN == 0 and k % _BK == 0
    use_kernel = tiles and (
        _INTERPRET
        or (
            _KERNEL_OPTED_IN
            and jax.default_backend() in ("tpu", "axon")
        )
    )
    x2 = x.reshape(m, k)
    if use_kernel:
        out = _pallas_int8_matmul(
            x2, w_q, scale.astype(jnp.float32), interpret=_INTERPRET
        )
    else:
        out = _xla_int8_matmul(x2, w_q, scale)
    return out.reshape(*lead, n)
