"""Paged KV cache: a block/page allocator over the int8 ``DecodeCache``
storage scheme (the serving engine's memory layer).

The fixed-batch decode path (workloads/generate.py) allocates one
contiguous ``[b, max_seq, kvh, hd]`` buffer per cache: every sequence
pays ``max_seq`` positions of HBM whether it is 30 tokens long or 3000,
and a sequence cannot leave the batch without reshuffling the buffer.
The engine (workloads/engine.py) replaces that with the vLLM-style
paged layout:

- **pools**: per layer, one shared ``[num_pages, page_size, kvh, hd]``
  K pool and one V pool (plus ``[num_pages, page_size, kvh]`` f32 scale
  pools in int8 mode — the same per-(token, head) symmetric scheme as
  ``quantize.quantize_kv``, scale 0 for all-zero rows so the zero-tail
  invariant stays checkable per page);
- **block tables**: each sequence owns an ordered list of page ids;
  position ``p`` of the sequence lives at ``(pages[p // page_size],
  p % page_size)``. Attention walks the table
  (ops/attention.py ``paged_decode_attention``), so compute and HBM
  traffic are bounded by the LIVE context, not the allocation;
- **ref-counted free list** (:class:`PageAllocator`): pages are
  acquired one at a time as sequences grow, released (and re-zeroed —
  the per-page zero-tail invariant) when a sequence finishes or is
  evicted, and ref-counted so a future prefix-sharing / speculative
  fork can alias one page into two tables without copying.

Page 0 is a RESERVED scratch page: it is never handed out, inactive
engine slots' masked writes land there, and block-table rows default to
it — so a gather through an unused table entry reads poison that the
length mask never admits, rather than aliasing a live sequence's page.

No reference counterpart (the reference is a DRA driver); this is the
workload-payload serving layer, proven by tests/test_paged_kv.py and
the engine parity suite.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from tpu_dra.workloads.models.llama import LlamaConfig
from tpu_dra.workloads.generate import KV_QUANT_MODES

# Page id 0 is the poison scratch page (see module doc).
SCRATCH_PAGE = 0


class PageExhaustedError(RuntimeError):
    """alloc() found the free list empty. The engine's reservation-gated
    admission makes this unreachable in normal operation; hitting it
    means an accounting bug or an admission path that skipped
    ``reserve()``."""


class PageAllocator:
    """Host-side ref-counted free list over ``num_pages`` pages.

    Pure bookkeeping — device arrays are owned by :class:`PagedKVCache`.
    ``reserve``/``unreserve`` implement admission control: the engine
    reserves a sequence's worst-case page count up front, so a sequence
    that was admitted can always grow to its limit without racing other
    sequences for the tail of the free list (mid-scan exhaustion is an
    invariant violation, not a runtime condition).
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"need >= 2 pages (page {SCRATCH_PAGE} is reserved "
                f"scratch), got {num_pages}"
            )
        self.num_pages = num_pages
        # LIFO free list: recently-freed (and freshly-zeroed) pages are
        # reused first, keeping the touched working set small.
        self._free = list(range(num_pages - 1, SCRATCH_PAGE, -1))
        self._ref = [0] * num_pages
        # Pages with refcount > 1, maintained incrementally so
        # shared_extra() costs O(#shared pages), not O(num_pages) —
        # it runs inside the engine's per-step metrics export.
        self._multi: set = set()
        self._reserved = 0
        self._min_free = len(self._free)
        # Lifetime count of alloc() calls that found the list empty —
        # exported by the engine as engine_page_exhausted_total.
        self.exhausted = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def reserved_pages(self) -> int:
        return self._reserved

    def can_reserve(self, n: int) -> bool:
        return self.free_pages - self._reserved >= n

    def reserve(self, n: int) -> bool:
        """Set aside ``n`` pages of admission headroom (no physical pages
        move). False when the unreserved free pool is too small."""
        if not self.can_reserve(n):
            return False
        self._reserved += n
        return True

    def unreserve(self, n: int) -> None:
        if n > self._reserved:
            raise ValueError(
                f"unreserve({n}) exceeds outstanding reservation "
                f"{self._reserved}"
            )
        self._reserved -= n

    def alloc(self) -> int:
        """Pop a free page (refcount 1). Callers holding a reservation
        should ``unreserve(1)`` alongside each alloc."""
        if not self._free:
            self.exhausted += 1
            raise PageExhaustedError(
                f"page pool exhausted ({self.num_pages} pages, "
                f"{self._reserved} reserved)"
            )
        page = self._free.pop()
        self._ref[page] = 1
        if len(self._free) < self._min_free:
            self._min_free = len(self._free)
        return page

    def incref(self, page: int) -> None:
        if self._ref[page] < 1:
            raise ValueError(f"incref of unallocated page {page}")
        self._ref[page] += 1
        self._multi.add(page)

    def decref(self, page: int) -> bool:
        """Drop one reference; True when the page was freed (refcount hit
        zero and it returned to the free list)."""
        if page == SCRATCH_PAGE:
            raise ValueError("scratch page is never allocated or freed")
        if self._ref[page] < 1:
            raise ValueError(f"decref of unallocated page {page}")
        self._ref[page] -= 1
        if self._ref[page] <= 1:
            self._multi.discard(page)
        if self._ref[page] == 0:
            self._free.append(page)
            return True
        return False

    def refcount(self, page: int) -> int:
        return self._ref[page]

    def shared_extra(self, discount=None) -> int:
        """Total extra references across all pages — how many page
        allocations prefix sharing is currently avoiding (a page with
        refcount r stands in for r separately-allocated copies, saving
        r - 1). ``discount`` maps page -> references held by a cache or
        registry rather than by a sequence: those stand in for no
        allocation (a registered-but-never-shared prefix saves
        nothing), so savings count only the effective refcount
        ``r - discount``. Exported by the engine as
        ``engine_prefix_shared_pages``. O(#shared pages): the scan
        covers only the incrementally-maintained refcount>1 set, so
        the per-step metrics export is free while sharing is idle."""
        total = 0
        for page in self._multi:
            eff = self._ref[page] - (
                discount.get(page, 0) if discount else 0
            )
            if eff > 1:
                total += eff - 1
        return total

    @property
    def min_free(self) -> int:
        """Low-water mark of the free list — ``num_pages - 1 -
        min_free`` is the peak number of pages simultaneously allocated
        over the allocator's lifetime (the honest memory number the
        prefix-sharing bench compares against its unshared twin)."""
        return self._min_free


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedKVCache:
    """Device half of the paged cache: per-layer page pools.

    ``k``/``v``: L-tuples of ``[num_pages, page_size, kvh, hd]`` (model
    dtype, or int8 with L-tuples of ``[num_pages, page_size, kvh]`` f32
    ``k_scale``/``v_scale``). Block tables and per-sequence lengths live
    with the engine (host-owned, mirrored to device per chunk) — the
    cache itself is position-agnostic, which is what makes pages
    reusable across sequences.

    INVARIANT (per page): an allocated page's slots at positions beyond
    the owning sequence's length are ZERO (values and scales), and FREE
    pages are entirely zero — ``init_paged_cache`` establishes it, the
    engine's write path preserves it (each step writes exactly the next
    position), and :func:`zero_pages` re-establishes it on free. The
    scratch page is exempt (it absorbs masked writes and holds poison by
    design). :func:`tail_is_zero` checks it for tests/debug runs."""

    k: tuple
    v: tuple
    k_scale: "tuple | None" = None
    v_scale: "tuple | None" = None

    def tree_flatten(self):
        return (self.k, self.v, self.k_scale, self.v_scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_pages(self) -> int:
        return self.k[0].shape[0]

    @property
    def page_size(self) -> int:
        return self.k[0].shape[1]

    @property
    def n_layers(self) -> int:
        return len(self.k)

    def _pools(self):
        pools = [("k", self.k), ("v", self.v)]
        if self.quantized:
            pools += [("k_scale", self.k_scale), ("v_scale", self.v_scale)]
        return pools


def init_paged_cache(
    config: LlamaConfig,
    num_pages: int,
    page_size: int,
    kv_quant: str = "none",
) -> PagedKVCache:
    if kv_quant not in KV_QUANT_MODES:
        raise ValueError(
            f"unknown kv_quant {kv_quant!r}; expected one of {KV_QUANT_MODES}"
        )
    quant = kv_quant == "int8"
    kv_dtype = jnp.int8 if quant else config.dtype
    shape = (num_pages, page_size, config.n_kv_heads, config.head_dim)
    sshape = (num_pages, page_size, config.n_kv_heads)
    L = config.n_layers
    return PagedKVCache(
        k=tuple(jnp.zeros(shape, kv_dtype) for _ in range(L)),
        v=tuple(jnp.zeros(shape, kv_dtype) for _ in range(L)),
        k_scale=tuple(jnp.zeros(sshape, jnp.float32) for _ in range(L))
        if quant else None,
        v_scale=tuple(jnp.zeros(sshape, jnp.float32) for _ in range(L))
        if quant else None,
    )


def zero_pages(cache: PagedKVCache, page_ids) -> PagedKVCache:
    """Zero the listed pages in every pool (values AND scales) — the
    free-side half of the per-page zero-tail invariant. Host-side (runs
    between engine chunks, not inside the jitted step); ``page_ids`` is
    a host list/array of pool indices."""
    ids = jnp.asarray(list(page_ids), jnp.int32)
    if ids.size == 0:
        return cache
    out = {}
    for name, pool in cache._pools():
        out[name] = tuple(p.at[ids].set(0) for p in pool)
    return PagedKVCache(**out)


def copy_page(cache: PagedKVCache, src: int, dst: int) -> PagedKVCache:
    """Copy one page's content (values AND scales, every layer) from
    ``src`` to ``dst`` — the copy-on-write fork: a sequence about to
    write into a page another table still references copies it first
    and writes into its private copy. The int8 scale pools travel with
    their pages, so a forked int8 sequence dequantizes identically to
    its parent. Host-side (runs between engine chunks)."""
    si = jnp.int32(src)
    di = jnp.int32(dst)
    out = {}
    for name, pool in cache._pools():
        out[name] = tuple(p.at[di].set(p[si]) for p in pool)
    return PagedKVCache(**out)


def copy_page_prefix(
    cache: PagedKVCache, src: int, dst: int, upto
) -> PagedKVCache:
    """Copy positions ``[0, upto)`` of page ``src`` into ``dst`` and
    ZERO the rest of ``dst`` — the frozen-prefix fork: a registered
    prefix ending mid-page freezes exactly the shared positions, so
    every later sharer sees a page whose tail honors the zero-tail
    invariant regardless of what the registering sequence wrote past
    the prefix. ``upto`` may be traced (one compiled scatter per pool
    shape, not per offset)."""
    si = jnp.int32(src)
    di = jnp.int32(dst)
    page = cache.page_size
    keep = jnp.arange(page) < upto  # [page]
    out = {}
    for name, pool in cache._pools():
        newpool = []
        for p in pool:
            mask = keep.reshape((page,) + (1,) * (p.ndim - 2))
            newpool.append(
                p.at[di].set(jnp.where(mask, p[si], 0).astype(p.dtype))
            )
        out[name] = tuple(newpool)
    return PagedKVCache(**out)


def zero_page_tail(cache: PagedKVCache, page_id: int, start) -> PagedKVCache:
    """Zero positions ``[start, page_size)`` of one page in every pool
    — the speculative-rewind half of the zero-tail invariant: rejected
    draft K/V written past the accepted length is wiped from the kept
    boundary page (pages wholly past it are freed and re-zeroed through
    the normal batch path). ``start`` may be traced. Exactly the
    frozen-prefix fork with src == dst, so the masked scatter lives in
    one place."""
    return copy_page_prefix(cache, page_id, page_id, start)


def tail_is_zero(cache: PagedKVCache, pages, length: int) -> bool:
    """Does the per-page zero-tail invariant hold for a sequence that
    owns ``pages`` (ordered page ids) with ``length`` positions written?
    Checks every pool slot of the sequence's pages at positions >=
    length — values and scales — across all layers. Host/test helper."""
    page = cache.page_size
    ok = True
    for j, pid in enumerate(pages):
        lo = max(0, min(page, length - j * page))
        if lo >= page:
            continue
        for _, pool in cache._pools():
            for layer in pool:
                tail = layer[pid, lo:]
                ok = ok and bool(
                    jnp.sum(jnp.abs(tail.astype(jnp.float32))) == 0
                )
    return ok


def pages_are_zero(cache: PagedKVCache, page_ids) -> bool:
    """True when every listed page is entirely zero in every pool (the
    free-page invariant — what a sequence admitted onto a recycled page
    relies on for its own tail)."""
    for pid in page_ids:
        for _, pool in cache._pools():
            for layer in pool:
                if bool(
                    jnp.sum(jnp.abs(layer[pid].astype(jnp.float32))) != 0
                ):
                    return False
    return True


# --- KV extents: ship a sequence's pages between caches (ISSUE 17) ---


@dataclasses.dataclass
class KVExtent:
    """A sequence's KV state lifted off its cache — the transferable
    unit behind live prefill→decode migration.

    ``slots[i]`` describes position range ``[i*page_size,
    (i+1)*page_size)`` of the sequence: either ``("page", source_id)``
    for a page carried BY ID (a shared-prefix page both caches can
    already reach — grafting increfs it instead of copying), or
    ``("payload", j)`` for a page whose content rides in ``payload``
    at row ``j``. ``payload`` maps pool name ("k"/"v" and, in int8
    mode, "k_scale"/"v_scale") to an L-tuple of host arrays of shape
    ``[n_payload, page_size, ...]`` — full pages including their zero
    tails, so the zero-tail invariant transfers with the content and
    needs no re-establishment on the destination."""

    page_size: int
    length: int
    quantized: bool
    slots: tuple  # of ("page", id) | ("payload", row)
    payload: dict  # pool name -> L-tuple of [n_payload, page, ...] host

    @property
    def n_pages(self) -> int:
        return len(self.slots)

    @property
    def n_payload_pages(self) -> int:
        return sum(1 for kind, _ in self.slots if kind == "payload")

    @property
    def n_shared_pages(self) -> int:
        return self.n_pages - self.n_payload_pages

    @property
    def nbytes(self) -> int:
        return sum(
            layer.nbytes for pool in self.payload.values() for layer in pool
        )


def serialize_extent(
    cache: PagedKVCache, pages, length: int, by_id=()
) -> KVExtent:
    """Gather a sequence's block-table extent off ``cache`` into host
    memory. ``pages`` is the ordered page-id list covering ``length``
    written positions; ids in ``by_id`` (shared-prefix pages the
    destination can reach without a copy) are carried by reference, the
    rest as full-page payload — one device gather per pool per layer,
    not per page. The caller must have flushed any deferred page
    zeroing first: a payload page is copied verbatim, zero tail and
    all."""
    import numpy as np

    pages = [int(p) for p in pages]
    by_id = set(int(p) for p in by_id)
    if length > len(pages) * cache.page_size:
        raise ValueError(
            f"length {length} exceeds {len(pages)} pages of "
            f"{cache.page_size}"
        )
    slots = []
    rows = []
    for pid in pages:
        if pid in by_id:
            slots.append(("page", pid))
        else:
            slots.append(("payload", len(rows)))
            rows.append(pid)
    payload = {}
    if rows:
        ids = jnp.asarray(rows, jnp.int32)
        for name, pool in cache._pools():
            payload[name] = tuple(
                np.asarray(jax.device_get(layer[ids])) for layer in pool
            )
    else:
        for name, _pool in cache._pools():
            payload[name] = ()
    return KVExtent(
        page_size=cache.page_size,
        length=length,
        quantized=cache.quantized,
        slots=tuple(slots),
        payload=payload,
    )


def graft_extent(
    cache: PagedKVCache,
    allocator: PageAllocator,
    extent: KVExtent,
    *,
    alloc=None,
    id_map=None,
    attach=None,
):
    """Materialize ``extent`` into ``cache``/``allocator``: by-id slots
    are INCREF'd (through ``id_map`` when the destination knows the
    shared pages under different ids), payload slots get fresh pages via
    ``alloc`` (defaults to ``allocator.alloc`` — the engine passes a
    callable that also burns its admission reservation) and one scatter
    per pool per layer writes their content. ``attach`` maps slot INDEX
    -> destination page id the importer already holds equivalent
    content for (a registered shared prefix): those slots incref the
    destination page instead of copying, payload or not. Returns
    ``(new_cache, pages)`` with ``pages`` the sequence's ordered block
    table. On any failure nothing is left allocated or increfed."""
    if extent.page_size != cache.page_size:
        raise ValueError(
            f"extent page_size {extent.page_size} != cache "
            f"{cache.page_size}"
        )
    if extent.quantized != cache.quantized:
        raise ValueError("extent/cache kv-quantization modes differ")
    alloc = alloc or allocator.alloc
    id_map = id_map or {}
    attach = attach or {}
    pages = []
    increfed = []
    fresh = []
    rows = []  # (payload row, fresh page) scatter pairs
    try:
        for i, (kind, val) in enumerate(extent.slots):
            if i in attach:
                pid = int(attach[i])
                allocator.incref(pid)
                increfed.append(pid)
            elif kind == "page":
                pid = int(id_map.get(val, val))
                allocator.incref(pid)
                increfed.append(pid)
            else:
                pid = alloc()
                fresh.append(pid)
                rows.append(val)
            pages.append(pid)
    except BaseException:
        for pid in increfed:
            allocator.decref(pid)
        for pid in fresh:
            allocator.decref(pid)
        raise
    if fresh:
        dst = jnp.asarray(fresh, jnp.int32)
        sel = jnp.asarray(rows, jnp.int32)
        out = {}
        for name, pool in cache._pools():
            out[name] = tuple(
                layer.at[dst].set(jnp.asarray(prows)[sel])
                for layer, prows in zip(pool, extent.payload[name])
            )
        cache = PagedKVCache(**out)
    return cache, pages
