"""Fast CPU decode smoke — ``make decodebench`` (wired into ``ci``).

A hardware-free gate on the r6 serving path (ISSUE 2): tiny config, a
handful of steps, asserting the things the full bench can only measure
on a chip —

1. the FUSED decode-attention path actually dispatches from the decode
   scan (both cache layouts; a silent fall-through to the prefill
   einsum would void every roofline claim),
2. the fused op matches the naive fp32 oracle on a random cache (bf16
   and int8 storage),
3. int8-KV greedy decode agrees with bf16 decode token-for-token on a
   short horizon (the argmax-agreement bar from the acceptance
   criteria),
4. the fused sampler is token-identical to the unfused per-token loop
   for a fixed key (the <= 5% sampled-gap gate's correctness half).

Prints one JSON line; exits nonzero on any violation — the same
contract as bench.py legs, so CI sees a regression before a TPU run
does.
"""

from __future__ import annotations

import json
import sys
import time


def measure_step_breakdown(
    config,
    params,
    batch: int,
    ctx_len: int,
    reps: int = 10,
    temperature: float = 0.8,
    top_k: int = 40,
) -> dict:
    """Per-component timing of ONE decode step at a fixed context length
    — the measurement ROADMAP item 4 demands before any fusion work:
    attention is near its HBM floor (BENCH_r05: 1.046x), so the roofline
    gap lives in everything AROUND it, and this attributes the step to
    attention vs qkv/wo projections vs MLP vs embed/norm vs logits vs
    sampling, each as its own jitted, fetch-closed timing over ``reps``
    calls against the SAME cache state (the unrolled in-place layout the
    bench decodes with).

    Also times the full fused greedy step and the full sampled step, so
    the ``decode_sampled_vs_greedy`` gap is attributable per-component
    (``sampling_ms`` vs ``attention_ms`` — the ISSUE 8 satellite).
    ``residual_ms`` = step - sum(parts): dispatch/fusion slack the
    components don't explain (negative means XLA fuses across the
    component boundaries — also worth knowing). Returns a JSON-ready
    dict (the bench records it as ``decode_step_breakdown``)."""
    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.generate import (
        _mm,
        _project_qkv,
        _rms,
        forward_chunk,
        init_cache,
        sample_token,
        unroll_params,
    )
    from tpu_dra.workloads.icibandwidth import fetch
    from tpu_dra.workloads.models.llama import rope_frequencies
    from tpu_dra.workloads.ops.attention import decode_attention
    from tpu_dra.workloads.ops.decode_mlp import decode_mlp

    c = config
    params = unroll_params(params)
    max_seq = -(-(ctx_len + 1) // 64) * 64
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(
        rng, (batch, ctx_len), 1, c.vocab_size, jnp.int32
    )
    cache = init_cache(c, batch, max_seq, stacked=False)
    cache, _ = jax.jit(
        lambda p, cc, t: forward_chunk(c, p, cc, t)
    )(params, cache, prompt)
    tok = prompt[:, -1:]
    x = jax.random.normal(
        jax.random.PRNGKey(1), (batch, 1, c.dim), c.dtype
    )
    q1 = jax.random.normal(
        jax.random.PRNGKey(2), (batch, c.n_heads, c.head_dim), c.dtype
    )
    logits = jax.random.normal(
        jax.random.PRNGKey(3), (batch, c.vocab_size), jnp.float32
    )

    def fetch_tree(out):
        for leaf in jax.tree_util.tree_leaves(out):
            fetch(leaf)

    def timed(fn, *args) -> float:
        f = jax.jit(fn)
        fetch_tree(f(*args))  # compile + warm outside the timing
        t0 = time.monotonic()
        for _ in range(reps):
            out = f(*args)
        fetch_tree(out)
        return (time.monotonic() - t0) / reps * 1e3

    def attention_all(cc, q):
        outs = []
        for i in range(c.n_layers):
            outs.append(decode_attention(
                q, cc.k[i], cc.v[i], cc.pos,
                k_scale=None if cc.k_scale is None else cc.k_scale[i],
                v_scale=None if cc.v_scale is None else cc.v_scale[i],
                impl=c.decode_impl, block_k=c.decode_block_k,
            ))
        return jnp.stack(outs)

    def qkv_all(p, xx):
        cos, sin = rope_frequencies(c, cache.pos + jnp.arange(1))
        outs = []
        for i in range(c.n_layers):
            outs.append(_project_qkv(
                c, p[f"layer_{i}"], xx, cos, sin, batch, 1
            )[0])
        return jnp.stack(outs)

    def attn_out_all(p, q):
        flat = q.reshape(batch, 1, c.n_heads * c.head_dim)
        return jnp.stack([
            _mm(flat, p[f"layer_{i}"]["attention"]["wo"])
            for i in range(c.n_layers)
        ])

    def mlp_all(p, xx):
        x2 = xx[:, 0]
        outs = []
        for i in range(c.n_layers):
            lp = p[f"layer_{i}"]
            outs.append(decode_mlp(
                x2, lp["mlp_norm"]["scale"], lp["mlp"], c.norm_eps,
                impl=c.decode_mlp_impl, block_f=c.decode_mlp_block_f,
            ))
        return jnp.stack(outs)

    def embed_norm(p, t, xx):
        emb = p["embed"]["embedding"].astype(c.dtype)[t]
        return emb, _rms(xx, p["final_norm"]["scale"], c.norm_eps)

    def logits_head(p, xx):
        # The final norm is timed in embed_norm; this times ONLY the
        # lm_head matmul (xx stands in for the normalized activation —
        # same shape/dtype), so the parts sum counts the norm once.
        return _mm(xx, p["lm_head"]).astype(jnp.float32)

    def greedy_step(p, cc, t):
        cc2, lg = forward_chunk(c, p, cc, t)
        return cc2.pos, jnp.argmax(lg[:, -1], axis=-1)

    def sampled_step(p, cc, t, r):
        cc2, lg = forward_chunk(c, p, cc, t)
        return cc2.pos, sample_token(lg[:, -1], r, temperature, top_k)

    step_ms = timed(greedy_step, params, cache, tok)
    sampled_ms = timed(
        sampled_step, params, cache, tok, jax.random.PRNGKey(9)
    )
    parts = {
        "attention_ms": timed(attention_all, cache, q1),
        "qkv_ms": timed(qkv_all, params, x),
        "attn_out_ms": timed(attn_out_all, params, q1[:, None]),
        "mlp_ms": timed(mlp_all, params, x),
        "embed_norm_ms": timed(embed_norm, params, tok, x),
        "logits_ms": timed(logits_head, params, x),
    }
    sampling_ms = timed(
        lambda lg, r: sample_token(lg, r, temperature, top_k),
        logits, jax.random.PRNGKey(9),
    )
    explained = sum(parts.values())
    out = {
        "ctx_len": ctx_len,
        "batch": batch,
        "reps": reps,
        "step_ms": round(step_ms, 3),
        "sampled_step_ms": round(sampled_ms, 3),
        "sampling_ms": round(sampling_ms, 3),
        # The sampled-vs-greedy gap, attributed: the step-level delta
        # next to the isolated sampler cost (they should roughly agree;
        # a large difference means the sampler is breaking fusion
        # somewhere else in the scan body).
        "sampled_overhead_ms": round(sampled_ms - step_ms, 3),
        "residual_ms": round(step_ms - explained, 3),
    }
    out.update({k: round(v, 3) for k, v in parts.items()})
    for k, v in parts.items():
        out[k.replace("_ms", "_frac")] = round(v / max(step_ms, 1e-9), 3)
    return out


def main() -> int:
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dra.workloads.generate import (
        greedy_generate,
        sample_generate,
        sample_generate_unfused,
    )
    from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama
    from tpu_dra.workloads.ops import attention as A
    from tpu_dra.workloads.ops import decode_mlp as DM
    from tpu_dra.workloads.quantize import dequantize_kv, quantize_kv

    report = {"ok": False}

    # (2) op-level parity on a random cache, both storages.
    b, S, h, kvh, hd = 2, 32, 8, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, S, kvh, hd))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, S, kvh, hd))
    L = jnp.int32(21)  # chunk-unaligned on purpose
    ref = A.reference_decode_attention(q, k, v, L)
    got = A.decode_attention(q, k, v, L, impl="xla", block_k=8)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err < 1e-4, f"fused decode attention drifted {err} from oracle"
    k8, ks = quantize_kv(k)
    v8, vs = quantize_kv(v)
    refq = A.reference_decode_attention(
        q, dequantize_kv(k8, ks), dequantize_kv(v8, vs), L
    )
    gotq = A.decode_attention(
        q, k8, v8, L, k_scale=ks, v_scale=vs, impl="xla", block_k=8
    )
    errq = float(jnp.max(jnp.abs(gotq - refq)))
    assert errq < 1e-4, f"int8 fused decode attention drifted {errq}"
    report["op_max_err"] = err
    report["op_int8_max_err"] = errq

    # (1) + (3): generation through both layouts; the dispatch probe is
    # trace-time, so reading it after the traced call is sound.
    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    new_tokens = 12
    for scan in (True, False):
        c = dataclasses.replace(cfg, scan_layers=scan)
        model = Llama(c)
        params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=8)
        prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
        A._LAST_DECODE_IMPL = None
        DM._LAST_DECODE_MLP_IMPL = None
        t0 = time.monotonic()
        out_bf16 = greedy_generate(c, params, prompt, new_tokens)
        assert A._LAST_DECODE_IMPL in ("xla", "pallas"), (
            f"decode scan never dispatched the fused op "
            f"(scan_layers={scan}; saw {A._LAST_DECODE_IMPL!r})"
        )
        assert DM._LAST_DECODE_MLP_IMPL in ("xla", "pallas"), (
            f"decode scan never dispatched the fused MLP block "
            f"(scan_layers={scan}; saw {DM._LAST_DECODE_MLP_IMPL!r})"
        )
        out_int8 = greedy_generate(
            c, params, prompt, new_tokens, kv_quant="int8"
        )
        agree = float(
            np.mean(np.asarray(out_bf16[:, 8:]) == np.asarray(out_int8[:, 8:]))
        )
        layout = "stacked" if scan else "unrolled"
        assert agree >= 0.99, (
            f"int8-KV disagreed with bf16 decode: {agree:.3f} ({layout})"
        )
        report[f"{layout}_impl"] = A._LAST_DECODE_IMPL
        report[f"{layout}_int8kv_token_agreement"] = agree
        report[f"{layout}_seconds"] = round(time.monotonic() - t0, 2)

    # (5) int8 weight-only as a generate-path knob (ISSUE 8: the full
    # decode path — prefill, projections, MLP, logits — over the
    # quantized tree, previously engine-only): near-total token
    # agreement with the full-precision run on a short horizon.
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    base = greedy_generate(cfg, params, prompt, new_tokens)
    w8 = greedy_generate(
        cfg, params, prompt, new_tokens, weight_quant="int8"
    )
    w8_agree = float(
        np.mean(np.asarray(base[:, 8:]) == np.asarray(w8[:, 8:]))
    )
    assert w8_agree >= 0.95, (
        f"int8 weight-only decode disagreed with bf16: {w8_agree:.3f}"
    )
    report["w8_token_agreement"] = w8_agree

    # (6) the step-breakdown profiler (ISSUE 8 tentpole): every
    # component key present and positive — the TPU bench records this
    # dict as decode_step_breakdown, and the optimization loop is
    # driven by it, so its schema is a CI contract.
    bd = measure_step_breakdown(cfg, params, batch=2, ctx_len=24, reps=2)
    for key in (
        "step_ms", "sampled_step_ms", "sampling_ms", "attention_ms",
        "qkv_ms", "attn_out_ms", "mlp_ms", "embed_norm_ms", "logits_ms",
        "residual_ms", "attention_frac",
    ):
        assert key in bd, f"step breakdown missing {key}"
        if key.endswith("_ms") and "residual" not in key:
            assert bd[key] > 0, f"step breakdown {key} = {bd[key]}"
    report["breakdown_step_ms"] = bd["step_ms"]
    report["breakdown_attention_frac"] = bd["attention_frac"]

    # (4) fused sampler == unfused oracle, fixed key.
    prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    rng = jax.random.PRNGKey(5)
    fused = sample_generate(
        cfg, params, prompt, new_tokens, rng, temperature=0.8, top_k=8
    )
    unfused = sample_generate_unfused(
        cfg, params, prompt, new_tokens, rng, temperature=0.8, top_k=8
    )
    assert jnp.array_equal(fused, unfused), "fused sampler diverged"
    report["sampler_parity"] = True

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
