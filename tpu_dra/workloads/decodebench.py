"""Fast CPU decode smoke — ``make decodebench`` (wired into ``ci``).

A hardware-free gate on the r6 serving path (ISSUE 2): tiny config, a
handful of steps, asserting the things the full bench can only measure
on a chip —

1. the FUSED decode-attention path actually dispatches from the decode
   scan (both cache layouts; a silent fall-through to the prefill
   einsum would void every roofline claim),
2. the fused op matches the naive fp32 oracle on a random cache (bf16
   and int8 storage),
3. int8-KV greedy decode agrees with bf16 decode token-for-token on a
   short horizon (the argmax-agreement bar from the acceptance
   criteria),
4. the fused sampler is token-identical to the unfused per-token loop
   for a fixed key (the <= 5% sampled-gap gate's correctness half).

Prints one JSON line; exits nonzero on any violation — the same
contract as bench.py legs, so CI sees a regression before a TPU run
does.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dra.workloads.generate import (
        greedy_generate,
        sample_generate,
        sample_generate_unfused,
    )
    from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama
    from tpu_dra.workloads.ops import attention as A
    from tpu_dra.workloads.quantize import dequantize_kv, quantize_kv

    report = {"ok": False}

    # (2) op-level parity on a random cache, both storages.
    b, S, h, kvh, hd = 2, 32, 8, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, S, kvh, hd))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, S, kvh, hd))
    L = jnp.int32(21)  # chunk-unaligned on purpose
    ref = A.reference_decode_attention(q, k, v, L)
    got = A.decode_attention(q, k, v, L, impl="xla", block_k=8)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err < 1e-4, f"fused decode attention drifted {err} from oracle"
    k8, ks = quantize_kv(k)
    v8, vs = quantize_kv(v)
    refq = A.reference_decode_attention(
        q, dequantize_kv(k8, ks), dequantize_kv(v8, vs), L
    )
    gotq = A.decode_attention(
        q, k8, v8, L, k_scale=ks, v_scale=vs, impl="xla", block_k=8
    )
    errq = float(jnp.max(jnp.abs(gotq - refq)))
    assert errq < 1e-4, f"int8 fused decode attention drifted {errq}"
    report["op_max_err"] = err
    report["op_int8_max_err"] = errq

    # (1) + (3): generation through both layouts; the dispatch probe is
    # trace-time, so reading it after the traced call is sound.
    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    new_tokens = 12
    for scan in (True, False):
        c = dataclasses.replace(cfg, scan_layers=scan)
        model = Llama(c)
        params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=8)
        prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
        A._LAST_DECODE_IMPL = None
        t0 = time.monotonic()
        out_bf16 = greedy_generate(c, params, prompt, new_tokens)
        assert A._LAST_DECODE_IMPL in ("xla", "pallas"), (
            f"decode scan never dispatched the fused op "
            f"(scan_layers={scan}; saw {A._LAST_DECODE_IMPL!r})"
        )
        out_int8 = greedy_generate(
            c, params, prompt, new_tokens, kv_quant="int8"
        )
        agree = float(
            np.mean(np.asarray(out_bf16[:, 8:]) == np.asarray(out_int8[:, 8:]))
        )
        layout = "stacked" if scan else "unrolled"
        assert agree >= 0.99, (
            f"int8-KV disagreed with bf16 decode: {agree:.3f} ({layout})"
        )
        report[f"{layout}_impl"] = A._LAST_DECODE_IMPL
        report[f"{layout}_int8kv_token_agreement"] = agree
        report[f"{layout}_seconds"] = round(time.monotonic() - t0, 2)

    # (4) fused sampler == unfused oracle, fixed key.
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    rng = jax.random.PRNGKey(5)
    fused = sample_generate(
        cfg, params, prompt, new_tokens, rng, temperature=0.8, top_k=8
    )
    unfused = sample_generate_unfused(
        cfg, params, prompt, new_tokens, rng, temperature=0.8, top_k=8
    )
    assert jnp.array_equal(fused, unfused), "fused sampler diverged"
    report["sampler_parity"] = True

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
