"""Llama-3 family (flax) — the flagship benchmark workload.

TPU-first choices:

- bf16 everywhere on the forward path (MXU-native), fp32 for softmax,
  RMSNorm statistics, and the final logits;
- GQA (grouped-query attention), RoPE, SwiGLU — the Llama-3 architecture;
- ``scan_layers`` runs the decoder stack under ``nn.scan`` so XLA traces
  ONE layer (compile time + code cache stay flat as depth grows), with
  per-layer remat (``nn.remat``) trading FLOPs for HBM;
- no data-dependent Python control flow anywhere under jit; static shapes
  only;
- attention dispatches to the pallas flash kernel on TPU and the XLA
  reference elsewhere (tpu_dra/workloads/ops/attention.py), or to ring
  attention when sequence parallelism is active
  (tpu_dra/workloads/parallel/ring_attention.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_dra.workloads.ops.attention import attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.bfloat16
    scan_layers: bool = True
    remat: bool = True
    # "nothing": recompute everything (min HBM); "dots": save matmul
    # outputs and recompute only elementwise ops — the MXU work is the
    # expensive part, so this buys most of remat's memory win at a
    # fraction of its FLOP cost.
    remat_policy: str = "nothing"  # nothing | dots
    attention_impl: str = "auto"  # auto | pallas | xla | ring | ulysses
    # Flash-kernel tile sizes (pallas/auto paths); bench-swept.
    attention_block_q: int = 256
    attention_block_k: int = 256
    # Stream the LM-head loss over sequence chunks instead of
    # materializing [b, s, vocab] fp32 logits (ops/loss.py) — a large
    # HBM win at real vocab sizes; the training step picks this up via
    # the model's return_hidden path.
    fused_ce: bool = False
    ce_chunk: int = 256
    # Serving path (workloads/generate.py): fused single-query decode
    # attention dispatch ("auto" | "pallas" | "xla" | "reference") and
    # its cache-length chunk size (ops/attention.py decode_attention).
    decode_impl: str = "auto"
    decode_block_k: int = 256
    # Fused decode MLP+norm block for the s=1 step (ops/decode_mlp.py:
    # pallas ffn-block streaming kernel on TPU, the identical xla op
    # chain elsewhere) and its ffn tile width.
    decode_mlp_impl: str = "auto"  # auto | pallas | xla | reference
    decode_mlp_block_f: int = 512
    # Block-table attention dispatch for the paged serving engine
    # (ops/attention.py paged_decode_attention). Multi-device sharded
    # decode forces "xla": pallas custom calls have no SPMD partitioning
    # rule, so under GSPMD they would replicate and all-gather the very
    # weight/KV shards the mesh exists to split (engine/bench set this
    # alongside decode_mlp_impl when the decode mesh spans >1 device).
    paged_decode_impl: str = "auto"  # auto | pallas | xla | reference

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


LLAMA3_8B = LlamaConfig()

# Hardware-free test/dryrun config.
TINY_LLAMA = LlamaConfig(
    vocab_size=256,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    ffn_dim=128,
    rope_theta=10_000.0,
    scan_layers=True,
    remat=False,
)


def _remat_policy(name: str):
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "nothing":
        return jax.checkpoint_policies.nothing_saveable
    raise ValueError(f"unknown remat_policy: {name!r}")


def rope_frequencies(config: LlamaConfig, positions: jnp.ndarray) -> tuple:
    """cos/sin tables for rotary embeddings; positions [b, s] or [s]."""
    hd = config.head_dim
    inv_freq = 1.0 / (
        config.rope_theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., hd/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [b, s, h, hd]; cos/sin: [b, s, hd/2] or [s, hd/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if cos.ndim == 2:  # [s, hd/2] -> [1, s, 1, hd/2]
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    elif cos.ndim == 3:  # [b, s, hd/2] -> [b, s, 1, hd/2]
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    param_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param(
            "scale", nn.initializers.ones, (x.shape[-1],), self.param_dtype
        )
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        normed = x32 * jax.lax.rsqrt(var + self.eps)
        return (normed * scale.astype(jnp.float32)).astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, cos, sin) -> jnp.ndarray:
        c = self.config
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats,
            use_bias=False,
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            name=name,
        )
        b, s, _ = x.shape
        q = dense(c.n_heads * c.head_dim, "wq")(x)
        k = dense(c.n_kv_heads * c.head_dim, "wk")(x)
        v = dense(c.n_kv_heads * c.head_dim, "wv")(x)
        q = q.reshape(b, s, c.n_heads, c.head_dim)
        k = k.reshape(b, s, c.n_kv_heads, c.head_dim)
        v = v.reshape(b, s, c.n_kv_heads, c.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if c.attention_impl == "ulysses":
            from tpu_dra.workloads.parallel.ulysses import ulysses_attention

            out = ulysses_attention(q, k, v)
        elif c.attention_impl == "ring":
            from tpu_dra.workloads.parallel.ring_attention import (
                ring_attention,
            )

            out = ring_attention(q, k, v)
        else:
            out = attention(
                q, k, v, causal=True, impl=c.attention_impl,
                block_q=c.attention_block_q, block_k=c.attention_block_k,
            )
        out = out.reshape(b, s, c.n_heads * c.head_dim)
        return dense(c.dim, "wo")(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = self.config
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats,
            use_bias=False,
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            name=name,
        )
        gate = dense(c.ffn_dim, "w_gate")(x)
        up = dense(c.ffn_dim, "w_up")(x)
        return dense(c.dim, "w_down")(nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, cos, sin) -> jnp.ndarray:
        c = self.config
        x = x + LlamaAttention(c, name="attention")(
            RMSNorm(c.norm_eps, c.param_dtype, name="attention_norm")(x), cos, sin
        )
        x = x + LlamaMLP(c, name="mlp")(
            RMSNorm(c.norm_eps, c.param_dtype, name="mlp_norm")(x)
        )
        return x


class _ScannedBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin):
        return LlamaBlock(self.config, name="block")(x, cos, sin), None


class Llama(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(
        self, tokens: jnp.ndarray, return_hidden: bool = False
    ) -> jnp.ndarray:
        """tokens [b, s] int32 -> logits [b, s, vocab] (fp32), or the
        final-norm hidden states [b, s, dim] (compute dtype) when
        ``return_hidden`` — the fused-loss path applies the LM head
        chunk-by-chunk itself (ops/loss.py)."""
        c = self.config
        embed = nn.Embed(
            c.vocab_size,
            c.dim,
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            embedding_init=nn.initializers.normal(0.02),
            name="embed",
        )
        x = embed(tokens)
        positions = jnp.arange(tokens.shape[1])
        cos, sin = rope_frequencies(c, positions)

        if c.scan_layers:
            block = _ScannedBlock
            if c.remat:
                block = nn.remat(
                    block,
                    prevent_cse=False,
                    policy=_remat_policy(c.remat_policy),
                )
            x, _ = nn.scan(
                block,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=c.n_layers,
                in_axes=(nn.broadcast, nn.broadcast),
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(c, name="layers")(x, cos, sin)
        else:
            # nn.remat is a lifted transform: it wraps the CLASS (an
            # instance target raises TransformTargetError).
            block_cls = LlamaBlock
            if c.remat:
                block_cls = nn.remat(
                    LlamaBlock, policy=_remat_policy(c.remat_policy)
                )
            for i in range(c.n_layers):
                x = block_cls(c, name=f"layer_{i}")(x, cos, sin)

        x = RMSNorm(c.norm_eps, c.param_dtype, name="final_norm")(x)
        if return_hidden:
            # The LM head is still initialized (init traces the default
            # call); the fused loss reads its kernel from the param tree.
            return x
        logits = nn.Dense(
            c.vocab_size,
            use_bias=False,
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            name="lm_head",
        )(x)
        return logits.astype(jnp.float32)

    def init_params(self, rng, batch: int = 1, seq: int = 8):
        tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
        return self.init(rng, tokens)["params"]


def num_params(config: LlamaConfig) -> int:
    c = config
    per_layer = (
        c.dim * c.n_heads * c.head_dim  # wq
        + 2 * c.dim * c.n_kv_heads * c.head_dim  # wk, wv
        + c.n_heads * c.head_dim * c.dim  # wo
        + 3 * c.dim * c.ffn_dim  # gate, up, down
        + 2 * c.dim  # norms
    )
    return (
        c.vocab_size * c.dim  # embed
        + c.n_layers * per_layer
        + c.dim  # final norm
        + c.dim * c.vocab_size  # lm head
    )


def train_flops_per_token(config: LlamaConfig, seq: int) -> float:
    """Analytic MODEL FLOPs per trained token: 6 FLOPs per matmul
    parameter (fwd 2, bwd 4) plus the causal-attention score/value
    matmuls (4*seq*dim fwd at half visibility, tripled for training).
    Standard MFU accounting — rematerialized recompute does NOT count,
    so MFU stays comparable across remat policies."""
    c = config
    matmul_params = num_params(c) - c.vocab_size * c.dim  # embed lookup isn't a matmul
    return 6.0 * matmul_params + 6.0 * c.n_layers * c.dim * seq
