"""Model families the driver's benchmark/smoke workloads run."""

from tpu_dra.workloads.models.llama import (  # noqa: F401
    LLAMA3_8B,
    TINY_LLAMA,
    Llama,
    LlamaConfig,
)
from tpu_dra.workloads.models.mixtral import (  # noqa: F401
    MIXTRAL_8X7B,
    TINY_MIXTRAL,
    Mixtral,
    MixtralConfig,
)


def build_model(config):
    """Model instance for a family config (LlamaConfig | MixtralConfig)."""
    if isinstance(config, MixtralConfig):
        return Mixtral(config)
    if isinstance(config, LlamaConfig):
        return Llama(config)
    raise TypeError(f"unknown model config type: {type(config).__name__}")
