"""Model families the driver's benchmark/smoke workloads run."""

from tpu_dra.workloads.models.llama import (  # noqa: F401
    LLAMA3_8B,
    TINY_LLAMA,
    Llama,
    LlamaConfig,
)
