"""Mixtral family (flax) — sparse-MoE workload with expert parallelism.

The second flagship model family: Mixtral-8x7B-style sparse mixture of
experts — Llama attention (GQA + RoPE) with the dense SwiGLU MLP replaced
by a top-k routed expert layer.

TPU-first choices (why this is NOT a torch-MoE translation):

- **Static-shape capacity routing.** Token→expert assignment is expressed
  as dense one-hot dispatch/combine tensors (Switch-Transformer style), so
  every shape is static under jit: no gather/scatter with data-dependent
  sizes, no sorting networks. Dropped tokens (over capacity) pass through
  the residual, as in the reference MoE systems.
- **Expert compute = one batched einsum per projection.** Expert weights
  live in a single ``[E, d, f]`` array; the per-expert FFN is a 3D
  ``einsum`` that XLA tiles straight onto the MXU — no Python loop over
  experts, no ragged batching.
- **Expert parallelism via sharding, not send/recv.** The expert dim is
  sharded over the ``ep`` mesh axis (rules in parallel/mesh.py); XLA
  lowers the dispatch/combine einsums to all-to-alls over ICI. pjit owns
  the schedule — the model code never names a collective.
- Router runs in fp32 (softmax numerics), experts in bf16 (MXU).
- Load-balance auxiliary loss (`sown` under ``"aux_loss"``) keeps routing
  uniform, per the Switch/Mixtral recipe.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_dra.workloads.models.llama import (
    LlamaAttention,
    LlamaConfig,
    RMSNorm,
    rope_frequencies,
)


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32_000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    rope_theta: float = 1_000_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.bfloat16
    scan_layers: bool = True
    remat: bool = True
    attention_impl: str = "auto"  # auto | pallas | xla | ring | ulysses

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def attention_config(self) -> LlamaConfig:
        """The attention sub-module reuses the Llama implementation."""
        return LlamaConfig(
            vocab_size=self.vocab_size,
            dim=self.dim,
            n_layers=self.n_layers,
            n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads,
            ffn_dim=self.ffn_dim,
            rope_theta=self.rope_theta,
            norm_eps=self.norm_eps,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            attention_impl=self.attention_impl,
        )

    def capacity(self, seq: int) -> int:
        """Per-expert token-slot capacity for a length-``seq`` sequence."""
        return max(
            1,
            int(math.ceil(self.top_k * seq * self.capacity_factor / self.n_experts)),
        )


MIXTRAL_8X7B = MixtralConfig()

# Hardware-free test/dryrun config.
TINY_MIXTRAL = MixtralConfig(
    vocab_size=256,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    ffn_dim=128,
    n_experts=4,
    top_k=2,
    rope_theta=10_000.0,
    remat=False,
)


class MixtralMoE(nn.Module):
    """Top-k routed SwiGLU expert layer with capacity-based dispatch."""

    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = self.config
        b, s, d = x.shape
        cap = c.capacity(s)

        # --- router (fp32) ---
        router_logits = nn.Dense(
            c.n_experts,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            name="router",
        )(x.astype(jnp.float32))  # [b, s, E]
        probs = jax.nn.softmax(router_logits, axis=-1)
        gate, idx = jax.lax.top_k(probs, c.top_k)  # [b, s, k]
        gate = gate / jnp.maximum(jnp.sum(gate, axis=-1, keepdims=True), 1e-9)

        # --- load-balance aux loss (Switch eq. 4): E * sum_e f_e * p_e ---
        token_frac = jnp.mean(
            jax.nn.one_hot(idx[..., 0], c.n_experts, dtype=jnp.float32),
            axis=(0, 1),
        )
        prob_frac = jnp.mean(probs, axis=(0, 1))
        aux = c.n_experts * jnp.sum(token_frac * prob_frac)
        self.sow("aux_loss", "moe", c.router_aux_weight * aux)

        # --- capacity assignment: position of each (token, slot) in its
        # expert's buffer, computed with a cumsum over flattened slots so
        # shapes stay static (dropped slots fall through the residual) ---
        slot_mask = jax.nn.one_hot(idx, c.n_experts, dtype=jnp.float32)
        # [b, s*k, E] in slot order (token-major: all of token 0's k slots
        # first), matching Mixtral's priority of earlier tokens.
        flat_mask = slot_mask.reshape(b, s * c.top_k, c.n_experts)
        position = jnp.cumsum(flat_mask, axis=1) - 1.0  # [b, s*k, E]
        keep = flat_mask * (position < cap)
        dispatch = keep[..., None] * jax.nn.one_hot(
            position.astype(jnp.int32), cap, dtype=jnp.float32
        )  # [b, s*k, E, C]
        flat_gate = gate.reshape(b, s * c.top_k)
        combine = dispatch * flat_gate[..., None, None]  # [b, s*k, E, C]

        # --- dispatch tokens to expert buffers: all-to-all over ep when
        # the expert dim is sharded ---
        x_slots = jnp.repeat(x, c.top_k, axis=1)  # [b, s*k, d]
        xe = jnp.einsum(
            "btec,btd->ebcd", dispatch.astype(c.dtype), x_slots
        )  # [E, b, C, d]

        # --- per-expert SwiGLU, batched over E on the MXU ---
        init = nn.initializers.normal(0.02)
        w_gate = self.param(
            "experts_w_gate", init, (c.n_experts, d, c.ffn_dim), c.param_dtype
        )
        w_up = self.param(
            "experts_w_up", init, (c.n_experts, d, c.ffn_dim), c.param_dtype
        )
        w_down = self.param(
            "experts_w_down", init, (c.n_experts, c.ffn_dim, d), c.param_dtype
        )
        h = nn.silu(jnp.einsum("ebcd,edf->ebcf", xe, w_gate)) * jnp.einsum(
            "ebcd,edf->ebcf", xe, w_up
        )
        ye = jnp.einsum("ebcf,efd->ebcd", h, w_down)  # [E, b, C, d]

        # --- combine back to token order, weighted by the gates ---
        y_slots = jnp.einsum("ebcd,btec->btd", ye, combine.astype(c.dtype))
        y = y_slots.reshape(b, s, c.top_k, d).sum(axis=2)
        return y.astype(x.dtype)


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, cos, sin) -> jnp.ndarray:
        c = self.config
        attn_c = c.attention_config()
        x = x + LlamaAttention(attn_c, name="attention")(
            RMSNorm(c.norm_eps, c.param_dtype, name="attention_norm")(x), cos, sin
        )
        x = x + MixtralMoE(c, name="moe")(
            RMSNorm(c.norm_eps, c.param_dtype, name="moe_norm")(x)
        )
        return x


class _ScannedMixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x, cos, sin):
        return MixtralBlock(self.config, name="block")(x, cos, sin), None


class Mixtral(nn.Module):
    """tokens [b, s] int32 -> logits [b, s, vocab] (fp32)."""

    config: MixtralConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        c = self.config
        x = nn.Embed(
            c.vocab_size,
            c.dim,
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            embedding_init=nn.initializers.normal(0.02),
            name="embed",
        )(tokens)
        positions = jnp.arange(tokens.shape[1])
        cos, sin = rope_frequencies(c.attention_config(), positions)

        if c.scan_layers:
            block = _ScannedMixtralBlock
            if c.remat:
                block = nn.remat(
                    block,
                    prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable,
                )
            x, _ = nn.scan(
                block,
                variable_axes={"params": 0, "aux_loss": 0},
                split_rngs={"params": True},
                length=c.n_layers,
                in_axes=(nn.broadcast, nn.broadcast),
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(c, name="layers")(x, cos, sin)
        else:
            for i in range(c.n_layers):
                blk = MixtralBlock(c, name=f"layer_{i}")
                if c.remat:
                    blk = nn.remat(blk)
                x = blk(x, cos, sin)

        x = RMSNorm(c.norm_eps, c.param_dtype, name="final_norm")(x)
        logits = nn.Dense(
            c.vocab_size,
            use_bias=False,
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            name="lm_head",
        )(x)
        return logits.astype(jnp.float32)

    def init_params(self, rng, batch: int = 1, seq: int = 8):
        tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
        return self.init(rng, tokens)["params"]

    def apply_with_aux(self, params, tokens: jnp.ndarray):
        """(logits, total aux loss) — aux collected across layers."""
        logits, aux = self.apply(
            {"params": params}, tokens, mutable=["aux_loss"]
        )
        total = sum(
            jnp.sum(v) for v in jax.tree_util.tree_leaves(aux.get("aux_loss", {}))
        )
        return logits, total
