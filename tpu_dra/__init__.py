"""tpu-dra-driver: a TPU-native Kubernetes Dynamic Resource Allocation driver.

A from-scratch re-imagining of the NVIDIA DRA driver for GPUs
(reference: fabiendupont/k8s-dra-driver-gpu) for Cloud TPU:

- chip discovery via vfio-pci / /dev/accel / libtpu metadata instead of NVML
  (reference: cmd/gpu-kubelet-plugin/nvlib.go)
- dynamic TPU sub-slice reshaping in place of dynamic MIG partitioning
  (reference: cmd/gpu-kubelet-plugin/partitions.go, nvlib.go:860-1089)
- per-process chip multiplexing in place of MPS
  (reference: cmd/gpu-kubelet-plugin/sharing.go)
- ComputeDomains orchestrating multi-host ICI pod-slice topology instead of
  IMEX / Multi-Node NVLink (reference: cmd/compute-domain-*)

Package layout (mapping to the reference's layer map, SURVEY.md §1):

- ``tpu_dra.api``            -> api/nvidia.com/resource/v1beta1
- ``tpu_dra.k8sclient``      -> pkg/nvidia.com generated clients (+fakes)
- ``tpu_dra.infra``          -> pkg/{featuregates,flags,flock,workqueue}, internal/
- ``tpu_dra.tpulib``         -> nvlib.go / go-nvml hardware abstraction
- ``tpu_dra.plugin``         -> cmd/gpu-kubelet-plugin
- ``tpu_dra.computedomain``  -> cmd/compute-domain-{controller,daemon,kubelet-plugin}
- ``tpu_dra.webhook``        -> cmd/webhook
- ``tpu_dra.workloads``      -> the JAX/XLA payloads the driver schedules
  (models/ops/parallel/utils: Llama-3 pjit flagship, pallas kernels,
  ring-attention sequence parallelism, mesh/sharding helpers)
"""

from tpu_dra.version import __version__  # noqa: F401
