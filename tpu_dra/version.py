"""Version stamping.

Reference analog: internal/info/version.go:22-43 (ldflags injection wired in
Makefile:104-107). Here the build injects GIT_COMMIT via the environment or
the generated ``_build_info.py``; defaults keep dev builds identifiable.
"""

from __future__ import annotations

import os

__version__ = "0.1.0-dev"

DRIVER_NAME = "tpu.google.com"
CD_DRIVER_NAME = "compute-domain.tpu.google.com"

# API group served by our CRDs and opaque device configs.
API_GROUP = "resource.tpu.google.com"
API_VERSION = "v1beta1"


def git_commit() -> str:
    try:
        from tpu_dra import _build_info  # type: ignore

        return _build_info.GIT_COMMIT
    except Exception:
        return os.environ.get("TPU_DRA_GIT_COMMIT", "unknown")


def version_string() -> str:
    return f"{__version__}+{git_commit()[:12]}"
