"""Chip-sharing config types.

Reference analog: api/nvidia.com/resource/v1beta1/sharing.go. The GPU
strategies map onto TPU-native mechanisms:

- ``TimeSlicing``   — cooperative runtime time-share of one chip. On GPUs this
  maps to ``nvidia-smi compute-policy --set-timeslice``; on TPU it maps to the
  runtime scheduler knob carried into the workload env.
- ``Multiplexing``  — the MPS analog: multiple processes on one chip via the
  TPU runtime's per-process multiplexing, bounded by a per-process HBM limit
  (the pinned-device-memory-limit analog, sharing.go:73-80) and a per-process
  share of compute (the active-thread-percentage analog).

``PerProcessHbmLimit`` keeps the reference's selector algebra
(sharing.go MpsPerDevicePinnedMemoryLimit.Normalize): keys may be a device
index ("0") or a device UUID, an explicit per-device entry overrides the
default limit, and unknown selectors are rejected.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu_dra.api.quantity import Quantity
from tpu_dra.api.serde import ApiError, Field, Serde, nested, quantity_codec

TIME_SLICING_STRATEGY = "TimeSlicing"
MULTIPLEXING_STRATEGY = "Multiplexing"

DEFAULT_TIME_SLICE = "Default"
SHORT_TIME_SLICE = "Short"
MEDIUM_TIME_SLICE = "Medium"
LONG_TIME_SLICE = "Long"

_TIME_SLICE_ORDINALS = {
    DEFAULT_TIME_SLICE: 0,
    SHORT_TIME_SLICE: 1,
    MEDIUM_TIME_SLICE: 2,
    LONG_TIME_SLICE: 3,
}


def time_slice_ordinal(interval: str) -> int:
    """Runtime knob value for a named interval (sharing.go TimeSliceInterval.Int)."""
    return _TIME_SLICE_ORDINALS.get(interval, -1)


class InvalidDeviceSelector(ApiError):
    pass


class InvalidLimit(ApiError):
    pass


@dataclass
class TimeSlicingConfig(Serde):
    interval: Optional[str] = None

    FIELDS = {"interval": Field("interval")}

    def validate(self) -> None:
        if self.interval is not None and self.interval not in _TIME_SLICE_ORDINALS:
            raise ApiError(
                f"unknown time-slice interval: {self.interval!r} "
                f"(want one of {sorted(_TIME_SLICE_ORDINALS)})"
            )


class PerProcessHbmLimit(dict):
    """Map of device selector (index or UUID) -> HBM limit Quantity."""

    @classmethod
    def from_dict(cls, d, strict: bool = True) -> "PerProcessHbmLimit":
        out = cls()
        for k, v in (d or {}).items():
            out[str(k)] = Quantity.parse(v)
        return out

    def to_dict(self):
        return {k: str(v) for k, v in self.items()}

    def normalize(
        self,
        uuids: List[str],
        default_limit: Optional[Quantity],
    ) -> Dict[str, str]:
        """Resolve selectors against the claim's device UUIDs.

        Mirrors MpsPerDevicePinnedMemoryLimit.Normalize: start from the
        default limit applied to every device (when set), then apply
        per-device overrides; a key may be a positional index into ``uuids``
        or a UUID; anything else is an invalid selector.
        """
        limits: Dict[str, str] = {}
        if default_limit is not None:
            for u in uuids:
                limits[u] = str(default_limit)
        for k, v in self.items():
            uuid = self._resolve(k, uuids)
            limits[uuid] = str(v)
        return limits

    @staticmethod
    def _resolve(key: str, uuids: List[str]) -> str:
        if key in uuids:
            return key
        if key.isdigit():
            idx = int(key)
            if 0 <= idx < len(uuids):
                return uuids[idx]
            raise InvalidDeviceSelector(
                f"device index {idx} out of range (have {len(uuids)} devices)"
            )
        raise InvalidDeviceSelector(f"invalid device selector: {key!r}")


def _per_proc_codec():
    def dec(v, strict):
        if v is None:
            return None
        return PerProcessHbmLimit.from_dict(v, strict=strict)

    def enc(v):
        if v is None:
            return None
        return v.to_dict()

    return dec, enc


@dataclass
class MultiplexingConfig(Serde):
    """MPS-analog config (sharing.go MpsConfig)."""

    # Percentage of chip compute each client may use (active-thread-% analog).
    default_compute_share_percentage: Optional[int] = None
    # HBM limit applied to all devices unless overridden per-device.
    default_hbm_limit: Optional[Quantity] = None
    # Per-device overrides keyed by index or UUID.
    default_per_device_hbm_limit: Optional[PerProcessHbmLimit] = None

    FIELDS = {
        "defaultComputeSharePercentage": Field("default_compute_share_percentage"),
        "defaultHbmLimit": Field("default_hbm_limit", *quantity_codec()),
        "defaultPerDeviceHbmLimit": Field(
            "default_per_device_hbm_limit", *_per_proc_codec()
        ),
    }

    def validate(self) -> None:
        p = self.default_compute_share_percentage
        if p is not None and not (0 < p <= 100):
            raise ApiError(
                f"defaultComputeSharePercentage must be in (0, 100], got {p}"
            )
        if self.default_hbm_limit is not None and self.default_hbm_limit.to_bytes() <= 0:
            raise InvalidLimit(
                f"defaultHbmLimit must be positive, got {self.default_hbm_limit}"
            )
        for k, v in (self.default_per_device_hbm_limit or {}).items():
            if v.to_bytes() <= 0:
                raise InvalidLimit(f"per-device HBM limit for {k!r} must be positive")

    def normalized_limits(self, uuids: List[str]) -> Dict[str, str]:
        per_dev = self.default_per_device_hbm_limit or PerProcessHbmLimit()
        return per_dev.normalize(uuids, self.default_hbm_limit)


@dataclass
class TpuSharing(Serde):
    """Sharing settings for a full-chip device (sharing.go GpuSharing)."""

    strategy: str = ""
    time_slicing_config: Optional[TimeSlicingConfig] = None
    multiplexing_config: Optional[MultiplexingConfig] = None

    FIELDS = {
        "strategy": Field("strategy", required=True),
        "timeSlicingConfig": Field("time_slicing_config", *nested(TimeSlicingConfig)),
        "multiplexingConfig": Field("multiplexing_config", *nested(MultiplexingConfig)),
    }

    def is_time_slicing(self) -> bool:
        return self.strategy == TIME_SLICING_STRATEGY

    def is_multiplexing(self) -> bool:
        return self.strategy == MULTIPLEXING_STRATEGY

    def get_time_slicing_config(self) -> Optional[TimeSlicingConfig]:
        if self.strategy != TIME_SLICING_STRATEGY:
            raise ApiError(f"strategy is not set to {TIME_SLICING_STRATEGY!r}")
        if self.multiplexing_config is not None:
            raise ApiError(
                f"cannot use multiplexingConfig with the "
                f"{TIME_SLICING_STRATEGY!r} strategy"
            )
        return self.time_slicing_config

    def get_multiplexing_config(self) -> Optional[MultiplexingConfig]:
        if self.strategy != MULTIPLEXING_STRATEGY:
            raise ApiError(f"strategy is not set to {MULTIPLEXING_STRATEGY!r}")
        if self.time_slicing_config is not None:
            raise ApiError(
                f"cannot use timeSlicingConfig with the "
                f"{MULTIPLEXING_STRATEGY!r} strategy"
            )
        return self.multiplexing_config

    def validate(self) -> None:
        from tpu_dra.infra import featuregates as fg

        if self.strategy == TIME_SLICING_STRATEGY:
            if not fg.enabled(fg.TIME_SLICING_SETTINGS):
                raise ApiError(
                    "time-slicing settings require the TimeSlicingSettings "
                    "feature gate"
                )
            if self.multiplexing_config is not None:
                raise ApiError("multiplexingConfig invalid with TimeSlicing strategy")
            if self.time_slicing_config is not None:
                self.time_slicing_config.validate()
        elif self.strategy == MULTIPLEXING_STRATEGY:
            if not fg.enabled(fg.MULTIPLEXING_SUPPORT):
                raise ApiError(
                    "multiplexing requires the MultiplexingSupport feature gate"
                )
            # Composes with DynamicSubslice (r5; the reference's
            # MPS-on-dynamic-MIG, device_state.go:653-677): a dynamic
            # placement's parent chips are fixed at enumeration, and the
            # overlap defenses prevent any reshape of a held sub-slice's
            # chips, so the arbiter's chip set is lease-stable. (r3/r4
            # refused this combination; the refusal was over-broad.)
            if self.time_slicing_config is not None:
                raise ApiError("timeSlicingConfig invalid with Multiplexing strategy")
            if self.multiplexing_config is not None:
                self.multiplexing_config.validate()
        else:
            raise ApiError(f"unknown sharing strategy: {self.strategy!r}")


@dataclass
class TpuSubsliceSharing(Serde):
    """Sharing settings for a sub-slice device (sharing.go MigDeviceSharing):
    sub-slices support multiplexing but not time-slicing settings."""

    strategy: str = ""
    multiplexing_config: Optional[MultiplexingConfig] = None

    FIELDS = {
        "strategy": Field("strategy", required=True),
        "multiplexingConfig": Field("multiplexing_config", *nested(MultiplexingConfig)),
    }

    def is_time_slicing(self) -> bool:
        return self.strategy == TIME_SLICING_STRATEGY

    def is_multiplexing(self) -> bool:
        return self.strategy == MULTIPLEXING_STRATEGY

    def get_multiplexing_config(self) -> Optional[MultiplexingConfig]:
        if self.strategy != MULTIPLEXING_STRATEGY:
            raise ApiError(f"strategy is not set to {MULTIPLEXING_STRATEGY!r}")
        return self.multiplexing_config

    def validate(self) -> None:
        from tpu_dra.infra import featuregates as fg

        if self.strategy == TIME_SLICING_STRATEGY:
            return  # accepted as a no-op on sub-slices (reference parity)
        if self.strategy == MULTIPLEXING_STRATEGY:
            if not fg.enabled(fg.MULTIPLEXING_SUPPORT):
                raise ApiError(
                    "multiplexing requires the MultiplexingSupport feature gate"
                )
            # Valid on static AND dynamic sub-slices (r5): the arbiter
            # owns the sub-slice's parent chips either way — fixed by the
            # placement before materialization, reshape-protected by the
            # overlap defenses for the lease's life (the reference's
            # MPS-on-MIG incl. dynamic, device_state.go:653-677).
            if self.multiplexing_config is not None:
                self.multiplexing_config.validate()
            return
        raise ApiError(f"unknown sharing strategy: {self.strategy!r}")
