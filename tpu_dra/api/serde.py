"""Typed JSON (de)serialization with strict/nonstrict modes.

Reference analog: api/nvidia.com/resource/v1beta1/api.go:41-98 — a scheme
mapping (apiVersion, kind) to types, with a StrictDecoder (fails on unknown
fields; for user input) and a NonstrictDecoder (drops unknown fields; for
checkpoint JSON written by older/newer driver versions).

Types register themselves with :func:`register`; each declares a
``FIELDS: dict[json_key, Field]`` table that drives decode/encode. Nested
types, lists, and Quantity values are supported declaratively.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple, Type

from tpu_dra.api.errors import ApiError, DecodeError  # noqa: F401 — ApiError re-exported via tpu_dra.api
from tpu_dra.api.quantity import Quantity


class Interface:
    """Common API for all config types (api.go:41-44)."""

    def normalize(self) -> None:
        raise NotImplementedError

    def validate(self) -> None:
        raise NotImplementedError


@dataclass(frozen=True)
class Field:
    """Declarative field spec: attribute name + optional nested codec."""

    attr: str
    # decode: json value -> python value; encode: python value -> json value
    decode: Optional[Callable[[Any, bool], Any]] = None
    encode: Optional[Callable[[Any], Any]] = None
    required: bool = False


def nested(cls: type) -> Tuple[Callable, Callable]:
    def dec(v, strict):
        if v is None:
            return None
        return cls.from_dict(v, strict=strict)

    def enc(v):
        if v is None:
            return None
        return v.to_dict()

    return dec, enc


def nested_list(cls: type) -> Tuple[Callable, Callable]:
    def dec(v, strict):
        if v is None:
            return None
        return [cls.from_dict(x, strict=strict) for x in v]

    def enc(v):
        if v is None:
            return None
        return [x.to_dict() for x in v]

    return dec, enc


def quantity_codec() -> Tuple[Callable, Callable]:
    def dec(v, strict):
        if v is None:
            return None
        return Quantity.parse(v)

    def enc(v):
        if v is None:
            return None
        return str(v)

    return dec, enc


class Serde:
    """Mixin implementing FIELDS-driven from_dict/to_dict."""

    FIELDS: Dict[str, Field] = {}

    @classmethod
    def from_dict(cls, d: Dict[str, Any], strict: bool = True):
        if not isinstance(d, dict):
            raise DecodeError(f"{cls.__name__}: expected object, got {type(d).__name__}")
        known = set(cls.FIELDS)
        unknown = set(d) - known - {"apiVersion", "kind"}
        if strict and unknown:
            raise DecodeError(
                f"{cls.__name__}: unknown field(s): {sorted(unknown)}"
            )
        kwargs = {}
        for key, f in cls.FIELDS.items():
            if key in d:
                v = d[key]
                kwargs[f.attr] = f.decode(v, strict) if f.decode else v
            elif f.required:
                raise DecodeError(f"{cls.__name__}: missing required field {key!r}")
        return cls(**kwargs)  # type: ignore[call-arg]

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, f in self.FIELDS.items():
            v = getattr(self, f.attr)
            if v is None or (v == [] and not isinstance(v, (int, float))):
                continue
            out[key] = f.encode(v) if f.encode else v
        return out


# (group/version, kind) -> type registry; the runtime.Scheme analog.
_REGISTRY: Dict[Tuple[str, str], Type] = {}


def register(api_version: str, kind: str):
    def wrap(cls):
        _REGISTRY[(api_version, kind)] = cls
        cls.API_VERSION = api_version
        cls.KIND = kind
        return cls

    return wrap


def registered_kinds() -> Dict[Tuple[str, str], Type]:
    return dict(_REGISTRY)


def decode(data: "bytes | str | Dict[str, Any]", strict: bool):
    """Decode a typed object keyed on apiVersion+kind."""
    if isinstance(data, (bytes, str)):
        try:
            d = json.loads(data)
        except json.JSONDecodeError as e:
            raise DecodeError(f"invalid JSON: {e}") from e
    else:
        d = data
    if not isinstance(d, dict):
        raise DecodeError(f"expected JSON object, got {type(d).__name__}")
    av, kind = d.get("apiVersion"), d.get("kind")
    if not av or not kind:
        raise DecodeError("object is missing apiVersion and/or kind")
    cls = _REGISTRY.get((av, kind))
    if cls is None:
        raise DecodeError(f"no kind {kind!r} registered for {av!r}")
    return cls.from_dict(d, strict=strict)


def strict_decode(data):
    return decode(data, strict=True)


def nonstrict_decode(data):
    return decode(data, strict=False)


def encode(obj) -> str:
    d = {"apiVersion": obj.API_VERSION, "kind": obj.KIND}
    d.update(obj.to_dict())
    return json.dumps(d, sort_keys=True)
