"""Error hierarchy for the API layer.

All errors that user-supplied claim config can trigger derive from
:class:`ApiError`, so the kubelet plugins can catch one type and convert it
into a typed NodePrepareResources failure.
"""


class ApiError(ValueError):
    pass


class DecodeError(ApiError):
    pass


class QuantityError(ApiError):
    pass
