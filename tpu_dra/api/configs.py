"""Opaque device-config types embedded in ResourceClaims.

Reference analog: api/nvidia.com/resource/v1beta1/{gpuconfig.go, migconfig.go,
vfiodeviceconfig.go, computedomainconfig.go}. Semantics preserved:

- each type implements normalize() (fill defaults, feature-gate-aware) and
  validate();
- defaults are feature-gate dependent: e.g. a default TpuConfig carries
  time-slicing settings only when the TimeSlicingSettings gate is on
  (gpuconfig.go DefaultGpuConfig);
- ComputeDomain{Channel,Daemon}Config carry the domainID that ties a claim
  back to its ComputeDomain (computedomainconfig.go).
"""

from __future__ import annotations

import uuid as uuidlib
from dataclasses import dataclass
from typing import Optional

from tpu_dra.api.serde import ApiError, Field, Interface, Serde, nested, register
from tpu_dra.api.sharing import (
    DEFAULT_TIME_SLICE,
    MULTIPLEXING_STRATEGY,
    TIME_SLICING_STRATEGY,
    MultiplexingConfig,
    TimeSlicingConfig,
    TpuSharing,
    TpuSubsliceSharing,
)

_API_VERSION = "resource.tpu.google.com/v1beta1"


@register(_API_VERSION, "TpuConfig")
@dataclass
class TpuConfig(Serde, Interface):
    """Config for a full-chip device claim (gpuconfig.go GpuConfig)."""

    sharing: Optional[TpuSharing] = None

    FIELDS = {"sharing": Field("sharing", *nested(TpuSharing))}

    def normalize(self) -> None:
        from tpu_dra.infra import featuregates as fg

        if self.sharing is None:
            if not fg.enabled(fg.TIME_SLICING_SETTINGS):
                return
            self.sharing = TpuSharing(strategy=TIME_SLICING_STRATEGY)

        if fg.enabled(fg.TIME_SLICING_SETTINGS):
            if (
                self.sharing.strategy == TIME_SLICING_STRATEGY
                and self.sharing.time_slicing_config is None
            ):
                self.sharing.time_slicing_config = TimeSlicingConfig(
                    interval=DEFAULT_TIME_SLICE
                )
        if fg.enabled(fg.MULTIPLEXING_SUPPORT):
            if (
                self.sharing.strategy == MULTIPLEXING_STRATEGY
                and self.sharing.multiplexing_config is None
            ):
                self.sharing.multiplexing_config = MultiplexingConfig()

    def validate(self) -> None:
        if self.sharing is None:
            return
        self.sharing.validate()


def default_tpu_config() -> TpuConfig:
    from tpu_dra.infra import featuregates as fg

    cfg = TpuConfig()
    if fg.enabled(fg.TIME_SLICING_SETTINGS):
        cfg.sharing = TpuSharing(
            strategy=TIME_SLICING_STRATEGY,
            time_slicing_config=TimeSlicingConfig(interval=DEFAULT_TIME_SLICE),
        )
    return cfg


@register(_API_VERSION, "TpuSubsliceConfig")
@dataclass
class TpuSubsliceConfig(Serde, Interface):
    """Config for a sub-slice device claim (migconfig.go MigDeviceConfig)."""

    sharing: Optional[TpuSubsliceSharing] = None

    FIELDS = {"sharing": Field("sharing", *nested(TpuSubsliceSharing))}

    def normalize(self) -> None:
        from tpu_dra.infra import featuregates as fg

        if self.sharing is None:
            if not fg.enabled(fg.TIME_SLICING_SETTINGS):
                return
            self.sharing = TpuSubsliceSharing(strategy=TIME_SLICING_STRATEGY)
        if fg.enabled(fg.MULTIPLEXING_SUPPORT):
            if (
                self.sharing.strategy == MULTIPLEXING_STRATEGY
                and self.sharing.multiplexing_config is None
            ):
                self.sharing.multiplexing_config = MultiplexingConfig()

    def validate(self) -> None:
        if self.sharing is None:
            return
        self.sharing.validate()


def default_tpu_subslice_config() -> TpuSubsliceConfig:
    from tpu_dra.infra import featuregates as fg

    cfg = TpuSubsliceConfig()
    if fg.enabled(fg.TIME_SLICING_SETTINGS):
        cfg.sharing = TpuSubsliceSharing(strategy=TIME_SLICING_STRATEGY)
    return cfg


@register(_API_VERSION, "VfioDeviceConfig")
@dataclass
class VfioDeviceConfig(Serde, Interface):
    """Config requesting vfio-pci passthrough of a chip
    (vfiodeviceconfig.go). Carries no fields; its presence selects the path."""

    FIELDS = {}

    def normalize(self) -> None:
        return

    def validate(self) -> None:
        return


def default_vfio_device_config() -> Optional[VfioDeviceConfig]:
    from tpu_dra.infra import featuregates as fg

    if not fg.enabled(fg.PASSTHROUGH_SUPPORT):
        return None
    return VfioDeviceConfig()


def _validate_domain_id(domain_id: str) -> None:
    if not domain_id:
        raise ApiError("domainID cannot be empty")
    try:
        uuidlib.UUID(domain_id)
    except ValueError as e:
        raise ApiError(f"domainID must be a UUID: {domain_id!r}") from e


@register(_API_VERSION, "ComputeDomainChannelConfig")
@dataclass
class ComputeDomainChannelConfig(Serde, Interface):
    """Opaque config on workload channel claims (computedomainconfig.go:28-34).

    ``domain_id`` is the ComputeDomain's UID; ``allocation_mode`` selects one
    channel vs. all channels (computedomain.go AllocationMode values).
    """

    domain_id: str = ""
    allocation_mode: str = ""

    FIELDS = {
        "domainID": Field("domain_id", required=True),
        "allocationMode": Field("allocation_mode"),
    }

    def normalize(self) -> None:
        return

    def validate(self) -> None:
        _validate_domain_id(self.domain_id)
        if self.allocation_mode not in ("", "Single", "All"):
            raise ApiError(
                f"allocationMode must be 'Single' or 'All', got "
                f"{self.allocation_mode!r}"
            )


@register(_API_VERSION, "ComputeDomainDaemonConfig")
@dataclass
class ComputeDomainDaemonConfig(Serde, Interface):
    """Opaque config on the per-node daemon claim
    (computedomainconfig.go:60-65)."""

    domain_id: str = ""

    FIELDS = {"domainID": Field("domain_id", required=True)}

    def normalize(self) -> None:
        return

    def validate(self) -> None:
        _validate_domain_id(self.domain_id)
