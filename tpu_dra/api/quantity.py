"""Kubernetes-style resource quantities.

The reference leans on ``k8s.io/apimachinery/pkg/api/resource.Quantity`` for
MPS pinned-memory limits (api/nvidia.com/resource/v1beta1/sharing.go:60,
75-80). The TPU build needs the same grammar for per-process HBM limits, so
this implements the subset of the k8s quantity grammar the driver uses:
plain integers, decimal SI suffixes (k, M, G, T, P, E, m for milli) and
binary suffixes (Ki, Mi, Gi, Ti, Pi, Ei).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from fractions import Fraction
from functools import total_ordering

from tpu_dra.api.errors import QuantityError

_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {
    "m": Fraction(1, 1000),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
    "": 1,
}

_RE = re.compile(r"^([+-]?[0-9]+(?:\.[0-9]+)?)(Ki|Mi|Gi|Ti|Pi|Ei|m|k|M|G|T|P|E)?$")


@total_ordering
@dataclass(frozen=True)
class Quantity:
    """An immutable quantity; preserves the original string form."""

    raw: str

    def __post_init__(self):
        m = _RE.match(self.raw.strip())
        if not m:
            raise QuantityError(f"unparseable quantity: {self.raw!r}")
        num, suffix = m.groups()
        mult = _BINARY.get(suffix or "") or _DECIMAL.get(suffix or "")
        if mult is None:
            raise QuantityError(f"unknown suffix in quantity: {self.raw!r}")
        object.__setattr__(self, "_value", Fraction(num) * Fraction(mult))

    @property
    def value(self) -> Fraction:
        return self._value  # type: ignore[attr-defined]

    def to_bytes(self) -> int:
        """Integral value (ceil), the form device runtimes consume."""
        return math.ceil(self.value)

    def __str__(self) -> str:
        return self.raw

    def __eq__(self, other) -> bool:
        if isinstance(other, Quantity):
            return self.value == other.value
        return NotImplemented

    def __lt__(self, other: "Quantity") -> bool:
        if not isinstance(other, Quantity):
            return NotImplemented
        return self.value < other.value

    def __hash__(self) -> int:
        return hash(self.value)

    @classmethod
    def parse(cls, s: "str | int | Quantity") -> "Quantity":
        if isinstance(s, Quantity):
            return s
        if isinstance(s, int):
            return cls(str(s))
        return cls(s)
