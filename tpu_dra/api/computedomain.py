"""ComputeDomain and ComputeDomainClique CRD types.

Reference analog: api/nvidia.com/resource/v1beta1/computedomain.go:39-48 and
computedomainclique.go:30-41.

TPU-native semantics: a ComputeDomain represents one multi-host **ICI
pod-slice** (plus optional DCN-connected extensions) instead of an IMEX/MNNVL
domain. A *clique* is the physical ICI domain — all hosts wired into one TPU
pod slice — named ``<cdUID>.<cliqueID>`` where cliqueID is the slice/ICI
fabric identifier discovered on-node (the NVLink clusterUUID.cliqueId analog,
cmd/compute-domain-kubelet-plugin/nvlib.go:188-357).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra.api.serde import Field, Serde, nested, nested_list, register

_API_VERSION = "resource.tpu.google.com/v1beta1"

CD_STATUS_NONE = ""
CD_STATUS_READY = "Ready"
CD_STATUS_NOT_READY = "NotReady"
# A previously-Ready domain that lost a node under nodeLossPolicy=failFast:
# terminal-until-recovery, so workloads and operators can distinguish
# "lost a member" from "still assembling" (both NotReady in the reference).
CD_STATUS_FAILED = "Failed"

# spec.nodeLossPolicy: what a Ready domain does when a registered node is
# lost (stale heartbeat / NotReady daemon).
NODE_LOSS_FAIL_FAST = "failFast"  # default: fail the domain promptly
NODE_LOSS_SHRINK = "shrink"       # prune the lost node; stay Ready on the
                                  # surviving hosts
NODE_LOSS_POLICIES = (NODE_LOSS_FAIL_FAST, NODE_LOSS_SHRINK)

CHANNEL_ALLOCATION_MODE_SINGLE = "Single"
CHANNEL_ALLOCATION_MODE_ALL = "All"


@dataclass
class ObjectMeta(Serde):
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[dict] = field(default_factory=list)
    deletion_timestamp: Optional[str] = None
    creation_timestamp: Optional[str] = None
    generation: int = 0
    # Standard apiserver-managed metadata we carry but never interpret; listed
    # so strict decoding of objects fetched from a real cluster succeeds.
    generate_name: str = ""
    managed_fields: Optional[list] = None
    self_link: str = ""
    deletion_grace_period_seconds: Optional[int] = None

    FIELDS = {
        "name": Field("name"),
        "namespace": Field("namespace"),
        "uid": Field("uid"),
        "resourceVersion": Field("resource_version"),
        "labels": Field("labels"),
        "annotations": Field("annotations"),
        "finalizers": Field("finalizers"),
        "ownerReferences": Field("owner_references"),
        "deletionTimestamp": Field("deletion_timestamp"),
        "creationTimestamp": Field("creation_timestamp"),
        "generation": Field("generation"),
        "generateName": Field("generate_name"),
        "managedFields": Field("managed_fields"),
        "selfLink": Field("self_link"),
        "deletionGracePeriodSeconds": Field("deletion_grace_period_seconds"),
    }


@dataclass
class ComputeDomainResourceClaimTemplate(Serde):
    name: str = ""

    FIELDS = {"name": Field("name", required=True)}


@dataclass
class ComputeDomainChannelSpec(Serde):
    resource_claim_template: ComputeDomainResourceClaimTemplate = field(
        default_factory=ComputeDomainResourceClaimTemplate
    )
    allocation_mode: str = ""

    FIELDS = {
        "resourceClaimTemplate": Field(
            "resource_claim_template",
            *nested(ComputeDomainResourceClaimTemplate),
            required=True,
        ),
        "allocationMode": Field("allocation_mode"),
    }


@dataclass
class ComputeDomainSpec(Serde):
    """numNodes = number of hosts in the slice; topology optionally pins the
    ICI mesh shape (e.g. "4x4" for v5p-16) — a TPU-native extension the
    scheduler and daemon use to validate complete slice membership."""

    num_nodes: int = 0
    channel: Optional[ComputeDomainChannelSpec] = None
    topology: str = ""
    accelerator_type: str = ""
    # Multi-slice (DCN/megascale) domains: number of ICI pod slices the
    # domain spans; must divide numNodes. 1 = single-slice (the common case).
    num_slices: int = 1
    # Node-loss policy for a Ready domain: "failFast" (default; the domain
    # goes Failed promptly so the job restarts) or "shrink" (the lost
    # node's registration is pruned and the domain stays Ready over the
    # survivors).
    node_loss_policy: str = ""

    FIELDS = {
        "numNodes": Field("num_nodes", required=True),
        "channel": Field("channel", *nested(ComputeDomainChannelSpec)),
        "topology": Field("topology"),
        "acceleratorType": Field("accelerator_type"),
        "numSlices": Field("num_slices"),
        "nodeLossPolicy": Field("node_loss_policy"),
    }


@dataclass
class ComputeDomainNode(Serde):
    name: str = ""
    ip_address: str = ""
    clique_id: str = ""
    index: int = 0
    status: str = ""

    FIELDS = {
        "name": Field("name"),
        "ipAddress": Field("ip_address"),
        "cliqueID": Field("clique_id"),
        "index": Field("index"),
        "status": Field("status"),
    }


@dataclass
class ComputeDomainStatus(Serde):
    status: str = ""
    nodes: List[ComputeDomainNode] = field(default_factory=list)

    FIELDS = {
        "status": Field("status"),
        "nodes": Field("nodes", *nested_list(ComputeDomainNode)),
    }


@register(_API_VERSION, "ComputeDomain")
@dataclass
class ComputeDomain(Serde):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ComputeDomainSpec = field(default_factory=ComputeDomainSpec)
    status: ComputeDomainStatus = field(default_factory=ComputeDomainStatus)

    FIELDS = {
        "metadata": Field("metadata", *nested(ObjectMeta)),
        "spec": Field("spec", *nested(ComputeDomainSpec)),
        "status": Field("status", *nested(ComputeDomainStatus)),
    }


@dataclass
class ComputeDomainDaemonInfo(Serde):
    """One slice daemon's registration (computedomainclique.go:30-41 analog):
    host identity + stable index used for DNS naming + readiness."""

    node_name: str = ""
    ip_address: str = ""
    clique_id: str = ""
    index: int = 0
    status: str = ""

    FIELDS = {
        "nodeName": Field("node_name"),
        "ipAddress": Field("ip_address"),
        "cliqueID": Field("clique_id"),
        "index": Field("index"),
        "status": Field("status"),
    }


@register(_API_VERSION, "ComputeDomainClique")
@dataclass
class ComputeDomainClique(Serde):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    daemons: List[ComputeDomainDaemonInfo] = field(default_factory=list)

    FIELDS = {
        "metadata": Field("metadata", *nested(ObjectMeta)),
        "daemons": Field("daemons", *nested_list(ComputeDomainDaemonInfo)),
    }
