"""API group ``resource.tpu.google.com/v1beta1``.

Reference analog: api/nvidia.com/resource/v1beta1 (api.go:26-98). Two kinds of
types share the group:

1. CRDs stored in the API server: :class:`ComputeDomain`,
   :class:`ComputeDomainClique`.
2. Opaque device-config types, never stored, embedded as opaque JSON in
   ResourceClaims and decoded by the kubelet plugins: :class:`TpuConfig`,
   :class:`TpuSubsliceConfig`, :class:`VfioDeviceConfig`,
   :class:`ComputeDomainChannelConfig`, :class:`ComputeDomainDaemonConfig`.

Two decoders (api.go:46-98):

- :func:`strict_decode` fails on unknown fields — used on user-supplied claim
  configs in NodePrepareResources.
- :func:`nonstrict_decode` drops unknown fields — used for checkpoint JSON
  that may come from older/newer driver versions (down/upgrade safety).
"""

from tpu_dra.api.serde import (  # noqa: F401
    ApiError,
    DecodeError,
    Interface,
    decode,
    encode,
    nonstrict_decode,
    register,
    strict_decode,
)
from tpu_dra.api.quantity import Quantity  # noqa: F401
from tpu_dra.api.sharing import (  # noqa: F401
    DEFAULT_TIME_SLICE,
    LONG_TIME_SLICE,
    MEDIUM_TIME_SLICE,
    MULTIPLEXING_STRATEGY,
    SHORT_TIME_SLICE,
    TIME_SLICING_STRATEGY,
    MultiplexingConfig,
    PerProcessHbmLimit,
    TimeSlicingConfig,
    TpuSharing,
    TpuSubsliceSharing,
)
from tpu_dra.api.configs import (  # noqa: F401
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    TpuConfig,
    TpuSubsliceConfig,
    VfioDeviceConfig,
    default_tpu_config,
    default_tpu_subslice_config,
    default_vfio_device_config,
)
from tpu_dra.api.computedomain import (  # noqa: F401
    CD_STATUS_FAILED,
    CD_STATUS_NOT_READY,
    CD_STATUS_NONE,
    CD_STATUS_READY,
    NODE_LOSS_FAIL_FAST,
    NODE_LOSS_POLICIES,
    NODE_LOSS_SHRINK,
    CHANNEL_ALLOCATION_MODE_ALL,
    CHANNEL_ALLOCATION_MODE_SINGLE,
    ComputeDomain,
    ComputeDomainClique,
    ComputeDomainDaemonInfo,
    ComputeDomainNode,
    ComputeDomainSpec,
    ComputeDomainStatus,
)

GROUP_NAME = "resource.tpu.google.com"
VERSION = "v1beta1"
API_VERSION = f"{GROUP_NAME}/{VERSION}"

TPU_CONFIG_KIND = "TpuConfig"
TPU_SUBSLICE_CONFIG_KIND = "TpuSubsliceConfig"
VFIO_DEVICE_CONFIG_KIND = "VfioDeviceConfig"
CD_CHANNEL_CONFIG_KIND = "ComputeDomainChannelConfig"
CD_DAEMON_CONFIG_KIND = "ComputeDomainDaemonConfig"
CD_KIND = "ComputeDomain"
CD_CLIQUE_KIND = "ComputeDomainClique"
