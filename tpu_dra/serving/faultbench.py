"""Crash-tolerant serving fabric bench + CPU smoke — ``make faultbench``
(wired into ``ci``), and the measurement core behind
``bench.py --leg-fault``.

The fabric's failure semantics (ISSUE 16) proven under load, on the
same end-to-end stack fabricbench composes (real scheduler, claims,
live engine replicas). Three drills:

1. **crash drill (greedy)**: a seeded chaos schedule
   (``replica_crash`` + ``replica_stall``) kills one replica hard and
   wedges a second MID-GENERATION under an open-loop trace. Gates:
   zero lost and zero duplicated sequences (journal recovery is
   exactly-once), completions TOKEN-IDENTICAL to an uninterrupted
   single-engine reference, both death reasons detected (reaper +
   stuck-iteration watchdog), and post-kill TTFT p99 recovery within
   the gated window (``fault_recovery_p99_ms`` vs
   FAULT_RECOVERY_BOUND_MS);
2. **crash drill (sampled)**: the same kills under temperature
   sampling — survivors resume with the JOURNALED ``(seed, serial)``
   schedule, and completions must be token-identical to a reference
   engine replaying that schedule (PR-8's position-keyed folding makes
   the schedule portable across replicas);
3. **crash-loop drill**: one claim's replica is re-crashed on every
   hot re-bind until its circuit opens — the breaker must quarantine
   the claim (routing stops, claim DELETED) and the autoscaler must
   REPLACE it through the normal claim path (packer-placed), with the
   trace still completing losslessly. The old fail-loudly path is
   structurally gone: no replica death raises out of ``Fabric.drive``.

Knobs (env): FAULT_NODES, FAULT_REQUESTS, FAULT_SEED,
FAULT_RECOVERY_BOUND_MS.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import sys
import time
from typing import List

import numpy as np

from tpu_dra.infra import chaos
from tpu_dra.serving.autoscaler import AutoscalerConfig
from tpu_dra.serving.fabricbench import (
    NS,
    Fabric,
    _engine_config,
    _model,
    warm_jit,
)
from tpu_dra.serving.router import INTERACTIVE, RouterConfig, TenantSpec
from tpu_dra.workloads.engine import Engine, Request


def _note(msg: str) -> None:
    print(f"faultbench: {msg}", file=sys.stderr)


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[int(q * (len(sorted_vals) - 1))]


def _kill_schedule(seed: int) -> chaos.FaultSchedule:
    """The seeded schedule: one hard crash, then one stall, both early
    enough that the open-loop trace still has work in flight AND
    arrivals keep landing afterwards (the recovery-TTFT window).
    Round-tripped through from_dict so the new serving kinds run the
    same validation gate every schedule file does."""
    rng = random.Random(seed)
    t_crash = round(0.15 + rng.uniform(0.0, 0.1), 3)
    t_stall = round(t_crash + 0.3 + rng.uniform(0.0, 0.15), 3)
    return chaos.FaultSchedule.from_dict({
        "version": 1,
        "seed": seed,
        "description": "faultbench: hard-kill one replica, wedge another",
        "events": [
            {"at": t_crash, "kind": chaos.REPLICA_CRASH,
             "replica_index": rng.randrange(8)},
            {"at": t_stall, "kind": chaos.REPLICA_STALL,
             "replica_index": rng.randrange(8)},
        ],
    })


def _make_trace(seed: int, requests: int, vocab: int, span_s: float):
    """Open-loop single-tenant trace: arrivals spread over ``span_s``
    so the kill schedule lands mid-trace with sequences in flight and
    post-kill arrivals measuring recovery."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(requests):
        out.append((
            round(span_s * i / max(1, requests - 1), 4),
            "gold",
            Request(
                rid=f"g-{i:04d}",
                prompt=rng.integers(1, vocab, 8).astype(np.int32),
                max_new_tokens=int(rng.choice([16, 24, 32])),
            ),
            f"s{i % 6}",
        ))
    return out


def run_crash_drill(
    config, params, nodes, requests, seed, timeout,
    temperature: float = 0.0, recovery_bound_ms: float = 20000.0,
) -> dict:
    """Kill one replica hard and wedge another mid-generation; gate
    exactly-once recovery, token identity (greedy OR sampled via the
    journaled schedule), and bounded post-kill TTFT."""
    label = "sampled" if temperature > 0 else "greedy"
    gold = TenantSpec("gold", INTERACTIVE, weight=1.0)
    slots = 4
    ec = _engine_config(slots, max_prompt=10, max_out=34)
    if temperature > 0:
        ec = dataclasses.replace(
            ec, temperature=temperature, top_k=20, sample_seed=13
        )
    warm_jit(config, params, ec)
    fab = Fabric(
        nodes, [gold], config, params, ec,
        RouterConfig(
            backlog_cap_tokens=1e9, max_inflight_per_replica=slots,
            # Detection small enough that the wedged replica's work
            # re-dispatches inside the drill; large enough that a slow
            # CI step (a fresh jit compile on a shape warm_jit missed)
            # never false-positives a healthy engine — a too-tight
            # deadline also races the armed crash flag: the watchdog
            # declares "stall" before the engine thread finishes its
            # step and trips the crash.
            stall_deadline_seconds=2.5,
            breaker_deaths=3, breaker_window_seconds=10.0,
            redispatch_backoff_base_seconds=0.01,
            redispatch_backoff_cap_seconds=0.1,
        ),
        AutoscalerConfig(
            min_replicas=3, max_replicas=3,
            # Load-driven scaling parked (the drill measures the
            # failure path): replacement/rebind still run.
            target_tokens_per_replica=1e9,
            cooldown_seconds=0.1,
            claim_check_seconds=0.2,
            dead_join_timeout_seconds=2.0,
        ),
    )
    sched = _kill_schedule(seed)
    trace = _make_trace(
        seed, requests, config.vocab_size,
        span_s=max(1.2, sched.events[-1].at + 0.6),
    )
    eng = chaos.ChaosEngine(sched)
    kill_walls: List[float] = []  # wall time each kill actually fired

    def _inject(kind):
        fault = "crash" if kind == chaos.REPLICA_CRASH else "stall"

        def inject(ev):
            # Never double-arm: a replica already carrying a pending
            # fault (or already erroring out) would have its one-shot
            # flag OVERWRITTEN, silently losing the first kill.
            live = [
                r for r in fab.router.live_replicas()
                if r._fault is None and r.error is None
            ]
            # Mid-generation is the point: prefer a replica holding
            # in-flight sequences (the replica_index picks among them).
            cands = [r for r in live if r.inflight] or live
            if not cands:
                return
            rep = cands[ev.params["replica_index"] % len(cands)]
            rep.inject_fault(fault)
            kill_walls.append(time.monotonic())

        return inject

    eng.register(chaos.REPLICA_CRASH, _inject(chaos.REPLICA_CRASH))
    eng.register(chaos.REPLICA_STALL, _inject(chaos.REPLICA_STALL))

    t0 = None  # chaos clock starts when the DRIVE starts, not at setup

    def chaos_tick():
        # Fire due events on the drive's control thread (the injector
        # touches replicas — the router's threading contract). The
        # first tick anchors t0 so event offsets are relative to the
        # open-loop trace, not to however long engine bring-up took.
        nonlocal t0
        if t0 is None:
            t0 = time.monotonic()
        while eng.remaining:
            nxt = eng.schedule.events[len(eng.schedule.events)
                                      - eng.remaining]
            if nxt.at > time.monotonic() - t0:
                break
            eng.step()

    try:
        fab.scale_to(3)
        res = fab.drive(
            trace, autoscale=True, timeout=timeout,
            extra_tick=chaos_tick,
        )
        # Late stall: if the trace drained before the stall landed, the
        # gate below fails loudly — the schedule/trace sizing contract
        # (kills land mid-generation) is part of what this smoke pins.
        deaths = fab.router.deaths
        reasons = {r for _, r, _ in fab.router.death_log}
        assert deaths >= 2, (
            f"[{label}] wanted >= 2 replica deaths, got {deaths} "
            f"({fab.router.death_log})"
        )
        assert "crash" in reasons and "stall" in reasons, (
            f"[{label}] wanted both detection paths (crash + stall), "
            f"got {reasons}"
        )
        assert fab.router.redispatched >= 1, (
            f"[{label}] no sequence was journal-recovered — the kills "
            f"did not land mid-generation"
        )
        # Exactly-once: every admitted rid completed, none twice (the
        # completion store is keyed by rid; count equality + set
        # equality close both directions).
        done = fab.router.completions
        want = {r.rid for _, _, r, _ in trace}
        assert res["rejected"] == 0, (
            f"[{label}] {res['rejected']} rejects under an uncapped "
            f"backlog"
        )
        assert set(done) == want, (
            f"[{label}] lost/invented sequences across replica "
            f"deaths: {set(done) ^ want}"
        )
        # Token identity vs an uninterrupted single-engine reference.
        # Sampled: the reference pins each request's JOURNALED
        # (seed, serial) schedule — the survivors did the same, so the
        # trajectories must agree token for token.
        refs = []
        for _, _, r, _ in trace:
            if temperature > 0:
                ss = fab.router.journal.sample_schedule(r.rid)
                assert ss is not None and ss[1] is not None, (
                    f"[{label}] no journaled sampling schedule for "
                    f"{r.rid}"
                )
                refs.append(dataclasses.replace(
                    r, sample_seed=ss[0], sample_serial=ss[1],
                ))
            else:
                refs.append(dataclasses.replace(r))
        ref = Engine(config, params, ec).run(refs)
        mismatch = [
            rid for rid in want
            if not np.array_equal(done[rid].tokens, ref[rid].tokens)
        ]
        assert not mismatch, (
            f"[{label}] completions diverged from the uninterrupted "
            f"reference on {sorted(mismatch)[:5]}"
        )
        # Post-kill recovery: TTFT p99 of requests submitted AFTER the
        # last kill fired must sit inside the gated window — capacity
        # loss plus journal replay cannot park late arrivals forever.
        last_kill = max(kill_walls) if kill_walls else t0
        post = sorted(
            c.ttft_s * 1000.0 for c in done.values()
            if c.t_submit >= last_kill
        )
        assert post, (
            f"[{label}] no arrivals after the last kill — the trace "
            f"span does not cover the recovery window"
        )
        recovery_p99 = round(_pct(post, 0.99), 2)
        assert recovery_p99 <= recovery_bound_ms, (
            f"[{label}] post-kill TTFT p99 {recovery_p99} ms exceeds "
            f"the {recovery_bound_ms} ms recovery bound "
            f"(FAULT_RECOVERY_BOUND_MS to widen on a hostile machine)"
        )
        _note(
            f"crash[{label}]: deaths={deaths} ({', '.join(sorted(reasons))}), "
            f"redispatched={fab.router.redispatched}, "
            f"duplicates_dropped={fab.router.duplicates_dropped}, "
            f"post-kill ttft p99 {recovery_p99} ms over {len(post)} "
            f"arrivals, wall {res['wall_s']}s"
        )
        return {
            "deaths": deaths,
            "reasons": sorted(reasons),
            "redispatched": fab.router.redispatched,
            "duplicates_dropped": fab.router.duplicates_dropped,
            "lost": 0,
            "recovery_p99_ms": recovery_p99,
            "recovery_n": len(post),
            "identical": True,
        }
    finally:
        fab.stop()


def run_crash_loop_drill(
    config, params, nodes, seed, timeout
) -> dict:
    """Crash one claim's replica on every hot re-bind until the
    breaker opens: the claim must be quarantined + DELETED, a
    replacement claim placed by the packer, and the trace must still
    complete losslessly and token-identically."""
    gold = TenantSpec("gold", INTERACTIVE, weight=1.0)
    slots = 4
    ec = _engine_config(slots, max_prompt=10, max_out=28)
    warm_jit(config, params, ec)
    fab = Fabric(
        nodes, [gold], config, params, ec,
        RouterConfig(
            backlog_cap_tokens=1e9, max_inflight_per_replica=slots,
            stall_deadline_seconds=5.0,
            breaker_deaths=3, breaker_window_seconds=30.0,
            redispatch_backoff_base_seconds=0.01,
            redispatch_backoff_cap_seconds=0.1,
        ),
        AutoscalerConfig(
            min_replicas=2, max_replicas=2,
            target_tokens_per_replica=1e9,
            cooldown_seconds=0.1,
            claim_check_seconds=0.5,
            dead_join_timeout_seconds=2.0,
        ),
    )
    rng = np.random.default_rng(seed)
    reqs = [
        Request(
            rid=f"loop-{i:03d}",
            prompt=rng.integers(1, config.vocab_size, 8).astype(np.int32),
            max_new_tokens=24,
        )
        for i in range(20)
    ]
    trace = [(0.0, "gold", r, f"s{i}") for i, r in enumerate(reqs)]
    try:
        fab.scale_to(2)
        target = fab.router.replicas[0].claim_name
        armed: set = set()

        def crash_loop_tick():
            # Re-arm the crash on whatever replica currently serves
            # the target claim (each re-bind makes a fresh Replica) —
            # the seam the replica_crash_loop chaos kind drives.
            if len(armed) >= 3:
                return
            for rep in fab.router.live_replicas():
                if (
                    rep.claim_name == target
                    and id(rep) not in armed
                    and rep.inflight
                    and rep.error is None
                ):
                    armed.add(id(rep))
                    rep.inject_fault("crash")
                    return

        res = fab.drive(
            trace, autoscale=True, timeout=timeout,
            extra_tick=crash_loop_tick,
        )
        deaths_on_target = sum(
            1 for name, _, _ in fab.router.death_log if name
        )
        assert fab.router.breaker.opened_total >= 1, (
            f"circuit never opened after {deaths_on_target} deaths "
            f"({fab.router.death_log})"
        )
        quarantines = [
            e for e in fab.autoscaler.events if e[0] == "quarantine"
        ]
        assert quarantines and quarantines[0][1] == target, (
            f"no quarantine event for {target}: "
            f"{fab.autoscaler.events}"
        )
        assert fab.claims.try_get(target, NS) is None, (
            f"quarantined claim {target} was not deleted"
        )
        replaces = [
            e for e in fab.autoscaler.events
            if e[0] == "replace-requested"
        ]
        assert replaces, "autoscaler never requested a replacement"
        replacement = replaces[0][1]
        cur = fab.claims.try_get(replacement, NS)
        alloc = ((cur or {}).get("status") or {}).get("allocation")
        assert alloc, (
            f"replacement claim {replacement} never placed by the "
            f"packer"
        )
        assert any(
            e[0] == "up-ready" and e[1] == replacement
            for e in fab.autoscaler.events
        ), f"replacement {replacement} never bound a replica"
        done = fab.router.completions
        want = {r.rid for r in reqs}
        assert set(done) == want and res["rejected"] == 0, (
            f"lost/invented sequences across the crash loop: "
            f"{set(done) ^ want}"
        )
        ref = Engine(config, params, ec).run(
            [dataclasses.replace(r) for r in reqs]
        )
        mismatch = [
            rid for rid in want
            if not np.array_equal(done[rid].tokens, ref[rid].tokens)
        ]
        assert not mismatch, (
            f"crash-loop completions diverged from the reference on "
            f"{mismatch}"
        )
        _note(
            f"crash-loop: {len(armed)} injected crashes on {target}, "
            f"rebinds={fab.autoscaler.rebinds}, circuit opened, claim "
            f"replaced by {replacement}, wall {res['wall_s']}s"
        )
        return {
            "deaths": fab.router.deaths,
            "rebinds": fab.autoscaler.rebinds,
            "circuit_opens": fab.router.breaker.opened_total,
            "quarantined": fab.autoscaler.quarantined,
            "claims_replaced": fab.autoscaler.replaced,
            "redispatched": fab.router.redispatched,
            "duplicates_dropped": fab.router.duplicates_dropped,
        }
    finally:
        fab.stop()


# --- entry points ------------------------------------------------------------


def run(
    nodes: int,
    requests: int,
    seed: int,
    smoke: bool = False,
    timeout: float = 600.0,
    recovery_bound_ms: float = 20000.0,
) -> dict:
    config, params = _model()
    _note(
        f"crash drills: {nodes} nodes, 3 replicas, {requests} requests, "
        f"seed {seed}"
    )
    greedy = run_crash_drill(
        config, params, nodes, requests, seed, timeout,
        temperature=0.0, recovery_bound_ms=recovery_bound_ms,
    )
    sampled = run_crash_drill(
        config, params, nodes, requests, seed + 1, timeout,
        temperature=0.8, recovery_bound_ms=recovery_bound_ms,
    )
    loop = run_crash_loop_drill(config, params, nodes, seed, timeout)
    report = {
        "fault_deaths": (
            greedy["deaths"] + sampled["deaths"] + loop["deaths"]
        ),
        "fault_redispatched": (
            greedy["redispatched"] + sampled["redispatched"]
            + loop["redispatched"]
        ),
        "fault_lost_sequences": greedy["lost"] + sampled["lost"],
        "fault_duplicates_dropped": (
            greedy["duplicates_dropped"] + sampled["duplicates_dropped"]
            + loop["duplicates_dropped"]
        ),
        "fault_recovery_p99_ms": greedy["recovery_p99_ms"],
        "fault_recovery_sampled_p99_ms": sampled["recovery_p99_ms"],
        "fault_circuit_opens": loop["circuit_opens"],
        "fault_claims_replaced": loop["claims_replaced"],
        "fault_rebinds": loop["rebinds"],
        "fault_greedy_identical": greedy["identical"],
        "fault_sampled_identical": sampled["identical"],
        "seed": seed,
    }
    if smoke:
        _note(
            "smoke contract: both detection paths, exactly-once journal "
            "recovery, greedy + journaled-sampled token identity, "
            "bounded post-kill TTFT, circuit-open -> quarantine -> "
            "claim replacement — all hold"
        )
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser("faultbench", description=__doc__)
    p.add_argument(
        "--smoke", action="store_true",
        help="CI size: small fleet/trace + the hard contract asserts",
    )
    args = p.parse_args(argv)
    env = os.environ.get
    nodes = int(env("FAULT_NODES", "8"))
    requests = int(env("FAULT_REQUESTS", "36" if args.smoke else "160"))
    seed = int(env("FAULT_SEED", "20260807"))
    bound = float(env("FAULT_RECOVERY_BOUND_MS", "20000"))
    report = run(
        nodes, requests, seed, smoke=args.smoke,
        recovery_bound_ms=bound,
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
