"""Claim-driven autoscaling: the replica set IS a set of ResourceClaims.

The autoscaler never picks a node and never places anything — it scales
the serving fabric by creating and deleting ResourceClaims and lets the
scheduler's fragmentation-aware packer (PR 6) place them, exactly like
any other tenant of the control plane:

- **scale-up**: create one claim (the caller's ``make_claim`` template
  names the sub-slice shape), wait for ``status.allocation`` to appear
  (the batch solve places it), then ``make_replica(claim)`` binds a new
  engine to the allocated device and the router starts dispatching to
  it. Decision → first-dispatchable is recorded as the **reaction
  time** (``fabric_autoscaler_reaction_seconds``).
- **scale-down**: quiesce the least-loaded replica, drive the PR-7
  backpressure drain through :meth:`Engine.evacuate` (host checkpoint,
  pages freed), splice the evacuated sequences back into the router's
  WFQ for lossless resume on the surviving replicas, and ONLY THEN
  delete the ResourceClaim — the tenant-transparent eviction ordering
  the fabric smoke gates (zero lost or duplicated sequences,
  token-identical completions under greedy decoding).

Decisions are load-derived (MISO, PAPERS.md 2207.11428): the signal is
the router's queued token backlog per live replica vs a target, with a
hysteresis band (``up_factor`` / ``down_factor``) and a cooldown
between actions. A desired REVERSAL inside the cooldown window is the
flapping signal — counted as ``fabric_autoscaler_flaps_total`` (and
suppressed); the doctor WARNs on it with the widen-the-band
remediation.

``tick()`` is a non-blocking state machine (steady → waiting-alloc →
steady, steady → draining → steady) advanced from the fabric's control
thread, so tests drive every transition deterministically.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

from tpu_dra.infra import lockdep
from tpu_dra.serving.router import Replica, Router


@dataclasses.dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # Queued-backlog target per live replica, in tokens. Above
    # target * up_factor per replica -> scale up; below
    # target * down_factor -> scale down. The gap between the two
    # factors is the hysteresis band that keeps a steady load from
    # oscillating the replica count.
    target_tokens_per_replica: float = 4096.0
    up_factor: float = 1.25
    down_factor: float = 0.25
    cooldown_seconds: float = 3.0
    # A claim the packer cannot place within this window is deleted and
    # the scale-up abandoned until the next pressure signal (capacity
    # may have been freed meanwhile — item 1's repacker will help).
    alloc_timeout_seconds: float = 30.0
    namespace: str = "fabric"
    # --- crash tolerance (ISSUE 16) ---
    # How often the claim-vanished detector polls the claim store (a
    # deleted/lost claim means the replica's device lease is gone: the
    # router must reclaim its sequences even though the thread lives).
    claim_check_seconds: float = 1.0
    # Join timeout when collecting a dead replica's thread: bounded so
    # a wedged thread cannot stall the control loop (the stop-timeout
    # path logs + counts it and leaves the corpse dead).
    dead_join_timeout_seconds: float = 1.0
    # --- disaggregated prefill/decode (ISSUE 17) ---
    # When True the fleet runs phase-role pools: the load signal splits
    # into queued PREFILL tokens per prefill replica vs queued DECODE
    # tokens per decode replica, scale-up creates a claim for the
    # needier phase (``make_replica`` is then called as
    # ``make_replica(claim, role)``), scale-down retires from the
    # emptier pool without ever dropping a phase to zero replicas, and
    # a dead replica's replacement inherits its role.
    disaggregated: bool = False


class ClaimAutoscaler:
    """``make_claim(name) -> dict`` builds the ResourceClaim body
    (shape/class selectors are the caller's policy);
    ``make_replica(claim) -> Replica`` binds a started replica to an
    ALLOCATED claim (the engine is cheap: same (config, int8) key =
    shared compiled executables via the engine's _JIT_CACHE)."""

    def __init__(
        self,
        router: Router,
        claims,  # ResourceClient bound to RESOURCE_CLAIMS
        make_claim: Callable[[str], dict],
        make_replica: Callable[[dict], Replica],
        config: Optional[AutoscalerConfig] = None,
        metrics=None,
        clock=time.monotonic,
    ):
        self.router = router
        self.claims = claims
        self.make_claim = make_claim
        self.make_replica = make_replica
        self.config = config or AutoscalerConfig()
        self.metrics = metrics
        self.clock = clock
        self.flaps = 0
        self.scaleups = 0
        self.scaledowns = 0
        self.rebinds = 0
        self.quarantined = 0
        self.replaced = 0
        self.reaction_s: List[float] = []
        self.drain_s: List[float] = []
        # Event log for tests and the bench: (kind, claim_name, t, info).
        self.events: List[tuple] = []
        self._serial = 0
        self._last_action: Optional[str] = None  # "up" | "down"
        self._last_action_t = -1e18
        # One flap per reversal EPISODE: tick() runs at control-loop
        # frequency (sub-ms), so counting every suppressed tick would
        # make the flap metric loop-frequency-dependent. The latch
        # clears when the reversal desire goes away or an action runs.
        self._flap_latched = False
        # In-flight transitions (at most one of each at a time).
        self._pending_claim: Optional[dict] = None
        self._pending_t0 = 0.0
        self._pending_is_replace = False
        self._draining: Optional[Replica] = None
        self._drain_t0 = 0.0
        # Crash tolerance (ISSUE 16): replacements owed to quarantined
        # or claim-less dead replicas (drained one at a time through
        # the single pending-claim slot), and the claim-vanished
        # detector's last poll time.
        self._replace_owed = 0
        self._last_claim_check = -1e18
        # Disaggregation (ISSUE 17): the role the in-flight scale-up /
        # replacement claim will bind as, and the roles owed by
        # quarantined or claim-less dead replicas (FIFO next to
        # _replace_owed; empty when not disaggregated).
        self._pending_role: Optional[str] = None
        self._replace_roles: List[str] = []

    # --- the control-thread entry point ---

    def tick(self) -> None:  # thread: control
        # Keyed on the ROUTER: the contract is "ticks on the same
        # thread that drives Router.poll", not merely self-consistency.
        lockdep.single_owner(self.router, "control")
        self._check_claims()
        self._tick_dead()
        if self._pending_claim is not None:
            self._tick_pending_alloc()
            return
        if self._draining is not None:
            self._tick_draining()
            return
        if self._replace_owed > 0:
            # Replacement is a repair, not a load decision: it bypasses
            # the cooldown/hysteresis band (the fleet is OWED this
            # capacity) but still flows through the one-at-a-time
            # pending-claim slot the packer places.
            self._begin_replace(self.clock())
            return
        self._maybe_scale()

    # --- crash tolerance (ISSUE 16) ---

    def _check_claims(self) -> None:
        """Claim-vanished detection: a live replica whose ResourceClaim
        no longer exists has lost its device lease — the router must
        reclaim its journaled sequences even though the thread is
        healthy."""
        now = self.clock()
        if now - self._last_claim_check < self.config.claim_check_seconds:
            return
        self._last_claim_check = now
        for rep in list(self.router.replicas):
            if not rep.claim_name or rep.dead or rep is self._draining:
                continue
            cur = self.claims.try_get(
                rep.claim_name, self.config.namespace
            )
            if cur is None:
                self.router.mark_dead(rep, "claim-vanished")

    def _tick_dead(self) -> None:
        """Collect replicas the router declared dead: join the thread
        (bounded), then either hot RE-BIND a fresh replica onto the
        still-allocated claim, or — when the claim's circuit is open
        (crash-looping) or the claim is gone — QUARANTINE: delete the
        claim and owe a replacement through the normal claim path."""
        for rep in self.router.take_dead():
            now = self.clock()
            rep.stop(timeout=self.config.dead_join_timeout_seconds)
            key = rep.claim_name or rep.name
            claim = (
                self.claims.try_get(
                    rep.claim_name, self.config.namespace
                )
                if rep.claim_name else None
            )
            alloc = ((claim or {}).get("status") or {}).get("allocation")
            if self.router.breaker.is_open(key):
                # Crash loop: re-binding would feed the loop. Replace
                # the claim — fresh name, fresh placement, closed
                # circuit.
                if rep.claim_name and claim is not None:
                    try:
                        self.claims.delete(
                            rep.claim_name, self.config.namespace
                        )
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                self.quarantined += 1
                if self.metrics is not None:
                    self.metrics.inc("fabric_quarantined_total")
                self.events.append(("quarantine", rep.claim_name, now, {
                    "reason": rep.death_reason,
                }))
                self._replace_owed += 1
                if self.config.disaggregated:
                    self._replace_roles.append(rep.role)
            elif alloc:
                # First (or rare) death with the claim still allocated:
                # hot re-bind a fresh engine onto the same devices —
                # with the dead replica's phase role: the pools' sizes
                # are the autoscaler's decision, not the crash's.
                rep2 = (
                    self.make_replica(claim, rep.role)
                    if self.config.disaggregated
                    else self.make_replica(claim)
                )
                rep2.claim_name = rep.claim_name
                rep2.claim = claim
                self.router.add_replica(rep2)
                self.rebinds += 1
                if self.metrics is not None:
                    self.metrics.inc("fabric_rebinds_total")
                self.events.append(("rebind", rep.claim_name, now, {
                    "reason": rep.death_reason,
                }))
            else:
                # Claim vanished (or claim-less bootstrap replica):
                # nothing to re-bind onto — owe a replacement.
                self.events.append(
                    ("dead-claim-gone", rep.claim_name, now, {
                        "reason": rep.death_reason,
                    })
                )
                self._replace_owed += 1
                if self.config.disaggregated:
                    self._replace_roles.append(rep.role)

    def _begin_replace(self, now: float) -> None:
        self._replace_owed -= 1
        self._pending_role = (
            self._replace_roles.pop(0) if self._replace_roles else None
        )
        self._serial += 1
        name = f"fabric-replica-{self._serial:04d}"
        claim = self.make_claim(name)
        claim["metadata"]["name"] = name
        claim["metadata"]["namespace"] = self.config.namespace
        self.claims.create(claim)
        self._pending_claim = claim
        self._pending_t0 = now
        self._pending_is_replace = True
        self.replaced += 1
        if self.metrics is not None:
            self.metrics.inc("fabric_claims_replaced_total")
        self.events.append(("replace-requested", name, now, {}))

    # --- decision ---

    def _load_per_replica(self) -> float:
        n = max(1, len(self.router.live_replicas()))
        return self.router.queued_tokens() / n

    def _gate_cooldown(self, want: str, now: float) -> bool:
        """Shared cooldown/hysteresis gate: False suppresses the
        action. A desired REVERSAL inside the cooldown window is the
        flapping signal, counted once per episode."""
        c = self.config
        if now - self._last_action_t < c.cooldown_seconds:
            if self._last_action is not None and want != self._last_action:
                # Up+down inside one cooldown window: the hysteresis
                # band is too tight for this load's variance. Count it
                # ONCE per episode (the doctor's flapping WARN) and
                # suppress the action.
                if not self._flap_latched:
                    self._flap_latched = True
                    self.flaps += 1
                    if self.metrics is not None:
                        self.metrics.inc("fabric_autoscaler_flaps_total")
            else:
                self._flap_latched = False
            return False
        self._flap_latched = False
        return True

    def _maybe_scale(self) -> None:
        if self.config.disaggregated:
            return self._maybe_scale_disagg()
        c = self.config
        n = len(self.router.live_replicas())
        load = self._load_per_replica()
        want: Optional[str] = None
        if load > c.target_tokens_per_replica * c.up_factor:
            if n < c.max_replicas:
                want = "up"
        elif load < c.target_tokens_per_replica * c.down_factor:
            if n > c.min_replicas:
                want = "down"
        if want is None:
            self._flap_latched = False
            return
        now = self.clock()
        if not self._gate_cooldown(want, now):
            return
        if want == "up":
            self._begin_scale_up(now)
        else:
            self._begin_scale_down(now)

    def _maybe_scale_disagg(self) -> None:
        """Per-phase pool sizing (ISSUE 17): the load signal is queued
        PREFILL tokens per prefill replica vs queued DECODE tokens per
        decode replica — the split of ``queued_tokens()`` the router
        maintains. The needier phase scales up; scale-down retires from
        the emptier pool, never dropping a phase below one replica (a
        phaseless fleet would deadlock its half of the pipeline into
        the re-prefill fallback)."""
        c = self.config
        live = self.router.live_replicas()
        n = len(live)
        n_p = sum(1 for r in live if r.role == "prefill")
        n_d = sum(1 for r in live if r.role == "decode")
        load_p = self.router.queued_prefill_tokens() / max(1, n_p)
        load_d = self.router.queued_decode_tokens() / max(1, n_d)
        want: Optional[str] = None
        role: Optional[str] = None
        if max(load_p, load_d) > c.target_tokens_per_replica * c.up_factor:
            if n < c.max_replicas:
                want = "up"
                role = "prefill" if load_p >= load_d else "decode"
        elif (
            load_p < c.target_tokens_per_replica * c.down_factor
            and load_d < c.target_tokens_per_replica * c.down_factor
            and n > c.min_replicas
            and (n_p > 1 or n_d > 1)
        ):
            want = "down"
            if n_d <= 1 or (load_p <= load_d and n_p > 1):
                role = "prefill"
            else:
                role = "decode"
        if want is None:
            self._flap_latched = False
            return
        now = self.clock()
        if not self._gate_cooldown(want, now):
            return
        if want == "up":
            self._begin_scale_up(now, role=role)
        else:
            self._begin_scale_down(now, role=role)

    # --- scale-up: create claim -> packer places -> bind replica ---

    def _begin_scale_up(
        self, now: float, role: Optional[str] = None
    ) -> None:
        self._serial += 1
        name = f"fabric-replica-{self._serial:04d}"
        claim = self.make_claim(name)
        claim["metadata"]["name"] = name
        claim["metadata"]["namespace"] = self.config.namespace
        self.claims.create(claim)
        self._pending_claim = claim
        self._pending_t0 = now
        self._pending_is_replace = False
        self._pending_role = role
        self._last_action, self._last_action_t = "up", now
        self.events.append(
            ("up-requested", name, now, {"role": role} if role else {})
        )

    def _tick_pending_alloc(self) -> None:
        name = self._pending_claim["metadata"]["name"]
        now = self.clock()
        cur = self.claims.try_get(name, self.config.namespace)
        alloc = ((cur or {}).get("status") or {}).get("allocation")
        if not alloc:
            if now - self._pending_t0 > self.config.alloc_timeout_seconds:
                # Unschedulable: abandon (delete so the claim does not
                # squat the queue) and re-decide on the next pressure.
                try:
                    self.claims.delete(name, self.config.namespace)
                except Exception:  # noqa: BLE001 — already gone
                    pass
                self.events.append(("up-unplaceable", name, now, {}))
                if self._pending_is_replace:
                    # A replacement is a debt, not an opportunity: an
                    # unplaceable one stays owed and retries on a later
                    # tick (capacity may free meanwhile).
                    self._replace_owed += 1
                    if self._pending_role is not None:
                        self._replace_roles.append(self._pending_role)
                self._pending_claim = None
                self._pending_role = None
            return
        rep = (
            self.make_replica(cur, self._pending_role)
            if self._pending_role is not None
            else self.make_replica(cur)
        )
        self._pending_role = None
        rep.claim_name = name
        rep.claim = cur
        self.router.add_replica(rep)
        self._pending_claim = None
        self.scaleups += 1
        reaction = now - self._pending_t0
        self.reaction_s.append(reaction)
        if self.metrics is not None:
            self.metrics.observe(
                "fabric_autoscaler_reaction_seconds", reaction
            )
            self.metrics.inc("fabric_autoscaler_scaleups_total")
        self.events.append(("up-ready", name, now, {
            "reaction_s": reaction,
            "devices": [
                r["device"] for r in alloc["devices"]["results"]
            ],
        }))

    # --- scale-down: quiesce -> evacuate -> requeue -> DELETE claim ---

    def _victim(self, role: Optional[str] = None) -> Optional[Replica]:
        # A replica mid-repack is NOT a scale-down candidate (ISSUE 12):
        # the repacker is moving its claim, not retiring it — deleting
        # the claim under the mover would strand the half-move. The
        # replica count still includes it (its claim still serves).
        live = [
            r for r in self.router.live_replicas() if not r.migrating
        ]
        if role is not None:
            live = [r for r in live if r.role == role]
        if len(self.router.live_replicas()) <= self.config.min_replicas:
            return None
        if not live:
            return None
        # Least in-flight work moves the least state; claim-less
        # replicas (bootstrap) are never preferred over claim-backed
        # ones — deleting their "claim" would be a no-op and the
        # measured drill wants the real ordering.
        return min(
            live,
            key=lambda r: (not r.claim_name, len(r.inflight)),
        )

    def _begin_scale_down(
        self, now: float, role: Optional[str] = None
    ) -> None:
        victim = self._victim(role)
        if victim is None:
            return
        victim.quiesced = True
        victim.begin_evacuate()
        self._draining = victim
        self._drain_t0 = now
        self._last_action, self._last_action_t = "down", now
        self.events.append(
            ("down-draining", victim.claim_name, now, {})
        )

    def _tick_draining(self) -> None:
        victim = self._draining
        if not victim.evac_done:
            return
        now = self.clock()
        requeued = self.router.requeue_evacuated(victim)
        engine_empty = not victim.engine.busy
        # THE ordering contract: the ResourceClaim is deleted only
        # after the drain handed every sequence back (pages freed,
        # engine empty) — eviction is tenant-transparent.
        if victim.claim_name:
            try:
                self.claims.delete(
                    victim.claim_name, self.config.namespace
                )
            except Exception:  # noqa: BLE001 — already gone
                pass
        self.router.remove_replica(victim)
        victim.stop(
            timeout=self.router.config.replica_join_timeout_seconds
        )
        self._draining = None
        self.scaledowns += 1
        drain = now - self._drain_t0
        self.drain_s.append(drain)
        if self.metrics is not None:
            self.metrics.inc("fabric_autoscaler_scaledowns_total")
            self.metrics.observe(
                "fabric_autoscaler_drain_seconds", drain
            )
        self.events.append(("down-complete", victim.claim_name, now, {
            "requeued": requeued,
            "drain_s": drain,
            "engine_empty_at_delete": engine_empty,
        }))
