"""Crash tolerance for the serving fabric (ISSUE 16).

Three small, separately-testable pieces the router composes:

- :class:`DispatchJournal` — the write-ahead record of every dispatch.
  An entry is created the first time a request is handed to a replica
  and carries everything needed to reconstruct the sequence WITHOUT the
  engine's cooperation: prompt, emitted-so-far (folded in from the
  completion outbox at evacuations and re-dispatches), tenant, timing
  stamps, and the sampling schedule ``(seed, serial)`` PR-8's
  position-keyed folding makes sufficient for token-identical resume of
  a *sampled* sequence on any survivor. Entries survive close() in a
  ``closed`` map so benches can rebuild reference sampling schedules;
  :meth:`snapshot` / :meth:`restore` round-trip the open set through
  plain JSON-able data for the crash-matrix drill (a restarted router
  adopts the journal and replays to exactly-once completions).
- :class:`CircuitBreaker` — per-claim death counting over a sliding
  window. N deaths inside the window opens the circuit: the router
  stops routing to replicas bound to that claim and the autoscaler
  REPLACES the claim instead of hot re-binding a crash-looper. The
  window is time-based, so an opened circuit half-closes on its own
  once the deaths age out.
- :func:`redispatch_backoff` — deterministic jittered exponential
  backoff for re-dispatching a dead replica's sequences, so a
  poisoned request cannot hot-loop the surviving fleet.

Everything here is control-thread-only state (the router's threading
contract); no locks are taken.
"""

from __future__ import annotations

import collections
import time
import zlib
from typing import Deque, Dict, List, Optional

import numpy as np


class ReplicaFault(RuntimeError):
    """An injected (chaos) replica fault. The replica's engine thread
    raises it out of its loop without the loud traceback re-raise real
    bugs get — injected deaths are expected and recovered."""


def redispatch_backoff(
    retries: int,
    base_seconds: float,
    cap_seconds: float,
    token: str,
) -> float:
    """Exponential backoff with deterministic jitter in [0.5x, 1.0x],
    derived from ``token`` (rid + retry count) so tests and seeded
    benches see the same schedule every run."""
    raw = min(cap_seconds, base_seconds * (2.0 ** max(0, retries - 1)))
    h = zlib.crc32(f"{token}|{retries}".encode()) & 0xFFFFFFFF
    return raw * (0.5 + 0.5 * (h / 0xFFFFFFFF))


class JournalEntry:
    """One dispatched request's reconstructable state."""

    __slots__ = (
        "rid", "tenant", "prompt", "max_new", "session", "cost",
        "emitted", "t_submit", "t_first", "t_dispatch", "replica",
        "replicas", "sample_seed", "sample_serial", "retries",
        "trace_ctx",
    )

    def __init__(self, rid: str, tenant: str, prompt, max_new: int,
                 session: Optional[str], cost: float):
        self.rid = rid
        self.tenant = tenant
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new = max_new
        self.session = session
        self.cost = cost
        self.emitted = np.zeros(0, np.int32)
        self.t_submit = 0.0
        self.t_first: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.replica = ""  # the replica currently holding it
        self.replicas: List[str] = []
        self.sample_seed: Optional[int] = None
        self.sample_serial: Optional[int] = None
        self.retries = 0
        # Live-only (NOT snapshotted — trace ctxs are process-local).
        self.trace_ctx = None

    def to_dict(self) -> dict:
        return {
            "rid": self.rid,
            "tenant": self.tenant,
            "prompt": [int(t) for t in self.prompt],
            "max_new": int(self.max_new),
            "session": self.session,
            "cost": float(self.cost),
            "emitted": [int(t) for t in self.emitted],
            "t_submit": float(self.t_submit),
            "t_first": (
                None if self.t_first is None else float(self.t_first)
            ),
            "t_dispatch": (
                None if self.t_dispatch is None
                else float(self.t_dispatch)
            ),
            "replica": self.replica,
            "replicas": list(self.replicas),
            "sample_seed": self.sample_seed,
            "sample_serial": self.sample_serial,
            "retries": int(self.retries),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "JournalEntry":
        e = cls(
            d["rid"], d["tenant"],
            np.asarray(d["prompt"], np.int32),
            int(d["max_new"]), d.get("session"), float(d["cost"]),
        )
        e.emitted = np.asarray(d.get("emitted") or [], np.int32)
        e.t_submit = float(d.get("t_submit") or 0.0)
        e.t_first = d.get("t_first")
        e.t_dispatch = d.get("t_dispatch")
        e.replica = d.get("replica") or ""
        e.replicas = list(d.get("replicas") or [])
        e.sample_seed = d.get("sample_seed")
        e.sample_serial = d.get("sample_serial")
        e.retries = int(d.get("retries") or 0)
        return e


class DispatchJournal:
    """Control-thread-owned dispatch journal: ``record`` at every
    dispatch, ``note_progress`` when an evacuation folds emitted
    tokens back, ``close`` at completion. ``open_entries`` is exactly
    the set a crashed fleet owes its tenants."""

    def __init__(self):
        self.entries: Dict[str, JournalEntry] = {}
        # Closed entries are kept (small: stamps + token ids, no KV)
        # so a restarted router refuses to replay a completed rid and
        # benches can reconstruct the sampling schedule per request.
        self.closed: Dict[str, JournalEntry] = {}

    def record(self, fr, replica_name: str) -> JournalEntry:
        """Journal one dispatch of router request ``fr`` (duck-typed:
        any object with the _FabricReq fields) onto ``replica_name``."""
        e = self.entries.get(fr.rid)
        if e is None:
            e = JournalEntry(
                fr.rid, fr.tenant, fr.prompt, fr.max_new, fr.session,
                fr.cost,
            )
            self.entries[fr.rid] = e
        e.emitted = fr.emitted
        e.t_submit = fr.t_submit
        e.t_first = fr.t_first
        e.t_dispatch = fr.t_dispatch
        e.replica = replica_name
        e.replicas = list(fr.replicas)
        e.sample_seed = fr.sample_seed
        e.sample_serial = fr.sample_serial
        e.retries = getattr(fr, "retries", 0)
        e.trace_ctx = fr.trace_ctx
        return e

    def note_progress(self, rid: str, emitted, t_first) -> None:
        e = self.entries.get(rid)
        if e is None:
            return
        e.emitted = np.asarray(emitted, np.int32)
        if e.t_first is None:
            e.t_first = t_first

    def get(self, rid: str) -> Optional[JournalEntry]:
        return self.entries.get(rid)

    def close(self, rid: str) -> None:
        e = self.entries.pop(rid, None)
        if e is not None:
            self.closed[rid] = e

    def is_closed(self, rid: str) -> bool:
        return rid in self.closed

    def open_entries(self) -> List[JournalEntry]:
        """Open (dispatched, not completed) entries in first-dispatch
        order — the replay order for a restarted router."""
        return sorted(
            self.entries.values(),
            key=lambda e: (e.t_dispatch or 0.0, e.t_submit, e.rid),
        )

    def sample_schedule(self, rid: str) -> Optional[tuple]:
        """``(seed, serial)`` journaled for ``rid`` (open or closed) —
        what a reference engine must pin to reproduce its tokens."""
        e = self.entries.get(rid) or self.closed.get(rid)
        if e is None or e.sample_serial is None:
            return None
        return (e.sample_seed, e.sample_serial)

    # --- crash-matrix snapshot/restore ---

    def snapshot(self) -> dict:
        """JSON-able state: open entries + closed rids. Trace ctxs are
        process-local and excluded (a restarted router re-mints)."""
        return {
            "open": [e.to_dict() for e in self.open_entries()],
            "closed": sorted(self.closed),
        }

    @classmethod
    def restore(cls, snap: dict) -> "DispatchJournal":
        j = cls()
        for d in snap.get("open") or []:
            j.entries[d["rid"]] = JournalEntry.from_dict(d)
        for rid in snap.get("closed") or []:
            # The closed-set marker is what matters for exactly-once;
            # the full entry bodies are not needed across a restart.
            j.closed.setdefault(rid, None)  # type: ignore[arg-type]
        return j


class CircuitBreaker:
    """Per-key (ResourceClaim name) death counting over a sliding
    window. ``max_deaths`` deaths within ``window_seconds`` opens the
    key's circuit; it half-closes by itself once deaths age out of the
    window (the replacement claim gets a fresh key anyway)."""

    def __init__(self, max_deaths: int = 3,
                 window_seconds: float = 30.0,
                 clock=time.monotonic):
        self.max_deaths = max_deaths
        self.window_seconds = window_seconds
        self.clock = clock
        self._deaths: Dict[str, Deque[float]] = {}
        self.opened_total = 0
        self._was_open: Dict[str, bool] = {}

    def _prune(self, key: str, now: float) -> Deque[float]:
        q = self._deaths.setdefault(key, collections.deque())
        horizon = now - self.window_seconds
        while q and q[0] < horizon:
            q.popleft()
        return q

    def record_death(self, key: str) -> bool:
        """Record one death for ``key``; returns True if this death
        OPENED the circuit (edge, not level — for the opened counter)."""
        now = self.clock()
        q = self._prune(key, now)
        q.append(now)
        was = self._was_open.get(key, False)
        open_now = len(q) >= self.max_deaths
        if open_now and not was:
            self.opened_total += 1
        self._was_open[key] = open_now
        return open_now and not was

    def is_open(self, key: str) -> bool:
        if key not in self._deaths:
            return False
        q = self._prune(key, self.clock())
        return len(q) >= self.max_deaths

    def open_keys(self) -> List[str]:
        return [k for k in list(self._deaths) if self.is_open(k)]

    def clear(self, key: str) -> None:
        self._deaths.pop(key, None)
        self._was_open.pop(key, None)

    def snapshot(self) -> dict:
        return {
            "deaths": {k: list(q) for k, q in self._deaths.items()},
            "opened_total": self.opened_total,
        }

    def restore(self, snap: dict) -> None:
        self._deaths = {
            k: collections.deque(v)
            for k, v in (snap.get("deaths") or {}).items()
        }
        self.opened_total = int(snap.get("opened_total") or 0)
        self._was_open = {
            k: len(q) >= self.max_deaths
            for k, q in self._deaths.items()
        }
