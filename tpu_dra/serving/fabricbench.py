"""Serving-fabric bench + CPU smoke — ``make fabricbench`` (wired into
``ci``), and the measurement core behind ``bench.py --leg-fabric``.

This leg composes the whole stack END TO END on one box: the shared
synthetic fleet published through the driver's real publisher
(:func:`tpu_dra.tools.fleetsim.spin_fleet`), the real
:class:`~tpu_dra.scheduler.core.SchedulerCore` (informers + SliceIndex
+ fragmentation-aware batch packing), ResourceClaims created/deleted by
the :class:`~tpu_dra.serving.autoscaler.ClaimAutoscaler`, and N live
:class:`~tpu_dra.workloads.engine.Engine` replicas behind the
multi-tenant :class:`~tpu_dra.serving.router.Router` — replaying a
seeded open-loop multi-tenant Poisson trace.

Headline SLO: **user-request-submitted → first-token** p50/p99
(``fabric_ttft_p50_ms`` / ``fabric_ttft_p99_ms``) at 10k+ concurrent
in-system sequences over ≥8 engine replicas (full mode; the smoke runs
the identical code path at CI size). Engines run the TINY model pinned
to CPU: the leg measures the TIER ABOVE the engine — routing, fairness,
admission, autoscaling — and queueing dominates its quantiles by
design; per-chip serving speed is ``--leg-serve``'s number.

Three measured phases:

1. **headline**: the full tenant mix (interactive + standard + batch)
   at an arrival rate held above the fleet's service rate, so the
   in-system population climbs past the concurrency bar while the
   latency tiers separate;
2. **fairness pair** (smoke gate a): the identical quiet-tenant trace
   measured twice — hot batch tenant ABSENT vs PRESENT. The WFQ
   contract: the hot tenant cannot degrade a quiet tenant's TTFT p99
   beyond a pinned bound over the hot-absent baseline
   (``fabric_quiet_p99_x``; FABRIC_ALLOW_GAP=1 bypasses on hostile
   machines);
3. **autoscale drill** (smoke gate b): a burst drives a claim-driven
   scale-up — the claim must be PLACED BY THE PACKER (allocation with
   device results from the synthetic fleet) and the decision→serving
   reaction time is recorded — then the post-burst lull drives a
   scale-down whose victim is evacuated MID-GENERATION: zero lost or
   duplicated sequences, completions TOKEN-IDENTICAL to an
   uninterrupted single-engine reference (greedy), and the
   ResourceClaim deleted only after the drain (the events log pins the
   ordering).

Knobs (env): FABRIC_NODES, FABRIC_REPLICAS, FABRIC_REQUESTS,
FABRIC_RATE, FABRIC_SEED, FABRIC_CAP, FABRIC_SLOTS, FABRIC_ALLOW_GAP,
FABRIC_ALLOW_SCALE.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import statistics
import sys
import time
from typing import List, Optional

import numpy as np

from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import RESOURCE_CLAIMS, ResourceClient
from tpu_dra.k8sclient.fake import FakeCluster
from tpu_dra.scheduler import fleet
from tpu_dra.scheduler.core import SchedulerCore
from tpu_dra.serving.autoscaler import AutoscalerConfig, ClaimAutoscaler
from tpu_dra.serving.router import (
    BATCH,
    INTERACTIVE,
    STANDARD,
    Replica,
    Router,
    RouterConfig,
    TenantSpec,
)
from tpu_dra.tools.fleetsim import spin_fleet
from tpu_dra.workloads.engine import Engine, EngineConfig, Request

NS = "fabric"


def _note(msg: str) -> None:
    print(f"fabricbench: {msg}", file=sys.stderr)


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[int(q * (len(sorted_vals) - 1))]


# --- model (TINY, CPU) -------------------------------------------------------


def _model():
    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    params = Llama(cfg).init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    return cfg, params


# --- multi-tenant trace ------------------------------------------------------


@dataclasses.dataclass
class TenantTraffic:
    spec: TenantSpec
    requests: int
    rate_rps: float
    prompt_lens: List[int]
    output_lens: List[int]
    sessions: int = 0  # 0 = affinity by prompt-prefix digest
    # ISSUE 15: the tenant's requests share one system prompt — the
    # first shared_prefix_len tokens of every prompt are a per-tenant
    # constant (drawn once from the tenant's rng). The router's
    # prefix digest then matches across the tenant's traffic and the
    # engines share the prefix pages copy-on-write; fabricbench
    # records the fleet-level saving as fabric_prefix_pages_saved.
    shared_prefix_len: int = 0


def make_fabric_trace(seed: int, traffic: List[TenantTraffic], vocab: int):
    """Seeded merged trace: per-tenant Poisson arrivals, prompt/output
    mixes, optional session ids and a per-tenant shared system-prompt
    prefix. Returns arrival-sorted ``(arrival_s, tenant, Request,
    session)`` tuples — the contract the smoke pins as deterministic
    before spending minutes replaying it."""
    out = []
    for ti, tt in enumerate(traffic):
        rng = np.random.default_rng((seed, ti))
        shared = (
            rng.integers(
                1, vocab, tt.shared_prefix_len
            ).astype(np.int32)
            if tt.shared_prefix_len else None
        )
        arrivals = np.cumsum(
            rng.exponential(1.0 / tt.rate_rps, tt.requests)
        )
        for i in range(tt.requests):
            plen = int(rng.choice(tt.prompt_lens))
            olen = int(rng.choice(tt.output_lens))
            session = (
                f"{tt.spec.name}-s{int(rng.integers(tt.sessions))}"
                if tt.sessions else None
            )
            prompt = rng.integers(1, vocab, plen).astype(np.int32)
            if shared is not None and plen > len(shared):
                prompt[: len(shared)] = shared
            out.append((
                float(arrivals[i]),
                tt.spec.name,
                Request(
                    rid=f"{tt.spec.name}-{i:05d}",
                    prompt=prompt,
                    max_new_tokens=olen,
                ),
                session,
            ))
    out.sort(key=lambda x: (x[0], x[2].rid))
    return out


# --- the fabric harness ------------------------------------------------------


class Fabric:
    """FakeCluster + published fleet + real scheduler + router +
    claim-driven autoscaler + N engine replicas, one process."""

    def __init__(
        self,
        nodes: int,
        tenants: List[TenantSpec],
        config,
        params,
        engine_config: EngineConfig,
        router_config: RouterConfig,
        autoscaler_config: AutoscalerConfig,
        shape: str = "1x1x1",
    ):
        self.metrics = Metrics()
        self.cluster = FakeCluster()
        self.agents = spin_fleet(self.cluster, nodes, self.metrics)
        # One registry for the whole fabric (publisher + scheduler +
        # router): the SLO-evaluated mode scrapes a single /metrics
        # endpoint the way fleetmon would scrape a co-located stack.
        self.core = SchedulerCore(
            self.cluster, retry_unschedulable_after=0.5,
            metrics=self.metrics,
        )
        self.core.start()
        self.claims = ResourceClient(self.cluster, RESOURCE_CLAIMS)
        self.config = config
        self.params = params
        self.engine_config = engine_config
        self.shape = shape
        self.router = Router(
            tenants, [], router_config, metrics=self.metrics
        )
        self.autoscaler = ClaimAutoscaler(
            self.router, self.claims,
            make_claim=self._make_claim,
            make_replica=self._make_replica,
            config=autoscaler_config,
            metrics=self.metrics,
        )
        deadline = time.monotonic() + 60
        for inf in (
            self.core.claim_informer, self.core.slice_informer,
            self.core.class_informer,
        ):
            if not inf.wait_for_sync(timeout=deadline - time.monotonic()):
                raise RuntimeError("scheduler informer sync timed out")

    def _make_claim(self, name: str) -> dict:
        claim = fleet.make_claim(0, self.shape)
        claim["metadata"] = {"name": name, "namespace": NS}
        return claim

    def _make_replica(self, claim: dict) -> Replica:
        # The cheap-replica premise: every replica shares one compiled
        # executable set through the engine's _JIT_CACHE (same
        # (config, int8) key) — pinned by the jit-cache test.
        engine = Engine(self.config, self.params, self.engine_config)
        rep = Replica(
            claim["metadata"]["name"], engine,
            claim_name=claim["metadata"]["name"], claim=claim,
            metrics=self.metrics,
        )
        rep.start()
        return rep

    def scale_to(self, n: int, timeout: float = 60.0) -> None:
        """Bootstrap the initial replica set through the SAME
        claim-create → packer-places → bind path scale-up uses."""
        deadline = time.monotonic() + timeout
        while len(self.router.live_replicas()) < n:
            if self.autoscaler._pending_claim is None:
                self.autoscaler._begin_scale_up(time.monotonic())
            self.autoscaler._tick_pending_alloc()
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"bootstrap to {n} replicas timed out at "
                    f"{len(self.router.live_replicas())}"
                )
            time.sleep(0.002)
        # Bootstrapping is provisioning, not a load decision: the
        # cooldown/flap bookkeeping AND the scale-up record start
        # clean — reaction times and events describe load-driven
        # actions only.
        self.autoscaler._last_action = None
        self.autoscaler._last_action_t = -1e18
        self.autoscaler.scaleups = 0
        self.autoscaler.reaction_s = []
        self.autoscaler.events = []

    def drive(
        self,
        trace,
        autoscale: bool = False,
        timeout: float = 600.0,
        extra_tick=None,
    ) -> dict:
        """Replay the trace open-loop (arrivals on the wall clock) on
        the control thread: submit due arrivals, poll the router, tick
        the autoscaler, until drained. ``extra_tick`` (optional) runs
        once per loop pass on the SAME control thread — the repack
        bench rides the repacker's tick() through it (ISSUE 12), per
        the router's threading contract."""
        i = 0
        submitted = 0
        rejected = 0
        t0 = time.monotonic()
        while True:
            now = time.monotonic() - t0
            while i < len(trace) and trace[i][0] <= now:
                _, tenant, req, session = trace[i]
                if self.router.submit(tenant, req, session=session):
                    submitted += 1
                else:
                    rejected += 1
                i += 1
            # A replica death no longer raises out of the drive loop:
            # poll()'s reaper classifies it, the dispatch journal
            # re-queues its in-flight sequences onto survivors, and the
            # autoscaler (when ticking) re-binds or replaces the claim
            # (ISSUE 16 — the old fail-loudly block lived here).
            moved = self.router.poll()
            if autoscale:
                self.autoscaler.tick()
            if extra_tick is not None:
                extra_tick()
            scaling = (
                self.autoscaler._pending_claim is not None
                or self.autoscaler._draining is not None
                or (autoscale and (
                    self.autoscaler._replace_owed > 0
                    or bool(self.router.dead_replicas)
                ))
            )
            if i >= len(trace) and not self.router.busy and not scaling:
                break
            if time.monotonic() - t0 > timeout:
                raise RuntimeError(
                    f"fabric drive timed out: {self.router.in_system()} "
                    f"sequences still in system"
                )
            if not moved:
                time.sleep(0.0005)
        return {
            "submitted": submitted,
            "rejected": rejected,
            "wall_s": round(time.monotonic() - t0, 3),
        }

    def stop(self) -> None:
        for rep in list(self.router.replicas):
            rep.stop()
        # Dead replicas the autoscaler never collected (autoscale=False
        # drives) still own threads; join them bounded.
        for rep in list(self.router.dead_replicas):
            rep.stop(timeout=2.0)
        self.core.stop()

    # --- reporting ---

    def ttft_quantiles(self, tenant: Optional[str] = None) -> dict:
        vals = sorted(
            c.ttft_s * 1000.0
            for c in self.router.completions.values()
            if tenant is None or c.tenant == tenant
        )
        return {
            "n": len(vals),
            "p50_ms": round(_pct(vals, 0.5), 2),
            "p99_ms": round(_pct(vals, 0.99), 2),
            "mean_ms": round(statistics.mean(vals), 2) if vals else 0.0,
        }


# --- phases ------------------------------------------------------------------


def _engine_config(slots: int, max_prompt: int, max_out: int) -> EngineConfig:
    page, chunk = 8, 4
    mpp = -(-(max_prompt + max_out + chunk) // page)
    return EngineConfig(
        page_size=page, max_slots=slots, max_pages_per_seq=mpp,
        scan_chunk=chunk, prefill_chunk=16,
    )


def warm_jit(config, params, ec: EngineConfig) -> None:
    """Compile outside the measurement: run one request per prefill
    bucket (plus the decode chunk they share) through a throwaway
    engine. The fleet's replicas hit the SAME _JIT_CACHE entry, so one
    warm engine warms every replica — the cheap-replica premise the
    jit-cache test pins; without this, the first tenant request of the
    run pays the whole fleet's cold compile and every TTFT quantile
    lies."""
    eng = Engine(config, params, ec)
    cap = ec.max_pages_per_seq * ec.page_size - (2 * ec.scan_chunk + 1)
    buckets = set()
    b = 1
    while b < ec.prefill_chunk:
        buckets.add(b)
        b *= 2
    buckets.add(ec.prefill_chunk)
    eng.run([
        Request(
            rid=f"warm{i}", prompt=np.ones(bl, np.int32),
            max_new_tokens=ec.scan_chunk + 1,
        )
        for i, bl in enumerate(sorted(x for x in buckets if x <= cap))
    ])


def run_headline(
    config, params, nodes, replicas, traffic, seed, cap,
    slots, timeout, slo_eval=False,
) -> dict:
    tenants = [t.spec for t in traffic]
    max_p = max(max(t.prompt_lens) for t in traffic)
    max_o = max(max(t.output_lens) for t in traffic)
    ec = _engine_config(slots, max_p, max_o)
    warm_jit(config, params, ec)
    fab = Fabric(
        nodes, tenants, config, params, ec,
        RouterConfig(
            backlog_cap_tokens=cap, max_inflight_per_replica=slots,
        ),
        AutoscalerConfig(
            min_replicas=replicas, max_replicas=replicas,
        ),
    )
    mon = srv = None
    try:
        if slo_eval:
            # SLO-evaluated mode (ISSUE 14): fleetmon scrapes the live
            # run's /metrics over HTTP while the trace replays, and the
            # per-class TTFT gates become SLO-catalog verdicts (scaled
            # SRE burn windows — the identical alert math a 30-day
            # window runs).
            from tpu_dra.infra.metrics import MetricsServer
            from tpu_dra.serving.router import SLO_CLASSES
            from tpu_dra.tools import fleetmon as fleetmon_mod

            srv = MetricsServer(fab.metrics, port=0, address="127.0.0.1")
            srv.start()
            mon = fleetmon_mod.FleetMon(
                [fleetmon_mod.Target("fabric", f"127.0.0.1:{srv.port}")],
                catalog=fleetmon_mod.builtin_catalog(
                    nodes=nodes, window_scale=1.0 / 600.0,
                    ttft_targets_s={
                        c.name: c.ttft_target_ms / 1000.0
                        for c in SLO_CLASSES
                    },
                ),
                interval_s=0.25, metrics=fab.metrics,
            )
            mon.start()
        fab.scale_to(replicas)
        trace = make_fabric_trace(seed, traffic, config.vocab_size)
        res = fab.drive(trace, timeout=timeout)
        done = fab.router.completions
        total_served = sum(len(c.tokens) for c in done.values())
        per_tenant = {
            t.spec.name: fab.ttft_quantiles(t.spec.name)
            for t in traffic
        }
        shares = {
            name: round(st["served_tokens"] / max(total_served, 1), 4)
            for name, st in fab.router.tenant_stats().items()
        }
        hits, misses = fab.router.affinity_hits, fab.router.affinity_misses
        # Fleet-level COW prefix sharing (ISSUE 15): high-water of
        # page allocations the engines avoided by incref'ing shared
        # prefix pages, summed over the replica fleet — the router's
        # prefix grouping measured as MEMORY, not just hit-rate.
        prefix_saved = sum(
            int(getattr(rep.engine, "prefix_saved_hw", 0))
            for rep in fab.router.replicas
        )
        out = {
            **res,
            "prefix_pages_saved": prefix_saved,
            "replicas": len(fab.router.live_replicas()),
            "completed": len(done),
            "ttft": fab.ttft_quantiles(),
            "per_tenant_ttft": per_tenant,
            "tenant_token_shares": shares,
            "peak_concurrent": fab.router.peak_concurrent,
            "wfq_max_lag_tokens": round(fab.router.max_lag_tokens, 1),
            "affinity_hit_rate": round(
                hits / max(hits + misses, 1), 4
            ),
        }
        assert res["submitted"] == len(done), (
            f"lost sequences: {res['submitted']} admitted, "
            f"{len(done)} completed"
        )
        if mon is not None:
            # One final scrape so the quantiles of the last completions
            # are in the store, then judge the catalog.
            mon.scrape_once()
            out["slo"] = {
                st.name: {
                    "data": st.data,
                    "ok": st.ok,
                    "current": st.current,
                    "burn_rate": st.burn_rate,
                    "alert": st.alert,
                    "budget_remaining": st.budget_remaining,
                }
                for st in mon.evaluate()
            }
        return out
    finally:
        if mon is not None:
            mon.stop()
        if srv is not None:
            srv.stop()
        fab.stop()


def run_fairness_pair(
    config, params, nodes, replicas, seed, slots, timeout,
) -> dict:
    """The identical quiet trace, hot tenant absent vs present."""
    gold = TenantTraffic(
        TenantSpec("gold", INTERACTIVE, weight=3.0),
        requests=12, rate_rps=15.0,
        prompt_lens=[6, 10], output_lens=[4, 8], sessions=4,
    )
    silver = TenantTraffic(
        TenantSpec("silver", STANDARD, weight=1.0),
        requests=8, rate_rps=10.0,
        prompt_lens=[8], output_lens=[6], sessions=2,
    )
    hot = TenantTraffic(
        TenantSpec("bulk", BATCH, weight=1.0),
        requests=60, rate_rps=2000.0,  # a t~0 flood
        prompt_lens=[8], output_lens=[16],
    )
    out = {}
    for label, traffic in (
        ("baseline", [gold, silver]),
        ("hot", [gold, silver, hot]),
    ):
        res = run_headline(
            config, params, nodes, replicas, traffic, seed,
            cap=1e9, slots=slots, timeout=timeout,
        )
        out[label] = res
        _note(
            f"fairness[{label}]: gold p99 "
            f"{res['per_tenant_ttft']['gold']['p99_ms']} ms, overall "
            f"p99 {res['ttft']['p99_ms']} ms, wall {res['wall_s']}s"
        )
    base = out["baseline"]["per_tenant_ttft"]["gold"]["p99_ms"]
    hot_p99 = out["hot"]["per_tenant_ttft"]["gold"]["p99_ms"]
    out["quiet_baseline_p99_ms"] = base
    out["quiet_p99_ms"] = hot_p99
    out["quiet_p99_x"] = round(hot_p99 / max(base, 1e-9), 3)
    # The structural contrast: the flooding tenant's own p99 carries
    # its backlog; the quiet tenant's must not (WFQ isolation).
    out["hot_tenant_p99_ms"] = (
        out["hot"]["per_tenant_ttft"]["bulk"]["p99_ms"]
    )
    return out


def run_autoscale_drill(config, params, nodes, seed, timeout) -> dict:
    """Claim-driven scale-up placed by the packer, then a scale-down
    that evacuates mid-generation — lossless and token-identical."""
    gold = TenantSpec("gold", INTERACTIVE, weight=1.0)
    slots = 4
    ec = _engine_config(slots, max_prompt=10, max_out=40)
    warm_jit(config, params, ec)
    fab = Fabric(
        nodes, [gold], config, params, ec,
        RouterConfig(
            backlog_cap_tokens=1e9, max_inflight_per_replica=slots,
        ),
        AutoscalerConfig(
            min_replicas=1, max_replicas=2,
            target_tokens_per_replica=256.0,
            # down_factor starts at 0 so the post-burst lull cannot
            # scale down INSIDE phase 1 (the drill wants the decision
            # to fire against phase 2's mid-generation longs).
            up_factor=1.25, down_factor=0.0,
            cooldown_seconds=0.3,
        ),
    )
    rng = np.random.default_rng(seed)
    burst = [
        Request(
            rid=f"burst-{i:03d}",
            prompt=rng.integers(1, config.vocab_size, 8).astype(np.int32),
            max_new_tokens=10,
        )
        for i in range(24)
    ]
    longs = [
        Request(
            rid=f"long-{i:03d}",
            prompt=rng.integers(1, config.vocab_size, 8).astype(np.int32),
            max_new_tokens=40,
        )
        for i in range(6)
    ]
    try:
        fab.scale_to(1)
        # Phase 1: the burst's queued backlog (16 x 18 tokens vs a
        # 256-token target on one replica) demands a second replica.
        trace = [
            (0.0, "gold", r, f"s{i}") for i, r in enumerate(burst)
        ]
        fab.drive(trace, autoscale=True, timeout=timeout)
        assert fab.autoscaler.scaleups >= 1, "burst never scaled up"
        up = [e for e in fab.autoscaler.events if e[0] == "up-ready"]
        assert up and up[0][3]["devices"], (
            "scale-up claim has no packer-placed devices"
        )
        reaction_ms = fab.autoscaler.reaction_s[0] * 1000.0
        # Arm scale-down for phase 2, after the cooldown from the
        # scale-up has fully expired.
        time.sleep(fab.autoscaler.config.cooldown_seconds + 0.05)
        fab.autoscaler.config.down_factor = 0.25
        # Phase 2: a few LONG sequences keep both replicas decoding
        # while the queue is empty — the lull decision drains a victim
        # MID-GENERATION and the survivors resume its sequences.
        trace2 = [
            (0.0, "gold", r, f"t{i}") for i, r in enumerate(longs)
        ]
        fab.drive(trace2, autoscale=True, timeout=timeout)
        assert fab.autoscaler.scaledowns >= 1, "lull never scaled down"
        down = [
            e for e in fab.autoscaler.events if e[0] == "down-complete"
        ][0]
        assert down[3]["engine_empty_at_delete"], (
            "claim deleted before the drain emptied the engine"
        )
        requeued = down[3]["requeued"]
        victim_claim = down[1]
        assert fab.claims.try_get(victim_claim, NS) is None, (
            f"victim claim {victim_claim} still exists"
        )
        # Lossless: every request completed exactly once...
        done = fab.router.completions
        want = {r.rid for r in burst} | {r.rid for r in longs}
        assert set(done) == want, (
            f"lost/invented sequences across the scale cycle: "
            f"{set(done) ^ want}"
        )
        # ...with completions TOKEN-IDENTICAL to an uninterrupted
        # single-engine reference (greedy determinism across replicas).
        ref = Engine(config, params, ec).run(
            [dataclasses.replace(r) for r in burst + longs]
        )
        mismatch = [
            rid for rid in want
            if not np.array_equal(done[rid].tokens, ref[rid].tokens)
        ]
        assert not mismatch, (
            f"scale-cycle completions diverged from the uninterrupted "
            f"reference on {mismatch}"
        )
        drain_ms = fab.autoscaler.drain_s[0] * 1000.0
        return {
            "scaleups": fab.autoscaler.scaleups,
            "scaledowns": fab.autoscaler.scaledowns,
            "scaleup_reaction_ms": round(reaction_ms, 2),
            "scaledown_drain_ms": round(drain_ms, 2),
            "evacuated_requeued": requeued,
            "flaps": fab.autoscaler.flaps,
            "placed_devices": up[0][3]["devices"],
        }
    finally:
        fab.stop()


# --- entry points ------------------------------------------------------------


def run(
    nodes: int,
    replicas: int,
    requests: int,
    rate: float,
    seed: int,
    cap: float,
    slots: int,
    smoke: bool = False,
    timeout: float = 900.0,
) -> dict:
    config, params = _model()

    # Trace determinism: the seeded multi-tenant trace is the contract
    # future rounds replay; pin it before spending minutes.
    probe = [TenantTraffic(
        TenantSpec("probe"), requests=32, rate_rps=100.0,
        prompt_lens=[4, 8], output_lens=[2, 4], sessions=3,
    )]
    t1 = make_fabric_trace(seed, probe, config.vocab_size)
    t2 = make_fabric_trace(seed, probe, config.vocab_size)
    assert len(t1) == len(t2) and all(
        a[0] == b[0] and a[1] == b[1] and a[3] == b[3]
        and np.array_equal(a[2].prompt, b[2].prompt)
        and a[2].max_new_tokens == b[2].max_new_tokens
        for a, b in zip(t1, t2)
    ), "fabric trace is not deterministic for a fixed seed"

    # Headline tenant mix: requests split ~27/33/40 across the tiers,
    # rates scaled so arrivals outrun service (the in-system population
    # must climb past the concurrency bar while tiers separate).
    mix = [
        TenantTraffic(
            TenantSpec("gold", INTERACTIVE, weight=4.0),
            requests=int(requests * 0.27), rate_rps=rate * 0.25,
            # One shared 16-token system prompt across the tenant
            # (ISSUE 15): the router's affinity-prefix digest matches
            # across gold's traffic, the engines share the prefix's
            # pages copy-on-write, and the headline records the fleet
            # saving as fabric_prefix_pages_saved. Prompts run past
            # the prefix so the share point stays page-aligned.
            prompt_lens=[20, 24, 28], output_lens=[2, 4, 6],
            sessions=50, shared_prefix_len=16,
        ),
        TenantTraffic(
            TenantSpec("silver", STANDARD, weight=2.0),
            requests=int(requests * 0.33), rate_rps=rate * 0.31,
            prompt_lens=[4, 8, 12], output_lens=[2, 4, 6], sessions=50,
        ),
        TenantTraffic(
            TenantSpec("bulk", BATCH, weight=1.0),
            requests=requests - int(requests * 0.27)
            - int(requests * 0.33),
            rate_rps=rate * 0.44,
            prompt_lens=[4, 8, 12], output_lens=[2, 4, 6],
        ),
    ]
    _note(
        f"headline: {nodes} nodes, {replicas} replicas, "
        f"{requests} requests at ~{rate:g}/s aggregate"
    )
    headline = run_headline(
        config, params, nodes, replicas, mix, seed, cap, slots, timeout,
        slo_eval=True,
    )
    _note(
        f"headline: ttft p50 {headline['ttft']['p50_ms']} ms p99 "
        f"{headline['ttft']['p99_ms']} ms, peak concurrent "
        f"{headline['peak_concurrent']}, rejected "
        f"{headline['rejected']}, wall {headline['wall_s']}s"
    )

    fairness = run_fairness_pair(
        config, params, nodes=min(nodes, 8), replicas=2, seed=seed,
        slots=4, timeout=timeout,
    )
    drill = run_autoscale_drill(
        config, params, nodes=min(nodes, 8), seed=seed, timeout=timeout
    )
    _note(
        f"autoscale: reaction {drill['scaleup_reaction_ms']} ms, drain "
        f"{drill['scaledown_drain_ms']} ms, requeued "
        f"{drill['evacuated_requeued']} mid-flight"
    )

    report = {
        "fabric_nodes": nodes,
        "fabric_replicas": headline["replicas"],
        "fabric_tenants": len(mix),
        "fabric_requests": headline["submitted"],
        "fabric_rejected": headline["rejected"],
        "fabric_ttft_p50_ms": headline["ttft"]["p50_ms"],
        "fabric_ttft_p99_ms": headline["ttft"]["p99_ms"],
        "fabric_peak_concurrent": headline["peak_concurrent"],
        "fabric_wfq_max_lag_tokens": headline["wfq_max_lag_tokens"],
        "fabric_affinity_hit_rate": headline["affinity_hit_rate"],
        "fabric_prefix_pages_saved": headline["prefix_pages_saved"],
        "fabric_tenant_shares": headline["tenant_token_shares"],
        "fabric_per_tenant_ttft": headline["per_tenant_ttft"],
        "fabric_quiet_p99_ms": fairness["quiet_p99_ms"],
        "fabric_quiet_baseline_p99_ms":
            fairness["quiet_baseline_p99_ms"],
        "fabric_quiet_p99_x": fairness["quiet_p99_x"],
        "fabric_hot_tenant_p99_ms": fairness["hot_tenant_p99_ms"],
        "fabric_scaleup_reaction_ms": drill["scaleup_reaction_ms"],
        "fabric_scaledown_drain_ms": drill["scaledown_drain_ms"],
        "fabric_autoscaler_flaps": drill["flaps"],
        "seed": seed,
    }

    # SLO-catalog verdicts (ISSUE 14): the headline ran with fleetmon
    # scraping it live — the per-class TTFT gates are now catalog
    # verdicts over scraped series, recorded next to the harness-side
    # quantiles they must agree with.
    slo_verdicts = headline.get("slo", {})
    for cls in ("interactive", "standard", "batch"):
        st = slo_verdicts.get(f"ttft-p99-{cls}")
        assert st is not None and st["data"], (
            f"SLO catalog has no data for ttft-p99-{cls} — the "
            f"router's fabric_ttft_seconds summary was not scraped"
        )
    report.update({
        "slo_ttft_interactive_burn_rate":
            slo_verdicts["ttft-p99-interactive"]["burn_rate"],
        "slo_ttft_batch_ok": bool(slo_verdicts["ttft-p99-batch"]["ok"]),
        "slo_fabric_catalog": slo_verdicts,
    })

    allow_gap = os.environ.get("FABRIC_ALLOW_GAP") == "1"
    allow_scale = os.environ.get("FABRIC_ALLOW_SCALE") == "1"
    for key in (
        "fabric_ttft_p50_ms", "fabric_ttft_p99_ms",
        "fabric_scaleup_reaction_ms",
    ):
        assert report[key] > 0, f"{key} missing/zero"
    # Gate (a): the hot tenant cannot degrade the quiet tenant's p99
    # beyond the pinned bound vs the hot-absent baseline. An absolute
    # floor keeps sub-100ms CPU jitter from tripping the ratio.
    if not allow_gap:
        ratio_ok = fairness["quiet_p99_x"] <= 3.0
        floor_ok = fairness["quiet_p99_ms"] <= 500.0
        assert ratio_ok or floor_ok, (
            f"fairness gate: quiet tenant p99 "
            f"{fairness['quiet_p99_ms']} ms with the hot tenant vs "
            f"{fairness['quiet_baseline_p99_ms']} ms without "
            f"(x{fairness['quiet_p99_x']}) — WFQ is not isolating "
            f"(FABRIC_ALLOW_GAP=1 to bypass on a hostile machine)"
        )
    # Gate (b) ran inside the drill (packer placement, lossless +
    # token-identical scale-down, drain-before-delete ordering).
    if not smoke and not allow_scale:
        assert report["fabric_replicas"] >= 8, (
            f"full leg wants >= 8 replicas, got "
            f"{report['fabric_replicas']} (FABRIC_ALLOW_SCALE=1 to "
            f"record anyway)"
        )
        assert report["fabric_peak_concurrent"] >= 10000, (
            f"full leg wants >= 10k concurrent in-system sequences, "
            f"peaked at {report['fabric_peak_concurrent']} — raise "
            f"FABRIC_REQUESTS/FABRIC_RATE (FABRIC_ALLOW_SCALE=1 to "
            f"record anyway)"
        )
    if smoke:
        # The batch tier's 30s objective is structurally safe at smoke
        # scale — a violation means the scrape/evaluate path itself
        # broke, not the machine was slow. (interactive's 250ms target
        # is recorded but not gated: CI jitter owns that band.)
        assert report["slo_ttft_batch_ok"], (
            f"batch-class TTFT SLO violating at smoke scale: "
            f"{slo_verdicts['ttft-p99-batch']}"
        )
        # Gold's shared system prompt must actually share pages on the
        # engines (ISSUE 15): the router stamps its popular prefix and
        # at least one replica registers + increfs it.
        assert report["fabric_prefix_pages_saved"] >= 1, (
            "fabric_prefix_pages_saved is 0 — the shared gold prefix "
            "never shared a page on any replica (router stamping or "
            "engine registry broke)"
        )
        _note(
            "smoke contract: trace determinism, SLO keys, fairness "
            f"gate (x{fairness['quiet_p99_x']}), packer-placed "
            "scale-up, lossless token-identical scale-down before "
            "claim delete, SLO-catalog TTFT verdicts scraped live — "
            "all hold"
        )
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser("fabricbench", description=__doc__)
    p.add_argument(
        "--smoke", action="store_true",
        help="CI size: small fleet/trace + the hard contract asserts",
    )
    args = p.parse_args(argv)
    env = os.environ.get
    if args.smoke:
        nodes = int(env("FABRIC_NODES", "8"))
        replicas = int(env("FABRIC_REPLICAS", "2"))
        requests = int(env("FABRIC_REQUESTS", "60"))
        rate = float(env("FABRIC_RATE", "200"))
        cap = float(env("FABRIC_CAP", "100000"))
        slots = int(env("FABRIC_SLOTS", "4"))
    else:
        nodes = int(env("FABRIC_NODES", "64"))
        replicas = int(env("FABRIC_REPLICAS", "8"))
        requests = int(env("FABRIC_REQUESTS", "15000"))
        rate = float(env("FABRIC_RATE", "3500"))
        cap = float(env("FABRIC_CAP", "500000"))
        slots = int(env("FABRIC_SLOTS", "16"))
    seed = int(env("FABRIC_SEED", "20260804"))
    report = run(
        nodes, replicas, requests, rate, seed, cap, slots,
        smoke=args.smoke,
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
