"""Serving fabric (ISSUE 11): the tier above one engine.

One :class:`~tpu_dra.workloads.engine.Engine` serves one DRA lease;
heavy traffic from millions of users needs the layer that spreads an
open-loop multi-tenant trace across a FLEET of engine replicas:

- :mod:`tpu_dra.serving.router` — session/prefix-affinity-aware
  dispatch, per-tenant SLO classes (latency-tier admission control),
  and weighted fair queuing over *tokens* so one hot tenant cannot
  starve the rest (the ShardedWorkQueue fairness story applied to the
  data plane);
- :mod:`tpu_dra.serving.autoscaler` — claim-driven autoscaling: the
  replica set grows by CREATING ResourceClaims (the PR-6 packer places
  them) and shrinks by evacuating an engine through the PR-7
  backpressure drain (host checkpoint, pages freed, lossless resume on
  another replica) BEFORE its ResourceClaim is deleted;
- :mod:`tpu_dra.serving.fabricbench` — fleetsim + engines composed into
  one end-to-end bench leg (``bench.py --leg-fabric`` /
  ``make fabricbench``): user-request-submitted → first-token p50/p99
  over the synthetic fleet, next to per-tenant fairness and autoscale
  reaction-time keys.
"""

from tpu_dra.serving.router import (  # noqa: F401
    BATCH,
    INTERACTIVE,
    STANDARD,
    FabricCompletion,
    Replica,
    Router,
    RouterConfig,
    SLOClass,
    TenantSpec,
)
from tpu_dra.serving.autoscaler import (  # noqa: F401
    AutoscalerConfig,
    ClaimAutoscaler,
)
