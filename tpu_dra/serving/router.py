"""Multi-tenant router: SLO-class admission + token-WFQ + affinity.

The data-plane fairness problem is the ShardedWorkQueue problem one
layer up: many tenants share a fleet of engine replicas, and a hot
tenant flooding requests must not starve everyone else's first-token
latency. The control-plane answer (PR 10) was sharding reconciles by
key; the data-plane answer here is **weighted fair queuing over
tokens** — the router holds the backlog itself (engines only ever see a
bounded number of in-flight sequences), and dispatch order is decided
by per-tenant virtual time, not arrival order:

- every request costs ``prompt_tokens + max_new_tokens`` virtual
  tokens;
- a tenant's request gets a start tag ``S = max(V, tenant.tail)`` and a
  finish tag ``F = S + cost / weight``; dispatch always picks the
  backlogged head with the smallest finish tag, and the fabric-wide
  virtual clock ``V`` advances to the dispatched start tag — classic
  WFQ, so over any busy interval tenant service converges to the weight
  ratio no matter how hot one tenant runs;
- per-tenant **virtual-time lag** (how far past a backlogged tenant's
  head turn the clock has advanced, in weighted tokens) is exported as
  ``fabric_tenant_vtime_lag{tenant=}`` — in a healthy fabric it stays
  bounded by roughly one request cost; sustained growth is the
  starvation signal the doctor WARNs on.

**SLO classes** (latency-tier admission control): each tenant carries a
class (INTERACTIVE / STANDARD / BATCH) whose ``admit_frac`` caps how
full the fabric's token backlog may be before that tier's requests are
REJECTED at the door. Under pressure the batch tier sheds first and
the interactive tier keeps admitting until the hard cap — overload
degrades the deferrable traffic, not the latency tier (MISO's
load-derived placement idea applied to admission).

**Affinity**: a request's affinity key (its session id, else a digest
of its prompt prefix) picks a preferred replica by rendezvous hashing,
so a session's turns — and unrelated requests sharing one system
prompt — land on the engine already holding their KV history (prefix
reuse is a locality property even before copy-on-write sharing lands;
ROADMAP item 3). A preferred replica with no headroom spills to the
least-loaded one: affinity is a hint, never a hot spot.

**Crash tolerance** (ISSUE 16): replicas are mortal. The control loop
classifies a replica dead on engine-thread death (``Replica.error``),
on a stuck-iteration watchdog (no engine step progress past a
``deadline.Budget`` while work is in flight), or when the autoscaler
reports its claim vanished. Every dispatch is journaled
(:class:`~tpu_dra.serving.faults.DispatchJournal`), so a dead
replica's in-flight sequences are reconstructed WITHOUT the engine's
cooperation and re-dispatched to survivors at their tenants' queue
front — token-identical under greedy, and token-identical under
sampling via the journaled per-request ``(seed, serial)`` schedule.
Containment: re-dispatches carry jittered exponential backoff, a
crash-looping claim's circuit opens
(:class:`~tpu_dra.serving.faults.CircuitBreaker`) so the autoscaler
replaces it instead of hot re-binding, and lost capacity degrades
admission gracefully — the backlog cap scales down by the owed
fraction, so BATCH sheds at the door first (``fabric_shed_total{cls=}``
counts it, ``fabric_degraded`` gauges it for fleetmon).

Threading contract — ENFORCED by ``# thread:`` annotations (lint codes
D802/D803, runtime twin :mod:`tpu_dra.infra.lockdep`), not prose:
``submit()`` and the lock-guarded gauges are ``# thread: any``;
``poll()`` and everything the autoscaler/repacker call are
``# thread: control`` (one thread assumes the control role per
fabric); ``Replica._loop`` is ``# thread: replica`` — it owns the only
thread that touches its engine's internals (dispatch rides the
engine's append-only ``add_request``).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import logging
import threading
import time
import zlib
from typing import Deque, Dict, List, Optional

import numpy as np

from tpu_dra.infra import deadline, lockdep, trace
from tpu_dra.serving.faults import (
    CircuitBreaker,
    DispatchJournal,
    ReplicaFault,
    redispatch_backoff,
)
from tpu_dra.workloads.engine import Completion, Evacuated, Request

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One latency tier. ``admit_frac`` is the fraction of the router's
    token-backlog cap this tier may still admit into: lower tiers hit
    their admission ceiling first as the fabric fills. ``ttft_target_ms``
    is the tier's advertised objective (recorded next to the measured
    quantiles; the bench compares, the router does not enforce)."""

    name: str
    tier: int  # 0 = most latency-sensitive
    admit_frac: float
    ttft_target_ms: float


INTERACTIVE = SLOClass("interactive", 0, 1.0, 250.0)
STANDARD = SLOClass("standard", 1, 0.85, 1000.0)
BATCH = SLOClass("batch", 2, 0.6, 30000.0)
# The built-in tiers, in tier order — what fleetmon's catalog states
# its per-class TTFT objectives from (fabricbench's SLO mode passes
# these targets in; tools cannot import serving per the layer DAG).
SLO_CLASSES = (INTERACTIVE, STANDARD, BATCH)


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    name: str
    slo: SLOClass = STANDARD
    weight: float = 1.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name}: weight must be > 0")


@dataclasses.dataclass
class RouterConfig:
    # Hard token-backlog cap (queued + in-flight request costs); tier
    # admission ceilings are fractions of it (SLOClass.admit_frac).
    backlog_cap_tokens: float = 262144.0
    # Engines admit/evict between scan chunks on their own; the router
    # additionally bounds how many sequences it hands each replica so
    # the BACKLOG stays in the WFQ (dispatch order keeps meaning) and a
    # drain/evacuation never strands more than this many sequences.
    max_inflight_per_replica: int = 16
    # Prompt tokens digested into the affinity key when the request
    # has no session id (one shared system prompt -> one replica).
    affinity_prefix_tokens: int = 16
    # --- crash tolerance (ISSUE 16) ---
    # Stuck-iteration watchdog: a replica with in-flight work whose
    # engine step-progress counter stands still this long is declared
    # dead (hung device call, wedged thread). deadline.Budget-backed.
    stall_deadline_seconds: float = 5.0
    # Circuit breaker: this many deaths of one claim inside the window
    # opens its circuit — the router stops routing to it and the
    # autoscaler REPLACES the claim instead of hot re-binding.
    breaker_deaths: int = 3
    breaker_window_seconds: float = 30.0
    # Jittered exponential backoff before a dead replica's sequence is
    # re-dispatched (a poisoned request must not hot-loop survivors).
    redispatch_backoff_base_seconds: float = 0.05
    redispatch_backoff_cap_seconds: float = 2.0
    # Replica.stop() join timeout: a wedged engine thread past this is
    # logged + counted (fabric_replica_stop_timeouts_total) and the
    # replica left in the dead state instead of silently blocking the
    # control thread for 30s while pretending it stopped.
    replica_join_timeout_seconds: float = 30.0


@dataclasses.dataclass
class FabricCompletion:
    """One request's end-to-end record, stitched across every replica
    it ran on (evacuations splice transparently)."""

    rid: str
    tenant: str
    tokens: np.ndarray
    t_submit: float  # router clock, at submit()
    t_first_token: float
    t_done: float
    replicas: List[str]  # every replica that served part of it

    @property
    def ttft_s(self) -> float:
        """The fabric SLO: user-request-submitted -> first token."""
        return self.t_first_token - self.t_submit

    @property
    def latency_s(self) -> float:
        return self.t_done - self.t_submit


class _FabricReq:
    __slots__ = (
        "rid", "tenant", "prompt", "max_new", "session", "cost",
        "start_tag", "finish_tag", "t_submit", "t_first", "emitted",
        "replicas", "trace_ctx", "t_dispatch", "prefix_key",
        "sample_seed", "sample_serial", "retries", "not_before",
    )

    def __init__(self, rid, tenant, prompt, max_new, session, cost):
        self.rid = rid
        self.tenant = tenant
        self.prompt = prompt
        self.max_new = max_new
        self.session = session
        self.cost = cost
        self.start_tag = 0.0
        self.finish_tag = 0.0
        self.t_submit = 0.0
        self.t_first: Optional[float] = None
        self.emitted = np.zeros(0, np.int32)
        self.replicas: List[str] = []
        # The request's trace identity (None while tracing is off):
        # minted at submit, it is the serving.request.queued span's
        # own ctx; dispatch/prefill/first-token/evacuate spans parent
        # under it (recorded retroactively from the completion stamps).
        self.trace_ctx = trace.new_ctx()
        self.t_dispatch: Optional[float] = None
        # Content digest of the prompt's affinity prefix — the engine's
        # prefix-sharing id (ISSUE 15), stamped at dispatch only once
        # the prefix has proven popular (>= 2 submissions).
        self.prefix_key: Optional[str] = None
        # Sampling schedule (ISSUE 16): serial assigned at submit (the
        # router's counter, engine-independent so it survives replica
        # death); seed captured from the first engine dispatched to.
        self.sample_seed: Optional[int] = None
        self.sample_serial: Optional[int] = None
        # Re-dispatch containment: death-recovery retry count and the
        # earliest clock time the next dispatch may run (backoff).
        self.retries = 0
        self.not_before = 0.0

    @property
    def remaining(self) -> int:
        return self.max_new - len(self.emitted)


class _TenantState:
    __slots__ = ("spec", "queue", "tail_tag", "served_tokens", "rejected")

    def __init__(self, spec: TenantSpec):
        self.spec = spec
        self.queue: Deque[_FabricReq] = collections.deque()
        self.tail_tag = 0.0  # finish tag of the newest queued request
        self.served_tokens = 0
        self.rejected = 0


class Replica:
    """One engine replica bound to one ResourceClaim. Owns the ONLY
    thread that steps the engine; the router talks to it through the
    engine's append-only ``add_request``, the completion ``outbox``,
    and the evacuation handshake (``begin_evacuate`` → ``evac_done`` →
    ``take_evacuated``) the autoscaler's scale-down drives."""

    def __init__(self, name: str, engine, claim_name: str = "",
                 claim: Optional[dict] = None, metrics=None,
                 role: str = "both"):
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"replica {name}: role must be prefill/decode/both, "
                f"got {role!r}"
            )
        self.name = name
        self.engine = engine
        self.claim_name = claim_name
        self.claim = claim
        self.metrics = metrics
        # Phase role (ISSUE 17): "prefill" replicas take prompt
        # dispatches and EXPORT each sequence to the decode pool at
        # prefill completion (live paged-KV migration); "decode"
        # replicas take migrated extents only; "both" is the colocated
        # default — no exports, all dispatches, every pre-existing
        # behavior unchanged.
        self.role = role
        # Set by the router's control loop: exports only run while a
        # live decode-role replica exists to receive them (otherwise a
        # fallback re-prefill would bounce straight back here).
        self.export_enabled = False
        self.quiesced = False  # router stops dispatching; engine drains
        # Mid-repack (ISSUE 12): the repacker owns this replica's fate;
        # the autoscaler must not pick it as a scale-down victim (the
        # claim is being MOVED, not retired — deleting it would turn a
        # defrag into an outage).
        self.migrating = False
        self.error: Optional[BaseException] = None  # engine-thread death
        # Dead state (ISSUE 16): set by Router.mark_dead (crash / stall
        # / claim-vanished) or by a stop() join timeout. A dead replica
        # is out of the routing set; its thread may still be wedged.
        self.dead = False
        self.death_reason = ""
        # Watchdog state, control-thread-owned: the engine progress
        # value last seen and the deadline budget it must beat.
        self.last_progress: Optional[int] = None  # thread: control
        self.watchdog: Optional[deadline.Budget] = None  # thread: control
        self._fault: Optional[str] = None  # chaos injection seam
        self.outbox: Deque[Completion] = collections.deque()
        # KV-migration mailboxes (ISSUE 17). GIL-atomic deque append /
        # popleft is the whole protocol: the engine thread produces
        # exports and import results, the control thread consumes them
        # (and produces the import inbox the engine thread consumes).
        self.migration_outbox: Deque = collections.deque()  # SequenceExtent
        self._import_inbox: Deque = collections.deque()  # (sx, t0)
        self.import_results: Deque = collections.deque()  # (sx, ok, t0)
        self.inflight: Dict[str, _FabricReq] = {}  # thread: control (router dispatch bookkeeping)
        self._evac_request = threading.Event()
        self._evac_done = threading.Event()
        self._evacuated: List[Evacuated] = []
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:  # thread: control
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"replica-{self.name}"
        )
        self._thread.start()

    def signal_stop(self) -> None:  # thread: control
        """Ask the engine thread to exit WITHOUT joining: the control
        loop must not block on a thread that may be wedged (that is the
        exact failure being contained). The autoscaler joins later with
        a bounded timeout via :meth:`stop`."""
        self._stop.set()
        self._wake.set()

    def stop(self, timeout: Optional[float] = None) -> bool:  # thread: control
        """Stop the engine thread; returns True if it actually exited
        within ``timeout`` seconds. A join timeout no longer hangs
        silently: it is logged, counted
        (``fabric_replica_stop_timeouts_total``), and the replica is
        left in the dead state instead of pretending it stopped."""
        if timeout is None:
            timeout = 30.0
        self._stop.set()
        self._wake.set()
        joined = True
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            joined = not self._thread.is_alive()
        if not joined:
            log.warning(
                "replica %s: engine thread did not stop within %.1fs "
                "(wedged); leaving it dead", self.name, timeout,
            )
            self.dead = True
            if not self.death_reason:
                self.death_reason = "stop-timeout"
            if self.metrics is not None:
                self.metrics.inc("fabric_replica_stop_timeouts_total")
        self.engine.close()
        return joined

    def inject_fault(self, kind: str) -> None:  # thread: control
        """Chaos seam (ISSUE 16): arm a fault the engine thread trips
        before its next step. ``"crash"`` raises :class:`ReplicaFault`
        out of the loop (the hard-death path); ``"stall"`` wedges the
        thread — it stops stepping and produces no progress, exactly
        what the router's stuck-iteration watchdog exists to catch."""
        self._fault = kind  # lint: disable=R200 (one-shot flag handoff: single writer arms, the engine thread consumes-and-clears; a GIL-atomic attribute store is the whole protocol)
        self._wake.set()

    def submit(self, req: Request) -> None:  # thread: control
        self.engine.add_request(req)
        self._wake.set()

    def submit_extent(self, sx, t0: float) -> None:  # thread: control
        """Hand a migrated sequence's KV extent to this replica's
        engine thread for grafting (control thread side of the import
        handshake). The result — grafted or rejected for capacity —
        comes back through ``import_results``."""
        self._import_inbox.append((sx, t0))  # lint: disable=R200 (GIL-atomic deque mailbox: control thread appends, engine thread popleft-drains)
        self._wake.set()

    # --- evacuation handshake (autoscaler scale-down) ---

    def begin_evacuate(self) -> None:  # thread: control
        self._evac_done.clear()
        self._evac_request.set()
        self._wake.set()

    @property
    def evac_done(self) -> bool:  # thread: control
        return self._evac_done.is_set()

    def take_evacuated(self) -> List[Evacuated]:  # thread: control
        out, self._evacuated = self._evacuated, []  # lint: disable=R200 (handshake-ordered: written by the engine thread BEFORE _evac_done.set(), read by the control thread only AFTER evac_done — the Event is the fence)
        return out

    # --- engine thread ---

    def _loop(self) -> None:  # thread: replica (entry: Thread target started by start())
        lockdep.single_owner(self, "replica")
        try:
            while not self._stop.is_set():
                fault = self._fault
                if fault == "crash":
                    self._fault = None  # lint: disable=R200 (consume side of the inject_fault one-shot flag handoff)
                    raise ReplicaFault(
                        f"chaos: injected crash on replica {self.name}"
                    )
                if fault == "stall":
                    # A wedged engine: no steps, no outbox drain, no
                    # progress — only the stop flag gets it out. The
                    # router's watchdog must detect this on its own.
                    while not self._stop.is_set():
                        time.sleep(0.005)
                    break
                if self._evac_request.is_set():
                    # Runs ON the engine thread between steps: evacuate
                    # is a host-side drain, never concurrent with a
                    # chunk.
                    self._drain_outbox()
                    self._evacuated = self.engine.evacuate()  # lint: disable=R200 (handshake-ordered: _evac_done.set() below is the fence the control-thread reader waits on)
                    self._evac_request.clear()
                    self._evac_done.set()
                # Graft migrated-in extents BETWEEN steps (host-side,
                # never concurrent with a chunk). A capacity rejection
                # is a normal result — the router falls back to
                # re-prefill dispatch.
                while self._import_inbox:
                    sx, t0 = self._import_inbox.popleft()  # lint: disable=R200 (GIL-atomic deque mailbox: consumer side of submit_extent)
                    ok = self.engine.import_sequence(sx)
                    self.import_results.append((sx, ok, t0))
                busy = self.engine.step() if self.engine.busy else False
                self._drain_outbox()
                if self.role == "prefill" and self.export_enabled:
                    # Phase handoff (ISSUE 17): every sequence that
                    # completed prefill ships its pages to the decode
                    # pool instead of decoding here.
                    for rid in self.engine.decoding_rids():
                        self.migration_outbox.append(
                            self.engine.export_sequence(rid)
                        )
                if not busy:
                    self._wake.wait(0.002)
                    self._wake.clear()
        except ReplicaFault as e:
            # Injected (chaos) death: expected and recovered — record
            # it for the control loop's reaper without the traceback
            # noise a re-raise through the thread excepthook produces.
            self.error = e
        except BaseException as e:  # noqa: BLE001 — surfaced to control
            # A dead engine thread must not look like a stuck queue:
            # the control loop checks .error, journals the replica's
            # in-flight sequences onto survivors, and keeps serving
            # (ISSUE 16 — the old behavior here was to fail loudly and
            # take every tenant down with one bad replica).
            self.error = e
            raise

    def _drain_outbox(self) -> None:
        done = self.engine.completed
        if done:
            for rid in list(done):
                self.outbox.append(done.pop(rid))


class Router:
    """See module doc. ``metrics`` gets the fabric gauges the doctor
    reads (``fabric_tenant_vtime_lag``, ``fabric_backlog_tokens``, ...);
    ``clock`` must be the same monotonic base the engines stamp with."""

    def __init__(
        self,
        tenants: List[TenantSpec],
        replicas: Optional[List[Replica]] = None,
        config: Optional[RouterConfig] = None,
        metrics=None,
        clock=time.monotonic,
    ):
        self.config = config or RouterConfig()
        self.metrics = metrics
        self.clock = clock
        self._tenants: Dict[str, _TenantState] = {
            t.name: _TenantState(t) for t in tenants
        }
        self.replicas: List[Replica] = list(replicas or [])
        self._vtime = 0.0
        self._lock = threading.Lock()  # guards WFQ state vs submit()
        self.completions: Dict[str, FabricCompletion] = {}
        # --- crash tolerance (ISSUE 16), control-thread-owned ---
        self.journal = DispatchJournal()
        self.breaker = CircuitBreaker(
            max_deaths=self.config.breaker_deaths,
            window_seconds=self.config.breaker_window_seconds,
            clock=clock,
        )
        # Dead replicas parked for the autoscaler (take_dead): it joins
        # their threads with a bounded timeout and decides re-bind vs
        # quarantine+replace.
        self.dead_replicas: List[Replica] = []
        self.deaths = 0
        self.death_log: List[tuple] = []  # (name, reason, t)
        self.redispatched = 0
        self.duplicates_dropped = 0
        # Replicas owed: died and not yet replaced. While > 0 the
        # admission cap scales down by live/(live+owed), so BATCH sheds
        # at the door first (graceful degradation, not a cliff).
        self._capacity_owed = 0
        self.shed: Dict[str, int] = {}  # per-SLO-class shed counts
        # Router-level sampling serial: engine-independent, assigned at
        # submit, journaled at dispatch — a re-dispatched SAMPLED
        # sequence pins it so the new engine replays the same
        # (seed, serial, position) key schedule.
        self._sample_serial = 0
        self._in_system = 0
        self.peak_concurrent = 0
        self._backlog_tokens = 0.0  # queued + inflight costs
        self._inflight_tokens = 0.0  # dispatched-not-completed costs
        # Per-phase split of the queued work (ISSUE 17): prefill-side
        # tokens still to be computed (prompt + folded emitted at next
        # dispatch) vs decode-side tokens still owed (remaining), for
        # queued requests plus the migration waiting room. The sums
        # track the same mutations as _backlog/_inflight under the same
        # lock; the autoscaler sizes the two phase pools from them.
        self._queued_prefill_tokens = 0.0
        self._queued_decode_tokens = 0.0
        # Migration waiting room (ISSUE 17): sequences exported off a
        # prefill replica, pages in hand, waiting for a decode replica
        # with headroom. Control-thread-owned.
        self._migrating: Deque = collections.deque()  # (sx, fr, t0)
        self.kv_migrations: Dict[str, int] = {}  # outcome -> count
        self.kv_migrated_pages = 0
        self.migration_seconds: List[float] = []
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.max_lag_tokens = 0.0  # high-water starvation lag observed
        # Prefix popularity (ISSUE 15): content-digest -> submissions
        # seen, bounded LRU. A request is stamped with prefix_id /
        # prefix_len for the ENGINE's copy-on-write sharing only once
        # its prefix digest has been seen >= 2 times — unique-prompt
        # traffic never pays the engine-side registration cost, while
        # a shared system prompt starts sharing from its second user.
        self._prefix_seen: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict()
        )
        self._prefix_seen_cap = 1024
        # Gauge export rides poll() but is throttled: the control loop
        # polls every ~ms and re-rendering the whole per-tenant gauge
        # set each pass starves the engine threads of the GIL for
        # nothing a scraper could see.
        self._export_period = 0.05
        self._last_export = -1e18

    # --- replica set (autoscaler-mutated, control thread only) ---

    def add_replica(self, rep: Replica) -> None:  # thread: control
        self.replicas.append(rep)  # lint: disable=R200 (replica-set mutation is control-thread-only by the module's threading contract; submit() threads never touch it)
        if self._capacity_owed > 0:
            # Capacity restored (re-bind or replacement claim): the
            # degradation factor recovers with it. Written under the
            # lock because submit() reads it for the admission ceiling.
            with self._lock:
                self._capacity_owed -= 1
        self._export()

    def remove_replica(self, rep: Replica) -> None:  # thread: control
        self.replicas = [r for r in self.replicas if r is not rep]  # lint: disable=R200 (control-thread-only, same contract as add_replica)
        self._export()

    def live_replicas(self) -> List[Replica]:  # thread: control
        return [r for r in self.replicas if not r.quiesced]

    def take_dead(self) -> List[Replica]:  # thread: control
        """Hand the parked dead replicas to the autoscaler (which joins
        their threads with a bounded timeout and re-binds or replaces
        their claims); clears the parking list."""
        out, self.dead_replicas = self.dead_replicas, []  # lint: disable=R200 (control-thread-only: poll() parks corpses, the autoscaler tick — same thread by contract — takes them)
        return out

    # --- intake ---

    # thread: any (the open-loop trace threads; WFQ state is lock-guarded)
    def submit(
        self, tenant: str, req: Request, session: Optional[str] = None
    ) -> bool:
        """Admit or reject (False) one request. Latency-tier admission:
        a tier admits only while the fabric backlog is under its
        ``admit_frac`` share of the cap — under pressure BATCH sheds
        first, INTERACTIVE keeps admitting until the hard cap."""
        ts = self._tenants[tenant]
        cost = float(len(req.prompt) + req.max_new_tokens)
        with self._lock:
            ceiling = (
                ts.spec.slo.admit_frac * self.config.backlog_cap_tokens
            )
            owed = self._capacity_owed
            if owed > 0:
                # Graceful degradation (ISSUE 16): dead-but-unreplaced
                # replicas shrink the effective cap by the lost
                # fraction, so tier ceilings bite sooner and BATCH
                # (admit_frac 0.6) sheds at the door FIRST while
                # INTERACTIVE keeps admitting — capacity loss degrades
                # the deferrable traffic, never a hard outage.
                live = len(self.replicas)  # lint: disable=R200 (len() of the atomically-swapped list; submit threads read, control thread swaps)
                ceiling *= live / float(live + owed)
            if self._backlog_tokens + cost > ceiling:
                ts.rejected += 1
                if self.metrics is not None:
                    self.metrics.inc(
                        "fabric_rejected_total",
                        labels={"tenant": tenant},
                    )
                if owed > 0:
                    cls = ts.spec.slo.name
                    self.shed[cls] = self.shed.get(cls, 0) + 1
                    if self.metrics is not None:
                        self.metrics.inc(
                            "fabric_shed_total", labels={"cls": cls}
                        )
                return False
            fr = _FabricReq(
                req.rid, tenant, np.asarray(req.prompt, np.int32),
                req.max_new_tokens, session, cost,
            )
            npfx = min(
                self.config.affinity_prefix_tokens, len(fr.prompt)
            )
            if npfx > 1:
                pkey = hashlib.sha1(
                    fr.prompt[:npfx].tobytes()
                ).hexdigest()
                self._prefix_seen[pkey] = (
                    self._prefix_seen.pop(pkey, 0) + 1
                )
                while len(self._prefix_seen) > self._prefix_seen_cap:
                    self._prefix_seen.popitem(last=False)
                fr.prefix_key = pkey
            fr.t_submit = self.clock()
            self._sample_serial += 1
            fr.sample_serial = self._sample_serial
            fr.start_tag = max(self._vtime, ts.tail_tag)
            fr.finish_tag = fr.start_tag + cost / ts.spec.weight
            ts.tail_tag = fr.finish_tag
            ts.queue.append(fr)
            self._backlog_tokens += cost
            self._queued_prefill_tokens += len(fr.prompt)
            self._queued_decode_tokens += fr.max_new
            self._in_system += 1
            self.peak_concurrent = max(self.peak_concurrent, self._in_system)
        return True

    # --- control loop ---

    def poll(self) -> bool:  # thread: control
        """One control-loop pass: reap dead replicas (journal-recover
        their in-flight work), collect completions, dispatch from the
        WFQ into replicas with headroom, export gauges. Returns True
        when any work moved. A replica death never raises out of here —
        it is detected, contained, and recovered (ISSUE 16)."""
        lockdep.single_owner(self, "control")
        moved = self._reap()
        # Migrations settle BEFORE completions: a fast decode replica
        # can graft an extent AND finish the sequence inside one poll
        # interval — collecting the completion first would pop the
        # in-flight entry and orphan the import result (the migration
        # would never count as shipped).
        moved = self._collect_migrations() or moved
        moved = self._dispatch_migrations() or moved
        moved = self._collect() or moved
        moved = self._dispatch() or moved
        now = self.clock()
        if now - self._last_export >= self._export_period:
            self._last_export = now
            self._export()
        return moved

    # --- failure detection + journal recovery (ISSUE 16) ---

    def _reap(self) -> bool:
        """Detection: engine-thread death (``Replica.error``) and the
        stuck-iteration watchdog (no step progress past the deadline
        while work is in flight). Claim-vanished detection lives in the
        autoscaler (it owns the claim store) and calls
        :meth:`mark_dead` with reason ``"claim-vanished"``."""
        moved = False
        for rep in list(self.replicas):
            if rep.error is not None and not rep.dead:
                self.mark_dead(rep, "crash")
                moved = True
            elif self._stalled(rep):
                self.mark_dead(rep, "stall")
                moved = True
        return moved

    def _stalled(self, rep: Replica) -> bool:
        prog = getattr(rep.engine, "progress", None)
        if prog is None or rep.quiesced or not rep.inflight:
            # No heartbeat source (stub engines), draining, or idle:
            # nothing to watchdog. Drop any armed budget so an idle
            # stretch never counts against the next burst.
            rep.watchdog = None
            return False
        if rep.watchdog is None or prog != rep.last_progress:
            rep.last_progress = prog
            rep.watchdog = deadline.Budget(
                timeout=self.config.stall_deadline_seconds,
                name=f"replica-{rep.name}-progress",
            )
            return False
        return rep.watchdog.expired()

    def mark_dead(self, rep: Replica, reason: str) -> int:  # thread: control
        """Classify ``rep`` dead, recover its in-flight sequences from
        the dispatch journal, and park it for the autoscaler. Returns
        how many sequences were re-queued. Idempotent per replica."""
        if rep.dead:
            return 0
        now = self.clock()
        rep.dead = True
        rep.quiesced = True
        rep.death_reason = reason
        # Never join here: the control thread must not block on a
        # possibly-wedged thread. The autoscaler joins with a bounded
        # timeout when it takes the corpse.
        rep.signal_stop()
        self.deaths += 1
        self.death_log.append((rep.name, reason, now))
        key = rep.claim_name or rep.name
        opened = self.breaker.record_death(key)
        # Sequences that FINISHED before the death are sitting in the
        # outbox — collect them first so the journal replay covers
        # exactly the in-flight set (zero duplicates).
        self._collect()
        n = self._reclaim(rep, now)
        self.replicas = [r for r in self.replicas if r is not rep]  # lint: disable=R200 (control-thread-only, same contract as add_replica)
        self.dead_replicas.append(rep)  # lint: disable=R200 (control-thread-only parking list, same contract as take_dead)
        with self._lock:
            # submit() reads the owed count for the degraded ceiling.
            self._capacity_owed += 1
        if self.metrics is not None:
            self.metrics.inc(
                "fabric_replica_deaths_total", labels={"reason": reason}
            )
            if n:
                self.metrics.inc("fabric_redispatched_total", float(n))
            if opened:
                self.metrics.inc("fabric_circuit_opened_total")
        log.warning(
            "replica %s dead (%s): %d in-flight sequences recovered "
            "from the journal%s", rep.name, reason, n,
            "; circuit OPEN" if opened else "",
        )
        self._export()
        return n

    def _reclaim(self, rep: Replica, now: float) -> int:
        """Journal recovery: rebuild every sequence the dead replica
        still held and splice it at the FRONT of its tenant's queue
        (its virtual cost was charged at first dispatch — re-entry is
        free), with jittered backoff gating the re-dispatch."""
        n = 0
        for rid in list(rep.inflight):
            rep.inflight.pop(rid)
            e = self.journal.get(rid)
            if e is None or self.journal.is_closed(rid):
                continue  # completed (collected above) or never journaled
            fr = self._from_journal(e)
            fr.retries += 1
            fr.not_before = now + redispatch_backoff(
                fr.retries,
                self.config.redispatch_backoff_base_seconds,
                self.config.redispatch_backoff_cap_seconds,
                fr.rid,
            )
            ts = self._tenants[fr.tenant]
            with self._lock:
                fr.start_tag = fr.finish_tag = self._vtime
                ts.queue.appendleft(fr)
                self._inflight_tokens -= fr.cost
                self._queued_prefill_tokens += (
                    len(fr.prompt) + len(fr.emitted)
                )
                self._queued_decode_tokens += fr.remaining
                self.redispatched += 1
            n += 1
        return n

    def _from_journal(self, e) -> _FabricReq:
        """A fresh _FabricReq carrying everything the journal knows —
        the dead engine contributes nothing."""
        fr = _FabricReq(
            e.rid, e.tenant, e.prompt, e.max_new, e.session, e.cost
        )
        fr.emitted = np.asarray(e.emitted, np.int32)
        fr.t_submit = e.t_submit
        fr.t_first = e.t_first
        fr.t_dispatch = e.t_dispatch
        fr.replicas = list(e.replicas)
        fr.sample_seed = e.sample_seed
        fr.sample_serial = e.sample_serial
        fr.retries = e.retries
        if e.trace_ctx is not None:
            fr.trace_ctx = e.trace_ctx
        return fr

    def recover_from_journal(self, journal: DispatchJournal) -> int:  # thread: control
        """Crash-matrix restart path: a NEW router adopts a restored
        journal — every open entry re-enters its tenant's queue front
        (first-dispatch order), accounting is rebuilt, and closed rids
        stay closed so replay is exactly-once. Returns the number of
        sequences re-queued."""
        self.journal = journal
        n = 0
        # appendleft inverts order: walk newest-first so the oldest
        # dispatch lands at the queue head.
        for e in reversed(journal.open_entries()):
            if e.tenant not in self._tenants:
                continue
            fr = self._from_journal(e)
            fr.retries += 1
            ts = self._tenants[fr.tenant]
            with self._lock:
                fr.start_tag = fr.finish_tag = self._vtime
                ts.queue.appendleft(fr)
                self._backlog_tokens += fr.cost
                self._queued_prefill_tokens += (
                    len(fr.prompt) + len(fr.emitted)
                )
                self._queued_decode_tokens += fr.remaining
                self._in_system += 1
            n += 1
        with self._lock:
            self.redispatched += n
        return n

    @property
    def busy(self) -> bool:  # thread: any (lock-guarded read)
        if self._in_system > 0:
            return True
        return any(r.outbox for r in self.replicas)

    def backlog_tokens(self) -> float:  # thread: any (lock-guarded read)
        with self._lock:
            return self._backlog_tokens

    def queued_tokens(self) -> float:  # thread: any (lock-guarded read)
        """Token cost still waiting in the WFQ (excludes dispatched
        work) — the autoscaler's load signal: in-flight cost is bounded
        by the per-replica inflight cap and finishes on its own; it is
        the QUEUE that says the replica set is too small (or too big)."""
        with self._lock:
            return self._backlog_tokens - self._inflight_tokens

    def in_system(self) -> int:  # thread: any (lock-guarded read)
        return self._in_system

    def queued_prefill_tokens(self) -> float:  # thread: any (lock-guarded read)
        """Prefill-side queued work: prompt (+ folded emitted) tokens
        the next dispatches will have to compute — the signal that says
        the PREFILL pool is too small."""
        with self._lock:
            return self._queued_prefill_tokens

    def queued_decode_tokens(self) -> float:  # thread: any (lock-guarded read)
        """Decode-side queued work: tokens still owed by queued
        requests plus the migration waiting room — the signal that says
        the DECODE pool is too small."""
        with self._lock:
            return self._queued_decode_tokens

    def migration_backlog(self) -> int:  # thread: any (lock-guarded read)
        """Extents waiting for a decode replica with headroom."""
        return len(self._migrating)

    # --- WFQ dispatch ---

    def _next_tenant(self, now: float) -> Optional[_TenantState]:
        best = None
        for ts in self._tenants.values():
            if not ts.queue:
                continue
            if ts.queue[0].not_before > now:
                # Re-dispatch backoff (ISSUE 16): this head is cooling
                # off after its replica died; skip the tenant this pass
                # rather than busy-spin the poisoned request.
                continue
            if best is None or (
                ts.queue[0].finish_tag < best.queue[0].finish_tag
            ):
                best = ts
        return best

    def _affinity_key(self, fr: _FabricReq) -> str:
        if fr.session:
            return fr.session
        prefix = fr.prompt[: self.config.affinity_prefix_tokens]
        return hashlib.sha1(prefix.tobytes()).hexdigest()

    def _pick_replica(self, fr: _FabricReq) -> Optional[Replica]:
        # An open circuit quarantines the claim: no routing to any
        # replica bound to it until the autoscaler replaces it (or the
        # deaths age out of the breaker window).
        live = [
            r for r in self.live_replicas()
            if not self.breaker.is_open(r.claim_name or r.name)
        ]
        # Phase roles (ISSUE 17): prompt dispatches go to
        # prefill-capable replicas; the decode pool only receives
        # migrated extents. If every prefill-capable replica is gone
        # (deaths outpacing replacement), serving degraded on the
        # decode pool beats deadlocking the queue.
        prefill_capable = [r for r in live if r.role != "decode"]
        live = prefill_capable or live
        if not live:
            return None
        cap = self.config.max_inflight_per_replica
        with_headroom = [r for r in live if len(r.inflight) < cap]
        if not with_headroom:
            return None
        # Rendezvous hash over the LIVE set: stable while the set is,
        # minimal movement when the autoscaler changes it.
        key = self._affinity_key(fr)
        preferred = max(
            live,
            key=lambda r: zlib.crc32(f"{key}|{r.name}".encode()),
        )
        if len(preferred.inflight) < cap:
            self.affinity_hits += 1
            return preferred
        self.affinity_misses += 1
        return min(with_headroom, key=lambda r: len(r.inflight))

    def _dispatch(self) -> bool:
        moved = False
        while True:
            with self._lock:
                ts = self._next_tenant(self.clock())
                if ts is None:
                    break
                fr = ts.queue[0]
            rep = self._pick_replica(fr)
            if rep is None:
                break
            with self._lock:
                ts.queue.popleft()
                self._vtime = max(self._vtime, fr.start_tag)
                self._inflight_tokens += fr.cost
                self._queued_prefill_tokens -= (
                    len(fr.prompt) + len(fr.emitted)
                )
                self._queued_decode_tokens -= fr.remaining
                # Read under the same lock submit() mutates it under.
                popular = (
                    fr.prefix_key is not None
                    and self._prefix_seen.get(fr.prefix_key, 0) >= 2
                )
                # High-water starvation lag is tracked HERE — vtime
                # only moves on dispatch, so sampling it in the
                # throttled export would miss any spike that drains
                # between exports and make the recorded
                # fabric_wfq_max_lag_tokens export-phase-dependent.
                for other in self._tenants.values():
                    if other.queue:
                        lag = (
                            self._vtime - other.queue[0].finish_tag
                        ) * other.spec.weight
                        if lag > self.max_lag_tokens:
                            self.max_lag_tokens = lag
            now = self.clock()
            if fr.t_dispatch is None:
                fr.t_dispatch = now
                # The queued (root) span closes at FIRST dispatch; an
                # evacuation re-dispatch must not re-record it under
                # the same span id.
                trace.record_span(
                    "serving.request.queued", fr.t_submit, now,
                    self_ctx=fr.trace_ctx,
                    attrs={"rid": fr.rid, "tenant": fr.tenant},
                )
            prompt = (
                np.concatenate([fr.prompt, fr.emitted])
                if len(fr.emitted) else fr.prompt
            )
            rep.inflight[fr.rid] = fr
            fr.replicas.append(rep.name)
            if fr.sample_seed is None:
                # The engine-wide seed, captured at FIRST dispatch:
                # with the router-assigned serial it is the journaled
                # sampling schedule a cross-replica resume pins.
                fr.sample_seed = getattr(
                    getattr(rep.engine, "ec", None), "sample_seed", None
                )
            # Write-ahead: the journal entry must cover this dispatch
            # BEFORE the engine can touch the request — a death at any
            # later point finds everything needed to rebuild.
            self.journal.record(fr, rep.name)
            # Prefix sharing (ISSUE 15): stamp the engine's COW fields
            # once the prefix digest is popular (>= 2 submissions). The
            # digest is over fr.prompt — a resumed sequence's folded
            # emitted tokens ride AFTER the prefix, so its prefix
            # tokens still match the registered entry and the resume
            # RE-ATTACHES via incref instead of re-materializing
            # private pages.
            with trace.span(
                "serving.request.dispatch", ctx=fr.trace_ctx,
                attrs={"rid": fr.rid, "replica": rep.name},
            ):
                rep.submit(Request(
                    rid=fr.rid, prompt=prompt, max_new_tokens=fr.remaining,
                    # A resumed sequence whose first token already
                    # happened on the drained replica must not
                    # re-observe the engine's TTFT histogram with a
                    # near-zero sample.
                    ttft_preobserved=fr.t_first is not None,
                    prefix_id=fr.prefix_key if popular else None,
                    prefix_len=min(
                        self.config.affinity_prefix_tokens,
                        len(fr.prompt),
                    ) if popular else 0,
                    # Pin the journaled sampling schedule: a sampled
                    # sequence resumed on ANY replica replays the same
                    # (seed, serial, position) keys (ISSUE 16).
                    sample_seed=fr.sample_seed,
                    sample_serial=fr.sample_serial,
                ))
            moved = True
        return moved

    def _collect(self) -> bool:
        moved = False
        for rep in self.replicas:
            while rep.outbox:
                c = rep.outbox.popleft()
                fr = rep.inflight.pop(c.rid, None)
                if fr is None or fr.rid in self.completions:
                    # Not ours anymore: the rid was journal-recovered
                    # onto another replica (or already completed there)
                    # after this engine raced its completion out.
                    # Exactly-once means the LATE copy is dropped.
                    self.duplicates_dropped += 1
                    if self.metrics is not None:
                        self.metrics.inc(
                            "fabric_duplicates_dropped_total"
                        )
                    continue
                tokens = (
                    np.concatenate([fr.emitted, c.tokens])
                    if len(fr.emitted) else np.asarray(c.tokens)
                )
                t_first = (
                    fr.t_first if fr.t_first is not None
                    else c.t_first_token
                )
                self.completions[fr.rid] = FabricCompletion(
                    rid=fr.rid, tenant=fr.tenant, tokens=tokens,
                    t_submit=fr.t_submit, t_first_token=t_first,
                    t_done=c.t_done, replicas=fr.replicas,
                )
                if fr.trace_ctx is not None and t_first is not None:
                    # Retroactive engine-side stages (the completion is
                    # the first moment the router knows them): prefill
                    # = dispatch -> first token, first_token = the TTFT
                    # span the fabric SLO quantiles measure.
                    if fr.t_dispatch is not None:
                        trace.record_span(
                            "serving.request.prefill",
                            fr.t_dispatch, t_first, ctx=fr.trace_ctx,
                            attrs={"rid": fr.rid,
                                   "replica": fr.replicas[0]
                                   if fr.replicas else ""},
                        )
                    trace.record_span(
                        "serving.request.first_token",
                        fr.t_submit, t_first, ctx=fr.trace_ctx,
                        attrs={"rid": fr.rid, "tenant": fr.tenant},
                    )
                ts = self._tenants[fr.tenant]
                if self.metrics is not None and t_first is not None:
                    # The SLO engine's per-class series (ISSUE 14):
                    # submitted -> first-token, keyed by SLO CLASS (3
                    # classes, bounded cardinality — per-tenant would
                    # explode under tenant churn). fleetmon's catalog
                    # evaluates ttft-p99-<cls> against the rendered
                    # {cls=,quantile="0.99"} quantile of this summary.
                    self.metrics.observe(
                        "fabric_ttft_seconds",
                        max(t_first - fr.t_submit, 0.0),
                        labels={"cls": ts.spec.slo.name},
                    )
                with self._lock:
                    ts.served_tokens += len(tokens)
                    self._backlog_tokens -= fr.cost
                    self._inflight_tokens -= fr.cost
                    self._in_system -= 1
                self.journal.close(fr.rid)
                moved = True
        return moved

    # --- live KV migration (ISSUE 17) ---

    def _decode_pool(self) -> List[Replica]:
        return [
            r for r in self.live_replicas()
            if r.role == "decode"
            and not self.breaker.is_open(r.claim_name or r.name)
        ]

    def _collect_migrations(self) -> bool:
        """Drain both migration mailboxes: exports coming OFF prefill
        replicas enter the waiting room (journal updated FIRST — from
        this moment a crash anywhere replays ``prompt + emitted`` by
        re-prefill, losing and duplicating nothing), and import results
        coming back from decode replicas settle as shipped (pages
        grafted, decode resumed) or fall back to re-prefill dispatch."""
        moved = False
        has_decode = bool(self._decode_pool())
        now = self.clock()
        for rep in self.replicas:
            if rep.role == "prefill":
                rep.export_enabled = has_decode and not rep.quiesced  # lint: disable=R200 (GIL-atomic bool gate read by the engine thread before each export batch)
            while rep.migration_outbox:
                sx = rep.migration_outbox.popleft()
                fr = rep.inflight.pop(sx.req.rid, None)
                if fr is None:
                    # Journal-recovered off this replica already (the
                    # death path owns it); the extent is just pages —
                    # dropping it loses nothing.
                    continue
                if len(sx.emitted):
                    fr.emitted = np.concatenate([fr.emitted, sx.emitted])
                if fr.t_first is None:
                    fr.t_first = sx.t_first
                # Crash-safety line: the journal's emitted-so-far is
                # current BEFORE the extent travels anywhere, so a death
                # mid-transfer (source already released its pages) falls
                # back to journal replay — re-prefill, token-identical
                # under the pinned (seed, serial, position) schedule.
                self.journal.note_progress(fr.rid, fr.emitted, fr.t_first)
                with self._lock:
                    self._inflight_tokens -= fr.cost
                    self._queued_decode_tokens += fr.remaining
                self._migrating.append((sx, fr, now))  # lint: disable=R200 (control-thread-owned: every reader/writer of the migration waiting room and counters runs on the single poll() thread)
                moved = True
            while rep.import_results:
                sx, ok, t0 = rep.import_results.popleft()
                fr = rep.inflight.get(sx.req.rid)
                if fr is None:
                    continue  # reclaimed by a death in between
                if ok:
                    dt = now - t0
                    self.kv_migrations["shipped"] = (  # lint: disable=R200 (control-thread-owned: every reader/writer of the migration waiting room and counters runs on the single poll() thread)
                        self.kv_migrations.get("shipped", 0) + 1
                    )
                    self.kv_migrated_pages += sx.extent.n_pages
                    self.migration_seconds.append(dt)
                    if self.metrics is not None:
                        self.metrics.inc(
                            "fabric_kv_migrations_total",
                            labels={"outcome": "shipped"},
                        )
                        self.metrics.inc(
                            "fabric_kv_migrated_pages_total",
                            float(sx.extent.n_pages),
                        )
                        self.metrics.observe(
                            "fabric_kv_migration_seconds", dt
                        )
                    trace.record_span(
                        "serving.request.migrate", t0, now,
                        ctx=fr.trace_ctx,
                        attrs={
                            "rid": fr.rid, "to_replica": rep.name,
                            "pages": int(sx.extent.n_pages),
                        },
                    )
                else:
                    # Capacity race on the destination: the sequence is
                    # NOT lost — it re-enters the WFQ front and the next
                    # dispatch re-prefills prompt + emitted.
                    rep.inflight.pop(sx.req.rid)
                    with self._lock:
                        self._inflight_tokens -= fr.cost
                    self._migration_fallback(fr)
                moved = True
        return moved

    def _dispatch_migrations(self) -> bool:
        """Move the waiting room onto decode replicas with headroom.
        With no decode pool at all (scaled away, all dead), waiting
        would deadlock — every extent falls back to re-prefill."""
        moved = False
        cap = self.config.max_inflight_per_replica
        while self._migrating:
            pool = self._decode_pool()
            if not pool:
                sx, fr, _t0 = self._migrating.popleft()  # lint: disable=R200 (control-thread-owned: every reader/writer of the migration waiting room and counters runs on the single poll() thread)
                with self._lock:
                    self._queued_decode_tokens -= fr.remaining
                self._migration_fallback(fr)
                moved = True
                continue
            with_headroom = [r for r in pool if len(r.inflight) < cap]
            if not with_headroom:
                break  # decode pool full: extents wait, pages in hand
            sx, fr, t0 = self._migrating.popleft()  # lint: disable=R200 (control-thread-owned: every reader/writer of the migration waiting room and counters runs on the single poll() thread)
            rep = min(with_headroom, key=lambda r: len(r.inflight))
            rep.inflight[fr.rid] = fr
            fr.replicas.append(rep.name)
            with self._lock:
                self._queued_decode_tokens -= fr.remaining
                self._inflight_tokens += fr.cost
            # Write-ahead, like _dispatch: the journal names the decode
            # replica BEFORE its engine can touch the extent.
            self.journal.record(fr, rep.name)
            rep.submit_extent(sx, t0)
            moved = True
        return moved

    def _migration_fallback(self, fr: _FabricReq) -> None:
        """Re-prefill fallback: splice the sequence back at its
        tenant's queue front (virtual cost charged at first dispatch —
        re-entry is free). The journal already carries every emitted
        token, so nothing is lost and _collect's duplicate drop keeps
        exactly-once intact."""
        self.kv_migrations["fallback"] = (  # lint: disable=R200 (control-thread-owned: every reader/writer of the migration waiting room and counters runs on the single poll() thread)
            self.kv_migrations.get("fallback", 0) + 1
        )
        if self.metrics is not None:
            self.metrics.inc(
                "fabric_kv_migrations_total",
                labels={"outcome": "fallback"},
            )
        ts = self._tenants[fr.tenant]
        with self._lock:
            fr.start_tag = fr.finish_tag = self._vtime
            ts.queue.appendleft(fr)
            self._queued_prefill_tokens += (
                len(fr.prompt) + len(fr.emitted)
            )
            self._queued_decode_tokens += fr.remaining

    # --- evacuation splice (autoscaler scale-down) ---

    def requeue_evacuated(self, rep: Replica) -> int:  # thread: control
        """Fold a drained replica's evacuated sequences back into the
        WFQ at the FRONT of their tenants' queues (they already waited
        their fair turn once — their virtual cost was charged at first
        dispatch, so re-entry is free and immediate). The next dispatch
        prefills ``prompt + emitted`` on another replica; completions
        splice transparently (_collect concatenates)."""
        # Sequences that FINISHED before the drain landed are sitting in
        # the outbox; collect them first so inflight holds exactly the
        # evacuated set.
        self._collect()
        n = 0
        for ev in rep.take_evacuated():
            fr = rep.inflight.pop(ev.req.rid)
            if len(ev.emitted):
                fr.emitted = np.concatenate([fr.emitted, ev.emitted])
            if fr.t_first is None:
                fr.t_first = ev.t_first
            # Keep the journal's emitted-so-far current: a death after
            # this drain must not replay tokens the drain preserved.
            self.journal.note_progress(fr.rid, fr.emitted, fr.t_first)
            t_evac = self.clock()
            ts = self._tenants[fr.tenant]
            with self._lock:
                fr.start_tag = fr.finish_tag = self._vtime
                ts.queue.appendleft(fr)
                self._inflight_tokens -= fr.cost
                self._queued_prefill_tokens += (
                    len(fr.prompt) + len(fr.emitted)
                )
                self._queued_decode_tokens += fr.remaining
            if fr.trace_ctx is not None:
                # The span covers the HAND-BACK + front-splice only
                # (the taxonomy's "evacuate" stage) — the sequence's
                # whole residence on the drained replica belongs to
                # its prefill/decode stages, not this one.
                trace.record_span(
                    "serving.request.evacuate", t_evac, self.clock(),
                    ctx=fr.trace_ctx,
                    attrs={
                        "rid": fr.rid, "from_replica": rep.name,
                        "emitted": int(len(fr.emitted)),
                    },
                )
            n += 1
        return n

    # --- observability ---

    def tenant_stats(self) -> Dict[str, dict]:  # thread: any (lock-guarded read)
        out = {}
        with self._lock:
            for name, ts in self._tenants.items():
                out[name] = {
                    "queued": len(ts.queue),
                    "served_tokens": ts.served_tokens,
                    "rejected": ts.rejected,
                    "weight": ts.spec.weight,
                    "slo": ts.spec.slo.name,
                }
        return out

    def _export(self) -> None:
        if self.metrics is None:
            return
        m = self.metrics
        with self._lock:
            m.set_gauge("fabric_backlog_tokens", self._backlog_tokens)
            m.set_gauge("fabric_in_system_sequences", self._in_system)
            m.set_gauge("fabric_replicas", len(self.live_replicas()))
            # Degradation fraction (ISSUE 16): owed/(live+owed) — 0 in
            # a healthy fabric, climbing toward 1 as deaths outpace
            # replacement. fleetmon burn-rates it (fabric-degraded).
            owed = self._capacity_owed
            live = len(self.replicas)
            m.set_gauge(
                "fabric_degraded",
                owed / float(live + owed) if owed else 0.0,
            )
            m.set_gauge(
                "fabric_circuit_open",
                float(len(self.breaker.open_keys())),
            )
            # Per-phase backlog + migration waiting room (ISSUE 17):
            # the autoscaler's pool-sizing signals, and the doctor's
            # imbalance / migration-backlog probes.
            m.set_gauge(
                "fabric_queued_prefill_tokens",
                self._queued_prefill_tokens,
            )
            m.set_gauge(
                "fabric_queued_decode_tokens",
                self._queued_decode_tokens,
            )
            m.set_gauge(
                "fabric_migration_backlog", float(len(self._migrating))
            )
            roles = {"prefill": 0, "decode": 0, "both": 0}
            for r in self.live_replicas():
                roles[r.role] = roles.get(r.role, 0) + 1
            for role, count in roles.items():
                m.set_gauge(
                    "fabric_phase_replicas", float(count),
                    labels={"phase": role},
                )
            for name, ts in self._tenants.items():
                # Starvation lag (weighted tokens): how far the fabric
                # clock ran past a backlogged tenant's head turn. Near
                # zero in a healthy WFQ; growth = this tenant is owed
                # service others received (the doctor's signal).
                lag = 0.0
                if ts.queue:
                    lag = max(
                        0.0,
                        (self._vtime - ts.queue[0].finish_tag)
                        * ts.spec.weight,
                    )
                self.max_lag_tokens = max(self.max_lag_tokens, lag)
                m.set_gauge(
                    "fabric_tenant_vtime_lag", lag,
                    labels={"tenant": name},
                )
                m.set_gauge(
                    "fabric_tenant_queued", float(len(ts.queue)),
                    labels={"tenant": name},
                )
