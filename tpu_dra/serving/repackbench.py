"""Elastic-repacker bench + CPU smoke — ``make repackbench`` (wired
into ``ci``), and the measurement core behind ``bench.py --leg-repack``
(ISSUE 12).

Two measured phases, both over the shared synthetic fleet
(:mod:`tpu_dra.scheduler.fleet`) published through the driver's real
publisher and allocated by the real scheduler:

1. **Serving drill (packed-vs-fragmented tok/s)** — small fleet, real
   TINY-model engines on CPU. Churn strands the grid: five 1x1 replicas
   pack four onto node A and spill one to node B; scaling three of A's
   away leaves ONE resident per node, so a pending 2x2 claim (a bigger
   replica) is Unschedulable despite six free chips. Aggregate tok/s is
   measured on the fragmented fleet, then the repacker — leader, live
   tenants — migrates a resident mid-generation (PR-11 evacuation:
   drain, requeue-at-front, token-identical greedy resume), the 2x2
   places on the emptied node, and the same trace is re-measured.
   Gates: ``repack_tok_s_gain`` > 1 (more serving capacity reachable
   after defrag), zero lost/duplicated sequences across the migration,
   and completions TOKEN-IDENTICAL to an uninterrupted reference.

2. **Repack storm (claim-ready p99 inside the PR-10 SLO)** — fleet
   scale, no engines. A fill wave + name-keyed churn fragments the
   fleet; the repacker (REAL Lease-based leader election over the same
   cluster, disruption-budgeted ``max_concurrent_migrations``) storms
   migrations WHILE an open-loop claim wave arrives; claim-submitted →
   prepared p99 (the fleetsim KubeletSim stamp) is measured against an
   identical quiet run. Gates: migrations happened, fragmentation
   strictly dropped, and the storm p99 stays inside the pinned bound
   of the quiet p99.

Knobs (env): REPACK_NODES, REPACK_FILL, REPACK_WAVE, REPACK_RATE,
REPACK_CHURN, REPACK_SEED, REPACK_ALLOW_GAP.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

from tpu_dra.infra.flags import LeaderElectionConfig
from tpu_dra.infra.leaderelection import LeaderElector
from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import RESOURCE_CLAIMS, ResourceClient
from tpu_dra.k8sclient.fake import FakeCluster
from tpu_dra.scheduler import fleet
from tpu_dra.scheduler.core import SchedulerCore
from tpu_dra.scheduler.repacker import Repacker, RepackerConfig
from tpu_dra.serving.autoscaler import AutoscalerConfig
from tpu_dra.serving.fabricbench import (
    Fabric,
    TenantTraffic,
    make_fabric_trace,
    _model,
    warm_jit,
)
from tpu_dra.serving.repack import FabricRepackAdapter
from tpu_dra.serving.router import INTERACTIVE, Replica, RouterConfig, TenantSpec
from tpu_dra.tools.fleetsim import KubeletSim, spin_fleet
from tpu_dra.workloads.engine import Engine, EngineConfig

NS = "fabric"


def _note(msg: str) -> None:
    print(f"repackbench: {msg}", file=sys.stderr)


def _pct(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[int(q * (len(s) - 1))]


# --- phase 1: serving drill --------------------------------------------------


def _engine_config(slots: int) -> EngineConfig:
    return EngineConfig(
        page_size=8, max_slots=slots, max_pages_per_seq=8,
        scan_chunk=4, prefill_chunk=16,
    )


def run_serving_drill(seed: int, timeout: float = 300.0) -> dict:
    config, params = _model()
    gold = TenantSpec("gold", INTERACTIVE, weight=1.0)
    small_ec = _engine_config(slots=4)
    big_ec = _engine_config(slots=8)  # the 2x2 replica: 4x the chips
    warm_jit(config, params, small_ec)
    warm_jit(config, params, big_ec)
    fab = Fabric(
        2, [gold], config, params, small_ec,
        RouterConfig(backlog_cap_tokens=1e9, max_inflight_per_replica=8),
        AutoscalerConfig(min_replicas=5, max_replicas=5),
    )

    def frag_of() -> float:
        return fab.core._snapshot_allocator().fragmentation()["frag_score"]

    try:
        fab.scale_to(5)
        # Churn: retire three of the four replicas the packer co-located
        # (every replica claim on the fuller node but one) — the
        # scale-in pattern that strands both nodes with one resident
        # each. The pending 2x2 then fits NOWHERE despite 6 free chips.
        by_node: Dict[str, List[Replica]] = {}
        for rep in list(fab.router.replicas):
            res = rep.claim["status"]["allocation"]["devices"]["results"]
            by_node.setdefault(res[0]["pool"], []).append(rep)
        full_node = max(by_node, key=lambda n: len(by_node[n]))
        assert len(by_node[full_node]) == 4, (
            f"packer spread the replicas unexpectedly: "
            f"{ {n: len(v) for n, v in by_node.items()} }"
        )
        for rep in by_node[full_node][:3]:
            fab.router.remove_replica(rep)
            rep.stop()
            fab.claims.delete(rep.claim_name, NS)
        big_claim = fleet.make_claim(0, "2x2x1")
        big_claim["metadata"] = {"name": "big-0000", "namespace": NS}
        fab.claims.create(big_claim)
        time.sleep(1.0)  # scheduler sweep: must stay Unschedulable
        assert not (
            (fab.claims.try_get("big-0000", NS) or {}).get("status") or {}
        ).get("allocation"), (
            "the 2x2 claim placed on the fragmented fleet — the drill "
            "needs it stranded"
        )
        frag_before = frag_of()
        assert frag_before > 0.05, f"fleet not fragmented: {frag_before}"

        def trace(prefix: str, n: int = 48):
            tt = TenantTraffic(
                gold, requests=n, rate_rps=400.0,
                prompt_lens=[4, 8], output_lens=[8, 12],
            )
            out = make_fabric_trace(seed, [tt], config.vocab_size)
            return [
                (t, tn, dataclasses.replace(r, rid=f"{prefix}-{r.rid}"), s)
                for (t, tn, r, s) in out
            ]

        def tok_s(prefix: str, wall: float) -> float:
            toks = sum(
                len(c.tokens) for rid, c in fab.router.completions.items()
                if rid.startswith(prefix)
            )
            return toks / max(wall, 1e-9)

        # Phase A: the fragmented fleet (2 small replicas).
        res_a = fab.drive(trace("fragA"), timeout=timeout)
        tok_frag = tok_s("fragA", res_a["wall_s"])

        # Converge: repacker migrates a resident MID-GENERATION while a
        # second trace is in flight; the 2x2 places; the big replica
        # binds through the same claim-watch pattern the autoscaler
        # uses.
        adapter = FabricRepackAdapter(fab.router, fab._make_replica)
        repacker = Repacker(
            fab.cluster,
            RepackerConfig(
                poll_period=0.2, frag_threshold=0.05,
                min_disruption_interval_seconds=2.0,
                drain_timeout_seconds=20.0,
            ),
            index=fab.core.index,
            serving=adapter,
            utilization=adapter.utilization,
            metrics=fab.metrics,
        )
        bound = {}

        def bind_big_when_placed():
            repacker.tick()
            if "big" in bound:
                return
            cur = fab.claims.try_get("big-0000", NS)
            if cur and (cur.get("status") or {}).get("allocation"):
                eng = Engine(config, params, big_ec)
                rep = Replica("big-0000", eng, claim_name="big-0000",
                              claim=cur)
                rep.start()
                fab.router.add_replica(rep)
                bound["big"] = rep

        fab.drive(
            trace("mid"), timeout=timeout, extra_tick=bind_big_when_placed
        )
        deadline = time.monotonic() + 60
        while ("big" not in bound or repacker._active) and (
            time.monotonic() < deadline
        ):
            bind_big_when_placed()
            fab.router.poll()
            time.sleep(0.01)
        assert repacker.migrations >= 1, "repacker never migrated"
        assert "big" in bound, (
            "the 2x2 claim never placed after defrag — repack did not "
            "free a whole node"
        )
        frag_after = frag_of()

        # Lossless + token-identical across the migration: every mid-
        # trace request completed exactly once, and greedy tokens match
        # an uninterrupted single-engine reference.
        mids = [r for (_t, _tn, r, _s) in trace("mid")]
        done = fab.router.completions
        missing = [r.rid for r in mids if r.rid not in done]
        assert not missing, f"sequences lost across the migration: {missing}"
        ref = Engine(config, params, _engine_config(slots=4)).run(
            [dataclasses.replace(r) for r in mids]
        )
        mismatch = [
            r.rid for r in mids
            if not np.array_equal(done[r.rid].tokens, ref[r.rid].tokens)
        ]
        assert not mismatch, (
            f"migration diverged from the uninterrupted reference on "
            f"{mismatch}"
        )

        # Phase B: the packed fleet (2 small + the 2x2 replica).
        res_b = fab.drive(trace("packB"), timeout=timeout)
        tok_packed = tok_s("packB", res_b["wall_s"])
        gain = tok_packed / max(tok_frag, 1e-9)
        _note(
            f"serving drill: {tok_frag:.1f} tok/s fragmented -> "
            f"{tok_packed:.1f} tok/s packed (x{gain:.2f}); frag "
            f"{frag_before} -> {frag_after}; migrations "
            f"{repacker.migrations}, requeued mid-flight >= 1: "
            f"{adapter.rebinds} rebinds"
        )
        return {
            "tok_s_fragmented": round(tok_frag, 1),
            "tok_s_packed": round(tok_packed, 1),
            "tok_s_gain": round(gain, 3),
            "frag_before": frag_before,
            "frag_after": frag_after,
            "migrations": repacker.migrations,
            "aborted": repacker.aborted,
            "rebinds": adapter.rebinds,
        }
    finally:
        fab.stop()


# --- phase 2: repack storm at fleet scale ------------------------------------


class StormRun:
    """Fill + churn a fleet, then measure claim-submitted -> prepared
    latency of an open-loop wave — with or without a concurrent repack
    storm (REAL leader-elected repacker over the same cluster)."""

    def __init__(self, nodes: int, prepare_ms: float = 1.0):
        self.metrics = Metrics()
        self.cluster = FakeCluster()
        self.agents = spin_fleet(self.cluster, nodes, self.metrics)
        self.core = SchedulerCore(self.cluster, retry_unschedulable_after=0.3)
        self.kubelet = KubeletSim(
            self.cluster, self.metrics, sharded=True, prepare_ms=prepare_ms
        )
        self.claims = ResourceClient(self.cluster, RESOURCE_CLAIMS)
        self.core.start()
        self.kubelet.start()
        deadline = time.monotonic() + 60
        for inf in (
            self.core.claim_informer, self.core.slice_informer,
            self.core.class_informer, self.kubelet.informer,
        ):
            if not inf.wait_for_sync(timeout=deadline - time.monotonic()):
                raise RuntimeError("informer sync timed out")
        self.repacker: Optional[Repacker] = None

    def frag(self) -> float:
        return self.core._snapshot_allocator().fragmentation()["frag_score"]

    def fill_and_churn(self, fill: int, churn: float, seed: int) -> None:
        # All-1x1 fill to capacity: the packer co-locates mixed shapes
        # so well that churn over them rarely strands anything — but a
        # single-filled fleet churned hard leaves many ONE-resident
        # nodes (3 free chips, largest reachable placement 2), the
        # stranding pattern the repacker exists to clean up.
        for i in range(fill):
            c = fleet.make_claim(i, "1x1x1")
            c["metadata"]["name"] = f"fill-{i:05d}"
            c["metadata"].pop("uid", None)
            self.claims.create(c)
        # Wait for the fill to settle (break early when everything
        # placed; a deliberately-overfull fleet just proceeds — churn
        # frees the room either way).
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            snapshot = self.claims.list()
            pending = [
                c for c in snapshot
                if not (c.get("status") or {}).get("allocation")
            ]
            if not pending:
                break
            time.sleep(0.05)
        # Name-keyed churn (same set either mode): the scale-in wave
        # that strands capacity.
        for claim in self.claims.list():
            name = claim["metadata"]["name"]
            if (zlib.crc32(name.encode()) % 100) < churn * 100:
                try:
                    self.claims.delete(
                        name, claim["metadata"].get("namespace")
                    )
                except Exception:  # noqa: BLE001 — already gone
                    pass
        time.sleep(0.3)

    def start_repacker(self) -> Repacker:
        elector = LeaderElector(self.cluster, LeaderElectionConfig(
            enabled=True, lease_name="tpu-dra-repacker",
            lease_duration=15.0, renew_deadline=10.0, retry_period=0.1,
        ))
        self.repacker = Repacker(
            self.cluster,
            RepackerConfig(
                poll_period=0.25, frag_threshold=0.02,
                max_concurrent_migrations=4,
                min_disruption_interval_seconds=1.0,
                max_candidates_per_poll=8,
            ),
            index=self.core.index,
            metrics=self.metrics,
            elector=elector,
        )
        self.repacker.start()
        deadline = time.monotonic() + 30
        while not self.repacker.is_leader and time.monotonic() < deadline:
            time.sleep(0.01)
        assert self.repacker.is_leader, "repacker never acquired the Lease"
        return self.repacker

    def run_wave(self, wave: int, rate: float, seed: int,
                 timeout: float = 300.0) -> dict:
        import random

        rng = random.Random(seed ^ 0xEE12)
        submit_times: Dict[str, float] = {}
        t_next = time.monotonic()
        for i in range(wave):
            # 1x1 arrivals only: a single chip can never be stranded by
            # fragmentation, so the QUIET baseline is guaranteed to
            # drain and the two modes measure the same schedulable
            # work — the storm's p99 delta is pure control-plane
            # contention (allocation + prepare under migration churn),
            # which is exactly what the SLO gate is about. (Whether
            # defrag unblocks LARGE shapes is the serving drill's gate.)
            c = fleet.make_claim(i, "1x1x1")
            c["metadata"]["name"] = f"wave-{i:05d}"
            c["metadata"].pop("uid", None)
            t_next += rng.expovariate(rate)
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            submit_times[c["metadata"]["name"]] = time.monotonic()
            self.claims.create(c)
        deadline = time.monotonic() + timeout
        want = set(submit_times)
        while time.monotonic() < deadline:
            with self.kubelet._lock:
                ready = {
                    n: t for n, (t, _e) in self.kubelet.ready.items()
                    if n in want
                }
            if len(ready) == len(want):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError(
                f"wave never drained: {len(want) - len(ready)} claims "
                f"not ready"
            )
        lat_ms = [
            (ready[n] - submit_times[n]) * 1000.0 for n in want
        ]
        return {
            "claims": len(want),
            "p50_ms": round(_pct(lat_ms, 0.5), 2),
            "p99_ms": round(_pct(lat_ms, 0.99), 2),
        }

    def stop(self) -> None:
        if self.repacker is not None:
            self.repacker.stop()
        self.kubelet.stop()
        self.core.stop()


def run_storm(
    nodes: int, fill: int, wave: int, rate: float, churn: float, seed: int,
) -> dict:
    out: dict = {}
    for label, repack in (("quiet", False), ("storm", True)):
        run = StormRun(nodes)
        try:
            run.fill_and_churn(fill, churn, seed)
            frag_before = run.frag()
            if repack:
                run.start_repacker()
            res = run.run_wave(wave, rate, seed)
            # Let in-flight migrations land before reading the end
            # state (the wave drain does not wait on the repacker).
            if repack:
                deadline = time.monotonic() + 30
                while run.repacker._active and time.monotonic() < deadline:
                    time.sleep(0.05)
            frag_after = run.frag()
            out[label] = {
                **res,
                "frag_before": frag_before,
                "frag_after": frag_after,
                "migrations": run.repacker.migrations if repack else 0,
                "aborted": run.repacker.aborted if repack else 0,
                "deferred": run.repacker.deferred if repack else 0,
            }
            _note(
                f"storm[{label}]: claim-ready p50 {res['p50_ms']} ms "
                f"p99 {res['p99_ms']} ms; frag {frag_before} -> "
                f"{frag_after}; migrations {out[label]['migrations']}"
            )
        finally:
            run.stop()
    return out


# --- entry points ------------------------------------------------------------


def run(
    nodes: int, fill: int, wave: int, rate: float, churn: float, seed: int,
    smoke: bool = False,
) -> dict:
    serving = run_serving_drill(seed)
    storm = run_storm(nodes, fill, wave, rate, churn, seed)

    report = {
        "repack_nodes": nodes,
        "repack_frag_before": storm["storm"]["frag_before"],
        "repack_frag_after": storm["storm"]["frag_after"],
        "repack_migrations": (
            storm["storm"]["migrations"] + serving["migrations"]
        ),
        "repack_aborted": storm["storm"]["aborted"] + serving["aborted"],
        "repack_deferred": storm["storm"]["deferred"],
        "repack_tok_s_fragmented": serving["tok_s_fragmented"],
        "repack_tok_s_packed": serving["tok_s_packed"],
        "repack_tok_s_gain": serving["tok_s_gain"],
        "repack_serve_frag_before": serving["frag_before"],
        "repack_serve_frag_after": serving["frag_after"],
        "repack_quiet_claim_ready_p99_ms": storm["quiet"]["p99_ms"],
        "repack_storm_claim_ready_p99_ms": storm["storm"]["p99_ms"],
        "repack_storm_p99_x": round(
            storm["storm"]["p99_ms"]
            / max(storm["quiet"]["p99_ms"], 1e-9),
            3,
        ),
        "seed": seed,
    }

    allow_gap = os.environ.get("REPACK_ALLOW_GAP") == "1"
    # Hard contract, both sizes: the repacker ACTED — in the STORM
    # itself, not just the serving drill — and the fleet got strictly
    # less fragmented; the serving drill's gates (lossless,
    # token-identical, 2x2 placed) already ran inside
    # run_serving_drill.
    assert storm["storm"]["migrations"] >= 1, (
        "the repack storm never migrated anything — the churned fleet "
        "was not fragmented enough or the repacker never led"
    )
    assert (
        report["repack_frag_after"] < report["repack_frag_before"]
    ), (
        f"repack storm did not reduce fragmentation: "
        f"{report['repack_frag_before']} -> {report['repack_frag_after']}"
    )
    if not allow_gap:
        # Gate (a): packed serving capacity beats fragmented.
        assert report["repack_tok_s_gain"] > 1.0, (
            f"packed fleet is not faster: x{report['repack_tok_s_gain']} "
            f"(REPACK_ALLOW_GAP=1 to bypass on a hostile machine)"
        )
        # Gate (b): the PR-10 claim-ready SLO survives the repack storm
        # — p99 within the pinned bound of the quiet baseline (an
        # absolute floor keeps small-scale jitter from tripping it).
        ratio_ok = report["repack_storm_p99_x"] <= 3.0
        floor_ok = report["repack_storm_claim_ready_p99_ms"] <= 1500.0
        assert ratio_ok or floor_ok, (
            f"claim-ready p99 blew the SLO during the repack storm: "
            f"{report['repack_storm_claim_ready_p99_ms']} ms vs quiet "
            f"{report['repack_quiet_claim_ready_p99_ms']} ms "
            f"(x{report['repack_storm_p99_x']}; REPACK_ALLOW_GAP=1 to "
            f"bypass)"
        )
    if smoke:
        _note(
            "smoke contract: migrations happened, frag strictly dropped, "
            f"tok/s gain x{report['repack_tok_s_gain']}, storm p99 "
            f"x{report['repack_storm_p99_x']} of quiet, lossless "
            "token-identical mid-generation migration — all hold"
        )
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser("repackbench", description=__doc__)
    p.add_argument(
        "--smoke", action="store_true",
        help="CI size: small fleet/trace + the hard contract asserts",
    )
    args = p.parse_args(argv)
    env = os.environ.get
    if args.smoke:
        # Fill = chip capacity (nodes x 4, all 1x1): churn then leaves
        # lone residents stranding their nodes — the storm's raw
        # material (see StormRun.fill_and_churn).
        nodes = int(env("REPACK_NODES", "24"))
        fill = int(env("REPACK_FILL", str(24 * 4)))
        wave = int(env("REPACK_WAVE", "24"))
        rate = float(env("REPACK_RATE", "60"))
    else:
        nodes = int(env("REPACK_NODES", "512"))
        fill = int(env("REPACK_FILL", str(512 * 4)))
        wave = int(env("REPACK_WAVE", "300"))
        rate = float(env("REPACK_RATE", "120"))
    churn = float(env("REPACK_CHURN", "0.7"))
    seed = int(env("REPACK_SEED", "20260804"))
    report = run(nodes, fill, wave, rate, churn, seed, smoke=args.smoke)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
