"""Serving-fabric adapter for the elastic repacker (ISSUE 12).

The repacker (:mod:`tpu_dra.scheduler.repacker`) is a control-plane
controller: it plans and WAL's placement moves but knows nothing about
engines. This module is the serving half of a tenant-transparent
migration — the PR-11 evacuation primitive driven through the repack
protocol:

- **drain**: quiesce the victim replica (marked ``migrating`` so the
  autoscaler never picks it as a scale-down victim mid-move) and start
  the engine-thread evacuation handshake (``begin_evacuate`` →
  ``evac_done``);
- **finish_drain**: splice the evacuated sequences back into the
  router's WFQ at their tenants' queue FRONT
  (``Router.requeue_evacuated``) — they re-prefill ``prompt + emitted``
  on a surviving replica and, under greedy decoding, complete
  token-identical to an uninterrupted run;
- **rebind**: once the claim is committed at its new placement, bind a
  fresh replica to it (cheap: same ``_JIT_CACHE`` key ⇒ shared compiled
  executables) and retire the drained one;
- **abort**: roll back — requeue anything drained, un-quiesce, clear
  the migrating mark; the tenant keeps serving on the old placement.

Threading: every method runs on the fabric's CONTROL thread (the same
thread that drives ``Router.poll`` and the autoscaler) — the
repacker's ``tick()`` is called from that thread when embedded in a
fabric. The contract is enforced by the D802 lint pass via the
``# thread: control`` annotations below (see docs/static-analysis.md).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Set

from tpu_dra.scheduler.repacker import ServingAdapter
from tpu_dra.serving.router import Replica, Router


class FabricRepackAdapter(ServingAdapter):
    """``make_replica(claim) -> Replica`` binds a STARTED replica to a
    committed claim (the same callback the autoscaler uses)."""

    def __init__(
        self,
        router: Router,
        make_replica: Callable[[dict], Replica],
        clock=time.monotonic,
    ):
        self.router = router
        self.make_replica = make_replica
        self.clock = clock
        self._draining: Set[str] = set()
        self.rebinds = 0
        self.aborts = 0

    # --- lookup ---

    @staticmethod
    def _claim_name(key: str) -> str:
        return key.split("/", 1)[-1]

    def _replica(self, key: str) -> Optional[Replica]:
        name = self._claim_name(key)
        for rep in self.router.replicas:
            if rep.claim_name == name:
                return rep
        return None

    # --- the repacker protocol ---

    def begin_drain(self, key: str) -> None:  # thread: control
        rep = self._replica(key)
        if rep is None:
            return  # no live tenant behind this claim: placement-only
        rep.migrating = True
        rep.quiesced = True  # lint: disable=R200 (control-thread-only by the router's threading contract; the repacker tick runs on it)
        rep.begin_evacuate()
        self._draining.add(key)

    def drain_done(self, key: str) -> bool:  # thread: control
        rep = self._replica(key)
        return rep is None or rep.evac_done

    def finish_drain(self, key: str) -> int:  # thread: control
        rep = self._replica(key)
        if rep is None or key not in self._draining:
            return 0
        self._draining.discard(key)
        return self.router.requeue_evacuated(rep)

    def rebind(self, key: str, claim: dict) -> None:  # thread: control
        old = self._replica(key)
        new = self.make_replica(claim)
        new.claim_name = claim["metadata"]["name"]
        new.claim = claim
        self.router.add_replica(new)
        if old is not None and old is not new:
            self.router.remove_replica(old)
            old.stop()
        self.rebinds += 1

    def abort(self, key: str) -> None:  # thread: control
        rep = self._replica(key)
        if rep is None:
            return
        if key in self._draining:
            # The engine thread may still be mid-evacuate: wait for the
            # handshake fence, then splice the drained work back. Abort
            # is rare (lease loss, drain timeout) — a bounded wait on
            # the control thread beats losing sequences.
            deadline = self.clock() + 10.0
            while not rep.evac_done and self.clock() < deadline:
                time.sleep(0.005)
            self._draining.discard(key)
            if rep.evac_done:
                self.router.requeue_evacuated(rep)
        rep.quiesced = False  # lint: disable=R200 (control-thread-only, same contract as begin_drain)
        rep.migrating = False
        self.aborts += 1

    # --- the utilization signal (MISO: idle claims move first) ---

    def utilization(self) -> Dict[str, float]:  # thread: control
        """Per-claim occupancy in [0, 1]: the replica's in-flight share
        of its dispatch cap. The repacker takes this callable directly
        as its ``utilization`` signal when embedded in a fabric."""
        cap = max(1, self.router.config.max_inflight_per_replica)
        out: Dict[str, float] = {}
        for rep in self.router.replicas:
            if not rep.claim_name or rep.claim is None:
                continue
            ns = rep.claim.get("metadata", {}).get("namespace")
            out[f"{ns}/{rep.claim_name}"] = min(
                1.0, len(rep.inflight) / cap
            )
        return out
