"""Disaggregated prefill/decode bench + CPU smoke — ``make
disaggbench`` (wired into ``ci``), and the measurement core behind
``bench.py --leg-disagg`` (ISSUE 17).

The contrast this leg measures: batched chunked prefill (ISSUE 15)
still shares each engine's iterations with decode, so a prompt-heavy
burst degrades both decode ITL and prefill TTFT at once. Phase
disaggregation splits the fleet into a PREFILL pool (takes prompt
dispatches, exports each sequence's paged-KV extent at prefill
completion) and a DECODE pool (grafts migrated extents, never runs a
prefill chunk), with the handoff a live page transfer — not a
re-prefill. Both sides of the comparison run the IDENTICAL seeded
prompt-heavy trace at EQUAL chips: N colocated ("both"-role) replicas
vs the same N split across the two phase pools.

Three measured phases:

1. **parity**: a small disagg fabric where sequences migrate
   mid-generation — completions must be TOKEN-IDENTICAL to an
   uninterrupted single-engine reference, greedy AND under the pinned
   (seed, serial, position) sampled schedule, with at least one real
   shipped migration and every allocator leak-free after the drive;
2. **kill drill** (faultbench-style): the decode replica is crashed at
   the migration boundary — first poll after it holds grafted
   sequences in flight. The dispatch journal replays ``prompt +
   emitted`` by re-prefill on the survivors: zero lost, zero
   duplicated, completions still token-identical to the reference;
3. **measure**: colocated vs disaggregated on the same trace. Reports
   TTFT p50/p99 and ITL p50/p99 per side and the ratios
   ``disagg_vs_colocated_ttft`` / ``disagg_vs_colocated_itl``
   (disagg p99 over colocated p99; < 1.0 = disaggregation wins). Full
   mode gates BOTH ratios < 1.0; ``DISAGG_ALLOW_GAP=1`` bypasses on
   CPU drill sizes where queueing noise owns the quantiles.

Knobs (env): DISAGG_NODES, DISAGG_REPLICAS, DISAGG_PREFILL (pool
split), DISAGG_REQUESTS, DISAGG_RATE, DISAGG_SEED, DISAGG_SLOTS,
DISAGG_ALLOW_GAP.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import statistics
import sys
from typing import List, Optional

import numpy as np

from tpu_dra.serving.autoscaler import AutoscalerConfig
from tpu_dra.serving.fabricbench import (
    Fabric,
    _engine_config,
    _model,
    _pct,
    warm_jit,
)
from tpu_dra.serving.router import (
    INTERACTIVE,
    Replica,
    RouterConfig,
    TenantSpec,
)
from tpu_dra.workloads.engine import Engine, Request

NS = "fabric"


def _note(msg: str) -> None:
    print(f"disaggbench: {msg}", file=sys.stderr)


# --- role-partitioned fabric -------------------------------------------------


class DisaggFabric(Fabric):
    """Fabric whose bootstrap assigns phase roles from a plan: the
    first claims bound become prefill replicas, the rest decode (or
    all "both" for the colocated baseline). The autoscaler's
    disaggregated mode calls ``make_replica(claim, role)`` explicitly
    (replacement inherits the dead replica's role); bootstrap binds
    walk the plan in claim order."""

    def __init__(self, *args, roles: Optional[List[str]] = None, **kw):
        self._role_plan = list(roles or [])
        self._role_i = 0
        super().__init__(*args, **kw)

    def _make_replica(self, claim: dict, role: Optional[str] = None):
        if role is None:
            if self._role_i < len(self._role_plan):
                role = self._role_plan[self._role_i]
                self._role_i += 1
            else:
                role = "both"
        engine = Engine(self.config, self.params, self.engine_config)
        rep = Replica(
            claim["metadata"]["name"], engine,
            claim_name=claim["metadata"]["name"], claim=claim,
            metrics=self.metrics, role=role,
        )
        rep.start()
        return rep


def _mk_fabric(
    nodes, config, params, ec, slots, roles=None, sample_seed=None,
) -> DisaggFabric:
    if sample_seed is not None:
        ec = dataclasses.replace(ec, sample_seed=sample_seed)
    return DisaggFabric(
        nodes, [TenantSpec("t", INTERACTIVE, weight=1.0)],
        config, params, ec,
        RouterConfig(
            backlog_cap_tokens=1e9, max_inflight_per_replica=slots,
        ),
        AutoscalerConfig(
            min_replicas=1, max_replicas=64,
            disaggregated=roles is not None,
        ),
        roles=roles,
    )


# --- trace -------------------------------------------------------------------


def make_disagg_trace(
    seed: int, requests: int, rate_rps: float, vocab: int,
    prompt_lens, output_lens, pin_sampling: bool = False,
    sample_seed: int = 0,
):
    """Seeded prompt-heavy open-loop trace, arrival-sorted
    ``(arrival_s, tenant, Request, session)`` tuples in the fabric
    drive contract. ``pin_sampling`` stamps an explicit per-request
    (seed, serial) so the sampled trajectory is a pure function of the
    trace — identical across disagg/colocated/reference runs
    regardless of admission order."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, requests))
    out = []
    for i in range(requests):
        plen = int(rng.choice(prompt_lens))
        olen = int(rng.choice(output_lens))
        out.append((
            float(arrivals[i]), "t",
            Request(
                rid=f"d-{i:05d}",
                prompt=rng.integers(1, vocab, plen).astype(np.int32),
                max_new_tokens=olen,
                sample_seed=sample_seed if pin_sampling else None,
                sample_serial=i if pin_sampling else None,
            ),
            None,
        ))
    out.sort(key=lambda x: (x[0], x[2].rid))
    return out


def _reference_tokens(config, params, ec, trace, sample_seed=None):
    """Uninterrupted single-engine run of the trace's requests — the
    token-parity oracle both disagg phases compare against."""
    if sample_seed is not None:
        ec = dataclasses.replace(ec, sample_seed=sample_seed)
    eng = Engine(config, params, ec)
    done = eng.run([dataclasses.replace(t[2]) for t in trace])
    eng.close()
    return {rid: c.tokens for rid, c in done.items()}


def _itl_ms(completions) -> List[float]:
    """Per-sequence mean inter-token latency, decode side only: time
    from first token to done over the tokens after the first. One
    sample per completion keeps slow sequences from drowning fast ones
    (the quantile is over SEQUENCES, matching the TTFT convention)."""
    out = []
    for c in completions.values():
        n = len(c.tokens)
        if n >= 2:
            out.append((c.t_done - c.t_first_token) * 1000.0 / (n - 1))
    return sorted(out)


def _latency_report(fab: DisaggFabric) -> dict:
    ttft = sorted(
        c.ttft_s * 1000.0 for c in fab.router.completions.values()
    )
    itl = _itl_ms(fab.router.completions)
    return {
        "n": len(ttft),
        "ttft_p50_ms": round(_pct(ttft, 0.5), 2),
        "ttft_p99_ms": round(_pct(ttft, 0.99), 2),
        "itl_p50_ms": round(_pct(itl, 0.5), 2),
        "itl_p99_ms": round(_pct(itl, 0.99), 2),
        "itl_mean_ms": round(statistics.mean(itl), 2) if itl else 0.0,
    }


# --- phase 1: parity ---------------------------------------------------------


def run_parity(config, params, nodes, slots, seed, timeout) -> dict:
    """Migrated sequences are token-identical to the un-migrated
    reference — greedy AND sampled — with >= 1 real shipped migration
    and leak-free allocators on both pools."""
    ec = _engine_config(slots, max_prompt=12, max_out=24)
    warm_jit(config, params, ec)
    trace = make_disagg_trace(
        seed, requests=8, rate_rps=50.0, vocab=config.vocab_size,
        prompt_lens=[8, 12], output_lens=[16, 24],
        pin_sampling=True, sample_seed=seed,
    )
    out = {}
    for label, sample_seed in (("greedy", None), ("sampled", seed)):
        # Greedy ignores the sampling schedule — strip the pins so the
        # engines (default seed) accept the requests.
        t = trace if sample_seed is not None else [
            (a, tn, dataclasses.replace(
                r, sample_seed=None, sample_serial=None,
            ), s)
            for a, tn, r, s in trace
        ]
        ref = _reference_tokens(config, params, ec, t, sample_seed)
        fab = _mk_fabric(
            nodes, config, params, ec, slots,
            roles=["prefill", "decode"], sample_seed=sample_seed,
        )
        try:
            fab.scale_to(2)
            fab.drive(t, timeout=timeout)
            done = fab.router.completions
            assert len(done) == len(trace), (
                f"parity[{label}]: {len(done)}/{len(trace)} completed"
            )
            shipped = fab.router.kv_migrations.get("shipped", 0)
            assert shipped >= 1, (
                f"parity[{label}]: no migration ever shipped "
                f"({fab.router.kv_migrations}) — the disagg path "
                f"did not exercise"
            )
            mismatch = [
                rid for rid in ref
                if not np.array_equal(done[rid].tokens, ref[rid])
            ]
            assert not mismatch, (
                f"parity[{label}]: migrated completions diverged from "
                f"the un-migrated reference on {mismatch}"
            )
            for rep in fab.router.replicas:
                alloc = rep.engine.allocator
                assert alloc.free_pages == alloc.num_pages - 1, (
                    f"parity[{label}]: {rep.name} leaked pages "
                    f"({alloc.free_pages}/{alloc.num_pages})"
                )
                assert alloc.reserved_pages == 0
            out[label] = {
                "completed": len(done),
                "kv_migrations_shipped": shipped,
                "kv_migrations_fallback":
                    fab.router.kv_migrations.get("fallback", 0),
                "kv_migrated_pages": fab.router.kv_migrated_pages,
            }
            _note(
                f"parity[{label}]: {len(done)} token-identical, "
                f"{shipped} shipped migrations "
                f"({fab.router.kv_migrated_pages} pages)"
            )
        finally:
            fab.stop()
    return out


# --- phase 2: kill drill -----------------------------------------------------


def run_kill_drill(config, params, nodes, slots, seed, timeout) -> dict:
    """Crash the decode replica at the migration boundary (grafted
    sequences in flight): the journal replays prompt + emitted by
    re-prefill on the surviving prefill replica — zero lost, zero
    duplicated, tokens identical to the uninterrupted reference."""
    ec = _engine_config(slots, max_prompt=12, max_out=32)
    warm_jit(config, params, ec)
    trace = make_disagg_trace(
        seed + 1, requests=8, rate_rps=100.0, vocab=config.vocab_size,
        prompt_lens=[8, 12], output_lens=[24, 32],
    )
    ref = _reference_tokens(config, params, ec, trace)
    fab = _mk_fabric(
        nodes, config, params, ec, slots, roles=["prefill", "decode"],
    )
    killed = [False]

    def _kill_at_migration_boundary():
        if killed[0]:
            return
        for rep in fab.router.replicas:
            if rep.role == "decode" and rep.inflight:
                # Grafted sequences in flight on the decode pool: the
                # exact window where the source already RELEASED its
                # pages — only the journal can reconstruct.
                rep.inject_fault("crash")
                killed[0] = True
                return

    try:
        fab.scale_to(2)
        fab.drive(
            trace, timeout=timeout,
            extra_tick=_kill_at_migration_boundary,
        )
        done = fab.router.completions
        want = {t[2].rid for t in trace}
        assert killed[0], (
            "kill drill never armed: no migration reached the decode "
            "replica's inflight set"
        )
        assert set(done) == want, (
            f"kill drill lost/invented sequences: {set(done) ^ want}"
        )
        mismatch = [
            rid for rid in want
            if not np.array_equal(done[rid].tokens, ref[rid])
        ]
        assert not mismatch, (
            f"kill drill: post-crash completions diverged from the "
            f"reference on {mismatch}"
        )
        recovered = [
            rid for rid, c in done.items() if len(c.replicas) > 1
        ]
        _note(
            f"kill drill: decode replica crashed with grafts in "
            f"flight; {len(recovered)} sequences journal-recovered, "
            f"all {len(done)} token-identical"
        )
        return {
            "killed": True,
            "completed": len(done),
            "journal_recovered": len(recovered),
            "kv_migrations": dict(fab.router.kv_migrations),
        }
    finally:
        fab.stop()


# --- phase 3: measure --------------------------------------------------------


def run_measure(
    config, params, nodes, replicas, prefill_replicas, requests,
    rate, slots, seed, timeout,
) -> dict:
    """Colocated vs disaggregated at equal chips on the identical
    seeded prompt-heavy trace."""
    ec = _engine_config(slots, max_prompt=48, max_out=16)
    warm_jit(config, params, ec)
    trace = make_disagg_trace(
        seed, requests=requests, rate_rps=rate,
        vocab=config.vocab_size,
        # Prompt-heavy by design: prefill work per request is ~3x the
        # decode work, the regime where phase interference shows.
        prompt_lens=[24, 32, 48], output_lens=[8, 12, 16],
    )
    n_p = max(1, min(prefill_replicas, replicas - 1))
    plans = {
        "colocated": ["both"] * replicas,
        "disagg": ["prefill"] * n_p + ["decode"] * (replicas - n_p),
    }
    out = {}
    for label, roles in plans.items():
        fab = _mk_fabric(
            nodes, config, params, ec, slots, roles=roles,
        )
        try:
            fab.scale_to(replicas)
            res = fab.drive(trace, timeout=timeout)
            done = fab.router.completions
            assert res["submitted"] == len(done), (
                f"measure[{label}]: lost sequences "
                f"({res['submitted']} admitted, {len(done)} completed)"
            )
            rep = _latency_report(fab)
            rep.update({
                "wall_s": res["wall_s"],
                "kv_migrations_shipped":
                    fab.router.kv_migrations.get("shipped", 0),
                "kv_migrations_fallback":
                    fab.router.kv_migrations.get("fallback", 0),
                "kv_migrated_pages": fab.router.kv_migrated_pages,
                "migration_p50_ms": round(_pct(sorted(
                    s * 1000.0 for s in fab.router.migration_seconds
                ), 0.5), 3),
            })
            out[label] = rep
            _note(
                f"measure[{label}]: ttft p99 {rep['ttft_p99_ms']} ms, "
                f"itl p99 {rep['itl_p99_ms']} ms, "
                f"{rep['kv_migrations_shipped']} migrations, wall "
                f"{rep['wall_s']}s"
            )
        finally:
            fab.stop()
    assert out["disagg"]["kv_migrations_shipped"] >= 1, (
        "measured disagg side shipped no migrations — the phase split "
        "never engaged (roles/export wiring broke)"
    )
    assert out["colocated"]["kv_migrations_shipped"] == 0, (
        "colocated baseline shipped migrations — 'both' replicas must "
        "never export"
    )
    return out


# --- entry point -------------------------------------------------------------


def run(
    nodes: int,
    replicas: int,
    prefill_replicas: int,
    requests: int,
    rate: float,
    slots: int,
    seed: int,
    smoke: bool = False,
    timeout: float = 900.0,
) -> dict:
    config, params = _model()

    parity = run_parity(
        config, params, nodes=min(nodes, 8), slots=slots, seed=seed,
        timeout=timeout,
    )
    drill = run_kill_drill(
        config, params, nodes=min(nodes, 8), slots=slots, seed=seed,
        timeout=timeout,
    )
    measure = run_measure(
        config, params, nodes, replicas, prefill_replicas, requests,
        rate, slots, seed, timeout,
    )

    dis, col = measure["disagg"], measure["colocated"]
    vs_ttft = round(
        dis["ttft_p99_ms"] / max(col["ttft_p99_ms"], 1e-9), 3
    )
    vs_itl = round(dis["itl_p99_ms"] / max(col["itl_p99_ms"], 1e-9), 3)
    report = {
        "disagg_nodes": nodes,
        "disagg_replicas": replicas,
        "disagg_prefill_replicas": max(
            1, min(prefill_replicas, replicas - 1)
        ),
        "disagg_requests": requests,
        "disagg_ttft_p50_ms": dis["ttft_p50_ms"],
        "disagg_ttft_p99_ms": dis["ttft_p99_ms"],
        "disagg_itl_p50_ms": dis["itl_p50_ms"],
        "disagg_itl_p99_ms": dis["itl_p99_ms"],
        "disagg_colocated_ttft_p99_ms": col["ttft_p99_ms"],
        "disagg_colocated_itl_p99_ms": col["itl_p99_ms"],
        "disagg_vs_colocated_ttft": vs_ttft,
        "disagg_vs_colocated_itl": vs_itl,
        "disagg_kv_migrations": dis["kv_migrations_shipped"],
        "disagg_kv_migration_fallbacks": dis["kv_migrations_fallback"],
        "disagg_kv_migrated_pages": dis["kv_migrated_pages"],
        "disagg_migration_p50_ms": dis["migration_p50_ms"],
        "disagg_parity": parity,
        "disagg_kill_drill": drill,
        "seed": seed,
    }
    _note(
        f"disagg vs colocated: ttft p99 x{vs_ttft}, itl p99 x{vs_itl} "
        f"(< 1.0 = disaggregation wins)"
    )
    allow_gap = os.environ.get("DISAGG_ALLOW_GAP") == "1"
    if not smoke and not allow_gap:
        # The headline claim, gated hard at full size: phase
        # disaggregation beats colocation on BOTH tails at equal
        # chips. CPU drill sizes run the identical code path but their
        # quantiles are queueing noise — DISAGG_ALLOW_GAP=1 records
        # anyway.
        assert vs_ttft < 1.0, (
            f"disaggregated TTFT p99 did not beat colocated "
            f"(x{vs_ttft}) — DISAGG_ALLOW_GAP=1 to record anyway"
        )
        assert vs_itl < 1.0, (
            f"disaggregated ITL p99 did not beat colocated "
            f"(x{vs_itl}) — DISAGG_ALLOW_GAP=1 to record anyway"
        )
    if smoke:
        _note(
            "smoke contract: token parity greedy+sampled across live "
            "migration, lossless kill at the migration boundary, "
            "shipped migrations on the measured disagg side, zero on "
            "colocated — all hold"
        )
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser("disaggbench", description=__doc__)
    p.add_argument(
        "--smoke", action="store_true",
        help="CI size: small fleet/trace + the hard contract asserts",
    )
    args = p.parse_args(argv)
    env = os.environ.get
    if args.smoke:
        nodes = int(env("DISAGG_NODES", "8"))
        replicas = int(env("DISAGG_REPLICAS", "2"))
        prefill = int(env("DISAGG_PREFILL", "1"))
        requests = int(env("DISAGG_REQUESTS", "24"))
        rate = float(env("DISAGG_RATE", "60"))
        slots = int(env("DISAGG_SLOTS", "4"))
    else:
        nodes = int(env("DISAGG_NODES", "64"))
        replicas = int(env("DISAGG_REPLICAS", "8"))
        prefill = int(env("DISAGG_PREFILL", "4"))
        requests = int(env("DISAGG_REQUESTS", "2000"))
        rate = float(env("DISAGG_RATE", "400"))
        slots = int(env("DISAGG_SLOTS", "8"))
    seed = int(env("DISAGG_SEED", "20260807"))
    report = run(
        nodes, replicas, prefill, requests, rate, slots, seed,
        smoke=args.smoke,
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
