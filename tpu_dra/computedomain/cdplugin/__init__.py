"""CD kubelet plugin (cmd/compute-domain-kubelet-plugin).

Advertises abstract **channel** devices + one **daemon** device per node,
gates workload pod startup on ComputeDomain readiness, and injects the
slice bootstrap config via CDI.
"""
