"""CD-plugin claim preparation.

Reference analog: cmd/compute-domain-kubelet-plugin/device_state.go —
channel claim prep (:147-288, :466-513) and daemon claim prep (:516-573):

- channel claims (workload pods): assert the claim lives in the CD's
  namespace (:296-311), label the node so the per-CD DaemonSet follows the
  workload (:312-365), then **assert CD readiness** — failure raises, the
  kubelet retries, and the pod stays in ContainerCreating until the whole
  slice is ready (:238-295). CDI edits inject the daemon-rendered bootstrap
  env + the per-CD config-dir mount (the ``/dev/nvidia-caps-imex-channels``
  analog is env+mount, TPUs have no channel device nodes).
- channel devices are **domain-exclusive per node** (:646-674 analog): one
  node serves exactly one ComputeDomain per channel at a time.
- daemon claims: create the per-CD config dir the daemon writes and the
  workloads read (the ``/imexd`` mount analog).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

from tpu_dra import api as configapi
from tpu_dra.api.errors import ApiError
from tpu_dra.computedomain import (
    CD_DRIVER_NAME,
    CD_LABEL_KEY,
    NUM_CHANNELS,
)
from tpu_dra.computedomain.daemon.bootstrap import read_bootstrap_env
from tpu_dra.infra import deadline
from tpu_dra.infra.crashpoint import crashpoint
from tpu_dra.k8sclient import COMPUTE_DOMAINS, NODES, ResourceClient
from tpu_dra.plugin.cdi import CDIHandler
from tpu_dra.plugin.checkpoint import (
    CLAIM_STATE_PREPARE_COMPLETED,
    CLAIM_STATE_PREPARE_STARTED,
    CheckpointManager,
    PreparedClaim,
)
from tpu_dra.plugin.device_state import PermanentError, PrepareError
from tpu_dra.plugin.prepared import (
    KubeletDevice,
    PreparedDevice,
    PreparedDeviceGroup,
    PreparedDevices,
)

log = logging.getLogger(__name__)

CHANNEL_DEVICE_TYPE = "cd-channel"
DAEMON_DEVICE_TYPE = "cd-daemon"


def channel_device_name(i: int) -> str:
    return f"channel-{i}"


DAEMON_DEVICE_NAME = "daemon"


class _NotReadyRetry(Exception):
    """The ComputeDomain exists but is not Ready yet and the deadline
    has not expired. Internal control flow only: ``prepare()`` catches
    it after releasing the device lock, pauses, and retries."""

    def __init__(self, cd_uid: str):
        super().__init__(cd_uid)
        self.cd_uid = cd_uid


class CDDeviceState:
    def __init__(
        self,
        backend,
        cdi: CDIHandler,
        checkpoints: CheckpointManager,
        node_name: str,
        domains_dir: str,
        ready_timeout: float = 0.0,
    ):
        self.backend = backend
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)
        self.nodes = ResourceClient(backend, NODES)
        self.cdi = cdi
        self.checkpoints = checkpoints
        self.node_name = node_name
        self.domains_dir = domains_dir
        self.ready_timeout = ready_timeout
        self._lock = threading.Lock()
        self._cd_location: Dict[str, tuple] = {}
        os.makedirs(domains_dir, exist_ok=True)

    # --- inventory (nvlib.go:138-187 analog) ---

    def allocatable_device_names(self) -> List[str]:
        return [channel_device_name(i) for i in range(NUM_CHANNELS)] + [
            DAEMON_DEVICE_NAME
        ]

    def domain_config_dir(self, cd_uid: str) -> str:
        return os.path.join(self.domains_dir, cd_uid)

    # --- ComputeDomain helpers (computedomain.go analog) ---

    def _get_cd_by_uid(self, domain_id: str) -> Optional[dict]:
        # Cache uid -> (namespace, name) so the readiness poll loop does a
        # targeted GET instead of re-listing every CD cluster-wide each tick.
        cached = self._cd_location.get(domain_id)
        if cached is not None:
            cd = self.cds.try_get(cached[1], cached[0])
            if cd is not None and cd["metadata"]["uid"] == domain_id:
                return cd
            del self._cd_location[domain_id]
        for cd in self.cds.list():
            if cd["metadata"]["uid"] == domain_id:
                self._cd_location[domain_id] = (
                    cd["metadata"]["namespace"],
                    cd["metadata"]["name"],
                )
                return cd
        return None

    def assert_compute_domain_namespace(self, cd: dict, claim: dict) -> None:
        """computedomain.go:296-311: a channel claim must live in its CD's
        namespace (defends against cross-namespace domainID spoofing)."""
        if claim["metadata"]["namespace"] != cd["metadata"]["namespace"]:
            raise PermanentError(
                f"claim namespace {claim['metadata']['namespace']!r} does not "
                f"match ComputeDomain namespace "
                f"{cd['metadata']['namespace']!r}"
            )

    def add_node_label(self, cd_uid: str) -> None:
        """computedomain.go:312-365: labeling the node triggers the per-CD
        DaemonSet to schedule here ("the CD follows the workload")."""
        node = self.nodes.try_get(self.node_name)
        if node is None:
            # Single-node/demo path: synthesize the Node object.
            node = self.nodes.create({"metadata": {"name": self.node_name}})
        labels = node["metadata"].get("labels") or {}
        cur = labels.get(CD_LABEL_KEY)
        if cur == cd_uid:
            return
        if cur is not None and cur != cd_uid:
            raise PrepareError(
                f"node {self.node_name} already part of compute domain {cur}"
            )
        self.nodes.patch(
            self.node_name, {"metadata": {"labels": {CD_LABEL_KEY: cd_uid}}}
        )

    def remove_node_label(self, cd_uid: str) -> None:
        node = self.nodes.try_get(self.node_name)
        if node is None:
            return
        if (node["metadata"].get("labels") or {}).get(CD_LABEL_KEY) == cd_uid:
            self.nodes.patch(
                self.node_name, {"metadata": {"labels": {CD_LABEL_KEY: None}}}
            )

    def assert_compute_domain_ready(
        self, cd_uid: str, ready_deadline: float
    ) -> dict:
        """computedomain.go:238-295: raising here holds the workload pod in
        ContainerCreating; the kubelet retries until the slice is whole.

        Single-shot check: not-Ready before the deadline raises
        :class:`_NotReadyRetry`, which ``prepare()`` catches OUTSIDE the
        device lock to pause and retry — the readiness wait must never
        hold ``self._lock``, or every other claim's prepare/unprepare on
        this node stalls behind one domain's assembly."""
        cd = self._get_cd_by_uid(cd_uid)
        if cd is None:
            raise PrepareError(f"ComputeDomain {cd_uid} not found")
        if cd.get("status", {}).get("status") == "Ready":
            return cd
        if time.monotonic() >= ready_deadline:
            raise PrepareError(
                f"ComputeDomain {cd_uid} is not ready "
                f"({cd.get('status', {}).get('status') or 'no status'})"
            )
        raise _NotReadyRetry(cd_uid)

    # --- prepare/unprepare ---

    def prepare(self, claim: dict) -> List[KubeletDevice]:
        budget = deadline.current()
        ready_deadline = time.monotonic() + self.ready_timeout
        while True:
            try:
                with self._lock:
                    return self._prepare_locked(claim, ready_deadline)
            except _NotReadyRetry as nr:
                # Pause with the lock RELEASED, then re-run the whole
                # locked attempt (label/WAL steps are idempotent). The
                # wait consumes the calling RPC's deadline budget
                # (expiry is retriable too — the kubelet re-Prepares
                # with a fresh budget).
                budget.check(
                    f"waiting for ComputeDomain {nr.cd_uid} readiness"
                )
                budget.pause(0.1)

    def _prepare_locked(
        self, claim: dict, ready_deadline: float
    ) -> List[KubeletDevice]:
        claim_uid = claim["metadata"]["uid"]
        cp = self.checkpoints.get()
        prev = cp.prepared_claims.get(claim_uid)
        if prev is not None and prev.checkpoint_state == CLAIM_STATE_PREPARE_COMPLETED:
            return prev.prepared_devices.get_devices()

        results = self._allocation_results(claim)
        config = self._decode_config(claim)

        self.checkpoints.update(
            lambda c: c.prepared_claims.__setitem__(
                claim_uid,
                PreparedClaim(
                    checkpoint_state=CLAIM_STATE_PREPARE_STARTED,
                    status=claim.get("status", {}),
                    name=claim["metadata"].get("name", ""),
                    namespace=claim["metadata"].get("namespace", ""),
                ),
            )
        )
        crashpoint("cdplugin.prepare.after_wal_started")

        if isinstance(config, configapi.ComputeDomainChannelConfig):
            prepared = self._prepare_channel(
                claim, config, results, ready_deadline
            )
        elif isinstance(config, configapi.ComputeDomainDaemonConfig):
            prepared = self._prepare_daemon(claim, config, results)
        else:
            raise PermanentError(
                f"unsupported config kind for CD plugin: {type(config).__name__}"
            )

        self.cdi.create_claim_spec_file(claim_uid, prepared)
        crashpoint("cdplugin.prepare.before_wal_completed")
        self.checkpoints.update(
            lambda c: c.prepared_claims.__setitem__(
                claim_uid,
                PreparedClaim(
                    checkpoint_state=CLAIM_STATE_PREPARE_COMPLETED,
                    status=claim.get("status", {}),
                    prepared_devices=prepared,
                    name=claim["metadata"].get("name", ""),
                    namespace=claim["metadata"].get("namespace", ""),
                ),
            )
        )
        return prepared.get_devices()

    def _prepare_channel(
        self,
        claim: dict,
        config: configapi.ComputeDomainChannelConfig,
        results: List[dict],
        ready_deadline: float,
    ) -> PreparedDevices:
        cd = self._get_cd_by_uid(config.domain_id)
        if cd is None:
            raise PrepareError(f"ComputeDomain {config.domain_id} not found")
        self.assert_compute_domain_namespace(cd, claim)
        self._assert_channels_not_allocated_to_other_domain(
            claim, config.domain_id, results
        )
        self.add_node_label(config.domain_id)
        self.assert_compute_domain_ready(config.domain_id, ready_deadline)

        config_dir = self.domain_config_dir(config.domain_id)
        env = read_bootstrap_env(config_dir) or {}
        if not env:
            raise PrepareError(
                f"bootstrap config for domain {config.domain_id} not yet "
                f"rendered by the slice daemon"
            )
        group = PreparedDeviceGroup()
        group.config_state.container_edits = {
            "mounts": [
                {
                    "hostPath": config_dir,
                    "containerPath": "/tpu-cd",
                    "options": ["ro", "rbind"],
                }
            ]
        }
        for result in results:
            pd = PreparedDevice(
                type=CHANNEL_DEVICE_TYPE,
                device=KubeletDevice(
                    requests=[result["request"]],
                    pool_name=result.get("pool", self.node_name),
                    device_name=result["device"],
                    cdi_device_ids=[
                        self.cdi.qualified_device_id(
                            claim["metadata"]["uid"], result["device"]
                        )
                    ],
                ),
                # CD_CONFIG_DIR points the workload's bootstrap consumer
                # (workloads/bootstrap.py) at the mounted config dir, so
                # peers.json coordinator resolution works even when the
                # pod doesn't share the daemon-maintained hosts file.
                runtime_env={**env, "CD_CONFIG_DIR": "/tpu-cd"},
            )
            group.devices.append(pd)
        return PreparedDevices([group])

    def _prepare_daemon(
        self,
        claim: dict,
        config: configapi.ComputeDomainDaemonConfig,
        results: List[dict],
    ) -> PreparedDevices:
        config_dir = self.domain_config_dir(config.domain_id)
        os.makedirs(config_dir, exist_ok=True)
        group = PreparedDeviceGroup()
        group.config_state.container_edits = {
            "mounts": [
                {
                    "hostPath": config_dir,
                    "containerPath": "/tpu-cd",
                    "options": ["rw", "rbind"],
                }
            ]
        }
        for result in results:
            pd = PreparedDevice(
                type=DAEMON_DEVICE_TYPE,
                device=KubeletDevice(
                    requests=[result["request"]],
                    pool_name=result.get("pool", self.node_name),
                    device_name=result["device"],
                    cdi_device_ids=[
                        self.cdi.qualified_device_id(
                            claim["metadata"]["uid"], result["device"]
                        )
                    ],
                ),
                runtime_env={"CD_UID": config.domain_id,
                             "CD_CONFIG_DIR": "/tpu-cd"},
            )
            group.devices.append(pd)
        return PreparedDevices([group])

    def _assert_channels_not_allocated_to_other_domain(
        self, claim: dict, domain_id: str, results: List[dict]
    ) -> None:
        """device_state.go:646-674 analog: a channel on this node serves one
        domain at a time."""
        requested = {r["device"] for r in results}
        cp = self.checkpoints.get()
        for uid, prev in cp.prepared_claims.items():
            if uid == claim["metadata"]["uid"]:
                continue
            prev_domain = self._domain_of(prev)
            for pd in [d for g in prev.prepared_devices for d in g.devices]:
                if (
                    pd.type == CHANNEL_DEVICE_TYPE
                    and pd.device.device_name in requested
                    and prev_domain
                    and prev_domain != domain_id
                ):
                    raise PrepareError(
                        f"channel {pd.device.device_name} on this node is "
                        f"already allocated to compute domain {prev_domain}"
                    )

    @staticmethod
    def _domain_of(prev: PreparedClaim) -> str:
        for cfg in (
            prev.status.get("allocation", {}).get("devices", {}).get("config", [])
        ):
            params = (cfg.get("opaque") or {}).get("parameters") or {}
            if params.get("domainID"):
                return params["domainID"]
        return ""

    def unprepare(self, claim_uid: str) -> None:
        with self._lock:
            cp = self.checkpoints.get()
            claim = cp.prepared_claims.get(claim_uid)
            if claim is None:
                log.info("unprepare noop: no checkpointed claim %s", claim_uid)
                return
            # Daemon claim teardown removes the per-CD config dir.
            for pd in claim.prepared_devices.of_type(DAEMON_DEVICE_TYPE):
                cd_uid = pd.runtime_env.get("CD_UID", "")
                if cd_uid:
                    shutil.rmtree(
                        self.domain_config_dir(cd_uid), ignore_errors=True
                    )
            self.cdi.delete_claim_spec_file(claim_uid)
            crashpoint("cdplugin.unprepare.before_wal_removed")
            self.checkpoints.update(
                lambda c: c.prepared_claims.pop(claim_uid, None)
            )

    def recover_stale_prepares(self) -> List[str]:
        """Boot-time rollback of CD claims stuck in ``PrepareStarted``
        (the CD analog of DeviceState.recover_stale_prepares): a CD claim
        holds no silicon, so rollback is dropping the orphaned CDI spec,
        the WAL entry, and — for a daemon claim whose domain no other
        claim references — the per-domain config dir ``_prepare_daemon``
        already created; the periodic label GC then releases the node's
        CD label once nothing references the domain."""
        cp = self.checkpoints.get()
        rolled: List[str] = []
        for uid, claim in sorted(cp.prepared_claims.items()):
            if claim.checkpoint_state != CLAIM_STATE_PREPARE_STARTED:
                continue
            log.warning(
                "boot recovery: rolling back stale CD PrepareStarted "
                "claim %s (%s/%s)", uid, claim.namespace, claim.name,
            )
            with self._lock:
                self.cdi.delete_claim_spec_file(uid)
                self.checkpoints.update(
                    lambda c: c.prepared_claims.pop(uid, None)
                )
                self._rollback_daemon_config_dir(uid, claim)
            rolled.append(uid)
        return rolled

    def _rollback_daemon_config_dir(
        self, claim_uid: str, claim: PreparedClaim
    ) -> None:
        """A crashed DAEMON-claim prepare may have left its per-domain
        config dir behind (``_prepare_daemon`` creates it before the WAL
        flips to completed), and with no prepared_devices record the
        normal unprepare rmtree never runs. The stored claim status names
        the device and the domain. Channel claims never touch the dir —
        and a domain any OTHER claim still references keeps it (it is a
        shared mount)."""
        results = (
            claim.status.get("allocation", {}).get("devices", {}).get(
                "results", []
            )
        )
        is_daemon = any(
            r.get("driver") == CD_DRIVER_NAME
            and r.get("device") == DAEMON_DEVICE_NAME
            for r in results
        )
        domain = self._domain_of(claim)
        if not is_daemon or not domain:
            return
        cp = self.checkpoints.get()
        for other_uid, other in cp.prepared_claims.items():
            if other_uid != claim_uid and self._domain_of(other) == domain:
                return
        log.info(
            "boot recovery: removing orphaned domain config dir for %s",
            domain,
        )
        shutil.rmtree(self.domain_config_dir(domain), ignore_errors=True)

    def cleanup_stale_node_labels(self) -> int:
        """computedomain.go:384-439 analog: drop our node's CD label when no
        prepared claim references that domain anymore."""
        node = self.nodes.try_get(self.node_name)
        if node is None:
            return 0
        uid = (node["metadata"].get("labels") or {}).get(CD_LABEL_KEY)
        if not uid:
            return 0
        cp = self.checkpoints.get()
        for prev in cp.prepared_claims.values():
            if self._domain_of(prev) == uid:
                return 0
        self.remove_node_label(uid)
        return 1

    # --- claim plumbing ---

    @staticmethod
    def _allocation_results(claim: dict) -> List[dict]:
        alloc = claim.get("status", {}).get("allocation")
        if alloc is None:
            raise PrepareError("claim not yet allocated")
        return [
            r
            for r in alloc.get("devices", {}).get("results", [])
            if r.get("driver") == CD_DRIVER_NAME
        ]

    @staticmethod
    def _decode_config(claim: dict):
        alloc = claim.get("status", {}).get("allocation", {})
        for entry in alloc.get("devices", {}).get("config", []):
            opaque = entry.get("opaque")
            if not opaque or opaque.get("driver") != CD_DRIVER_NAME:
                continue
            try:
                cfg = configapi.strict_decode(opaque.get("parameters"))
                cfg.normalize()
                cfg.validate()
                return cfg
            except ApiError as e:
                raise PermanentError(f"error decoding opaque config: {e}") from e
        raise PermanentError(
            "CD claim carries no opaque config for this driver"
        )
