"""CD-plugin driver wiring.

Reference analog: cmd/compute-domain-kubelet-plugin/driver.go (:55-299):
mirrors the gpu-plugin driver but publishes abstract channel/daemon devices
and adds permanent-error classification in prepare results.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Optional

from tpu_dra.computedomain import CD_DRIVER_NAME, NUM_CHANNELS
from tpu_dra.computedomain.cdplugin.device_state import (
    CDDeviceState,
    CHANNEL_DEVICE_TYPE,
    DAEMON_DEVICE_NAME,
    DAEMON_DEVICE_TYPE,
    channel_device_name,
)
from tpu_dra.infra.flock import Flock
from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import RESOURCE_SLICES, ResourceClient
from tpu_dra.k8sclient.circuit import bind_backend_metrics
from tpu_dra.k8sclient.degraded import DegradedModeController
from tpu_dra.plugin.cdi import CDIHandler
from tpu_dra.plugin.checkpoint import (
    CLAIM_STATE_PREPARE_COMPLETED,
    Checkpoint,
    CheckpointManager,
    PreparedClaim,
)
from tpu_dra.plugin.cleanup import CheckpointCleanupManager
from tpu_dra.plugin.dra_service import DRAService, RegistrationService, serve_unix
from tpu_dra.plugin.prepared import (
    KubeletDevice,
    PreparedDevice,
    PreparedDeviceGroup,
    PreparedDevices,
)
from tpu_dra.plugin.slicepub import SlicePublisher

log = logging.getLogger(__name__)


@dataclass
class CDDriverConfig:
    node_name: str = ""
    cdi_root: str = "/var/run/cdi"
    plugin_data_dir: str = "/var/lib/kubelet/plugins/compute-domain.tpu.google.com"
    kubelet_registrar_dir: str = "/var/lib/kubelet/plugins_registry"
    start_grpc: bool = True
    ready_timeout: float = 0.0


class CDDriver:
    def __init__(self, backend, config: CDDriverConfig, clique_id: str = ""):
        self.backend = backend
        self.config = config
        self.clique_id = clique_id
        self.metrics = Metrics(prefix="tpu_dra_cd")
        self.cdi = CDIHandler(cdi_root=config.cdi_root)
        self.checkpoints = CheckpointManager(
            config.plugin_data_dir,
            rebuild=self._rebuild_checkpoint_from_scan,
        )
        self.pu_flock = Flock(f"{config.plugin_data_dir}/pu.lock")
        self.state = CDDeviceState(
            backend,
            cdi=self.cdi,
            checkpoints=self.checkpoints,
            node_name=config.node_name,
            domains_dir=f"{config.plugin_data_dir}/domains",
            ready_timeout=config.ready_timeout,
        )
        self.slices = ResourceClient(backend, RESOURCE_SLICES)
        # Content-diffed pool-set publisher, same machinery as the TPU
        # plugin: a republish with unchanged channel/daemon devices (the
        # common case — CD slices are near-static) costs zero writes.
        # The publisher is NOT internally locked; _publish_lock
        # serializes its callers (start()'s thread vs the degraded
        # controller's heal thread), mirroring the TPU Driver.
        self._publisher = SlicePublisher(
            self.slices, node_name=config.node_name,
            label_selector={"tpu.google.com/cd-driver": "true"},
            metrics=self.metrics,
        )
        self._publish_lock = threading.Lock()
        self._stop = threading.Event()
        # Same RPC surface as the TPU plugin; only the state machine differs
        # (DRAService is generic over anything with prepare/unprepare).
        # Budgets minted per kubelet RPC carry the stop event; the
        # transport's circuit breaker (when the backend is rest.
        # KubeClient) pauses the claim GC while the apiserver is dark.
        self.circuit = bind_backend_metrics(backend, self.metrics)
        self.dra_service = DRAService(
            self.state, backend, self.pu_flock, metrics=self.metrics,
            stop=self._stop,
        )
        self.cleanup = CheckpointCleanupManager(
            self.state, backend, pu_flock=self.pu_flock,
            metrics=self.metrics, circuit=self.circuit,
        )
        # Degraded mode, same contract (and shared state machine) as
        # the TPU plugin's Driver: the api_degraded gauge (prefixed
        # tpu_dra_cd_ here — the doctor matches the suffix) flips while
        # any verb's circuit is open, and a fenced resync re-runs the
        # claim GC + slice republish on heal.
        self.degraded_ctl: Optional[DegradedModeController] = None
        if self.circuit is not None:
            node = config.node_name
            self.degraded_ctl = DegradedModeController(
                circuit=self.circuit,
                metrics=self.metrics,
                stop=self._stop,
                probe=lambda: self.slices.get(f"{node}-cd-heal-probe"),
                resync=self._heal_reconcile,
                name="cd-",
            )
        else:
            self.metrics.set_gauge("api_degraded", 0)
        self.label_gc_period = 60.0
        self._servers = []
        self._label_gc_thread: Optional[threading.Thread] = None

    def _rebuild_checkpoint_from_scan(self) -> Checkpoint:
        """Both CD checkpoint copies unreadable: reconstruct
        ``PrepareCompleted`` records from the per-claim CDI specs (the CD
        analog of Driver._rebuild_checkpoint_from_scan). The spec's env
        edits carry ``CD_UID``, so a rebuilt daemon claim's unprepare can
        still remove its per-domain config dir; without the rebuild,
        unprepare would no-op on the missing WAL entry and leak every
        spec and domain dir forever."""
        cp = Checkpoint()
        for uid in sorted(self.cdi.list_claim_uids()):
            try:
                spec = self.cdi.read_claim_spec(uid)
            except (OSError, ValueError) as e:
                log.error(
                    "rebuild: skipping unreadable CD CDI spec for claim "
                    "%s: %s", uid, e,
                )
                continue
            if not spec:
                continue
            group = PreparedDeviceGroup()
            for dev in spec.get("devices", []):
                device_name = self.cdi.parse_claim_device_name(
                    uid, dev.get("name", "")
                )
                if device_name is None:
                    continue
                env = {}
                for kv in (dev.get("containerEdits") or {}).get("env") or []:
                    k, _, v = kv.partition("=")
                    env[k] = v
                group.devices.append(PreparedDevice(
                    type=(
                        DAEMON_DEVICE_TYPE
                        if device_name == DAEMON_DEVICE_NAME
                        else CHANNEL_DEVICE_TYPE
                    ),
                    device=KubeletDevice(
                        pool_name=f"{self.config.node_name}-cd",
                        device_name=device_name,
                        cdi_device_ids=[
                            self.cdi.qualified_device_id(uid, device_name)
                        ],
                    ),
                    runtime_env=env,
                ))
            if group.devices:
                cp.prepared_claims[uid] = PreparedClaim(
                    checkpoint_state=CLAIM_STATE_PREPARE_COMPLETED,
                    prepared_devices=PreparedDevices([group]),
                )
        log.error(
            "rebuilt CD checkpoint from CDI scan: %d claims reconstructed",
            len(cp.prepared_claims),
        )
        return cp

    def start(self) -> None:
        # Boot-time WAL recovery before serving the kubelet: a CD claim
        # stuck in PrepareStarted (crash mid-prepare) is rolled back so
        # the kubelet retry starts clean (Driver.start analog).
        rolled = self.state.recover_stale_prepares()
        if rolled:
            log.warning(
                "rolled back %d stale CD PrepareStarted claim(s) at startup",
                len(rolled),
            )
        if self.config.start_grpc:
            dra_socket = f"{self.config.plugin_data_dir}/dra.sock"
            reg_socket = (
                f"{self.config.kubelet_registrar_dir}/{CD_DRIVER_NAME}-reg.sock"
            )
            self.registration = RegistrationService(
                CD_DRIVER_NAME, dra_socket, ["v1beta1"]
            )
            self._servers.append(serve_unix([self.dra_service], dra_socket))
            self._servers.append(serve_unix([self.registration], reg_socket))
            self._socket_paths = [dra_socket, reg_socket]
        self.cleanup.start()
        # Periodic stale-node-label GC (computedomain.go:384-439 analog):
        # drops this node's CD label once no prepared claim references the
        # domain, freeing the node for other ComputeDomains.
        self._label_gc_thread = threading.Thread(
            target=self._label_gc_loop, daemon=True, name="cd-label-gc"
        )
        self._label_gc_thread.start()
        self.publish_resources()

    def _label_gc_loop(self) -> None:
        while not self._stop.wait(self.label_gc_period):
            try:
                self.state.cleanup_stale_node_labels()
            except Exception:
                log.exception("stale node-label GC failed")

    def shutdown(self) -> None:
        self._stop.set()
        self.cleanup.stop()
        for s in self._servers:
            s.stop(grace=1).wait(timeout=5)

    def healthy(self) -> "tuple[bool, str]":
        """Liveness verdict for /healthz; see Driver.healthy."""
        from tpu_dra.infra.metrics import sockets_healthy

        return sockets_healthy(
            getattr(self, "_socket_paths", []),
            getattr(self, "registration", None),
        )

    # --- degraded mode (control-plane weather; shared state machine) ---

    def _heal_reconcile(self) -> None:
        """The CD-specific half of the fenced heal resync
        (DegradedModeController drives it): re-run the claim GC against
        the recovered apiserver and republish this node's CD
        ResourceSlices (a publish that failed while the control plane
        was dark would otherwise stay missing until restart). Each step
        fails independently — a flaky GC must not block the
        republish."""
        try:
            cleaned = self.cleanup.cleanup_once()
            if cleaned:
                log.warning(
                    "CD heal resync: unprepared %d claim(s) that went "
                    "stale during the outage", cleaned,
                )
        except Exception as e:  # noqa: BLE001 — resync is best-effort
            log.warning("CD heal resync claim reconcile failed: %s", e)
        # Drop the diff cache first: the outage may have eaten the
        # slices, and a trusted cache would turn the heal republish
        # into a zero-write no-op.
        with self._publish_lock:
            self._publisher.invalidate()
        self.publish_resources()

    MAX_DEVICES_PER_SLICE = 128  # apiserver validation cap on spec.devices

    def publish_resources(self) -> None:
        """NUM_CHANNELS channel devices + the daemon device
        (nvlib.go:138-187 analog), sharded across slices to respect the
        128-devices-per-ResourceSlice validation limit, with every slice
        declaring the pool's total slice count. Channels are abstract (no
        hardware), so attributes carry only the clique identity."""
        devices = []
        for i in range(NUM_CHANNELS):
            attrs = {"type": {"string": "cd-channel"}, "channel": {"int": i}}
            if self.clique_id:
                attrs["cliqueID"] = {"string": self.clique_id}
            devices.append(
                {"name": channel_device_name(i), "basic": {"attributes": attrs}}
            )
        daemon_attrs = {"type": {"string": "cd-daemon"}}
        if self.clique_id:
            daemon_attrs["cliqueID"] = {"string": self.clique_id}
        devices.append(
            {"name": DAEMON_DEVICE_NAME, "basic": {"attributes": daemon_attrs}}
        )

        chunks = [
            devices[i : i + self.MAX_DEVICES_PER_SLICE]
            for i in range(0, len(devices), self.MAX_DEVICES_PER_SLICE)
        ]

        def build(generation: int):
            return [
                {
                    "apiVersion": "resource.k8s.io/v1beta1",
                    "kind": "ResourceSlice",
                    "metadata": {
                        "name": (
                            f"{self.config.node_name}-{CD_DRIVER_NAME}-{idx}"
                        ),
                        "labels": {"tpu.google.com/cd-driver": "true"},
                    },
                    "spec": {
                        "driver": CD_DRIVER_NAME,
                        "nodeName": self.config.node_name,
                        "pool": {
                            "name": f"{self.config.node_name}-cd",
                            "generation": generation,
                            "resourceSliceCount": len(chunks),
                        },
                        "devices": chunk,
                    },
                }
                for idx, chunk in enumerate(chunks)
            ]

        with self._publish_lock:
            self._publisher.publish(build)
