"""compute-domain-kubelet-plugin entrypoint (mirrors the gpu-plugin main)."""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from tpu_dra.computedomain.cdplugin.driver import CDDriver, CDDriverConfig
from tpu_dra.infra import flags, signals
from tpu_dra.tpulib import new_tpulib

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpu-compute-domain-kubelet-plugin")
    flags.add_version_flag(p)
    flags.KubeClientConfig.add_flags(p)
    flags.LoggingConfig.add_flags(p)
    flags.add_feature_gate_flag(p)
    p.add_argument("--node-name", default=flags.env_default("NODE_NAME", ""))
    p.add_argument("--cdi-root", default=flags.env_default("CDI_ROOT", "/var/run/cdi"))
    p.add_argument(
        "--plugin-data-dir",
        default=flags.env_default(
            "PLUGIN_DATA_DIR",
            "/var/lib/kubelet/plugins/compute-domain.tpu.google.com",
        ),
    )
    p.add_argument(
        "--kubelet-registrar-dir",
        default=flags.env_default(
            "KUBELET_REGISTRAR_DIR", "/var/lib/kubelet/plugins_registry"
        ),
    )
    p.add_argument("--backend", default=flags.env_default("TPU_DRA_BACKEND", ""))
    # Driver-root resolution (root.go:29-87 analog), same as the TPU
    # plugin: the containerized plugin sees host trees under a prefix.
    p.add_argument(
        "--sysfs-root",
        default=flags.env_default("TPU_DRA_SYSFS_ROOT", "/sys"),
        help="Host sysfs mount (PCI/slice enumeration)",
    )
    p.add_argument(
        "--dev-root",
        default=flags.env_default("TPU_DRA_DEV_ROOT", "/dev"),
        help="Host /dev mount",
    )
    p.add_argument(
        "--fake-cluster",
        action="store_true",
        default=flags.env_default("TPU_DRA_FAKE_CLUSTER", False, bool),
    )
    p.add_argument(
        "--fake-cluster-seed",
        default=flags.env_default("TPU_DRA_FAKE_CLUSTER_SEED", ""),
        help="Directory of manifests to pre-create in the fake cluster",
    )
    p.add_argument(
        "--health-port", type=int, default=flags.env_default("HEALTH_PORT", 0, int)
    )
    args = p.parse_args(argv)
    flags.LoggingConfig.from_args(args).apply()
    signals.start_debug_signal_handlers()
    flags.apply_feature_gates(args)
    flags.log_startup_config(args)

    if args.fake_cluster:
        from tpu_dra.k8sclient import FakeCluster

        backend = FakeCluster()
        if args.fake_cluster_seed:
            n = backend.load_dir(args.fake_cluster_seed)
            log.info("seeded fake cluster with %d objects", n)
    else:
        backend = flags.KubeClientConfig.from_args(args).new_client()

    # Clique identity from local tpulib (nvlib.go:188-357 analog).
    clique_id = ""
    try:
        tpulib = new_tpulib(
            args.backend,
            sysfs_root=args.sysfs_root,
            dev_root=args.dev_root,
        )
        ici = tpulib.ici_domain()
        clique_id = ici.clique_id() if ici else ""
    except Exception as e:
        log.warning("could not discover ICI domain: %s", e)

    driver = CDDriver(
        backend,
        CDDriverConfig(
            node_name=args.node_name,
            cdi_root=args.cdi_root,
            plugin_data_dir=args.plugin_data_dir,
            kubelet_registrar_dir=args.kubelet_registrar_dir,
        ),
        clique_id=clique_id,
    )
    driver.start()

    # Health/metrics endpoint probed by the chart's startup/liveness probes
    # (cmd/compute-domain-kubelet-plugin/health.go analog).
    from tpu_dra.infra.metrics import start_health_server

    health_server = start_health_server(
        driver.metrics, args.health_port, healthz=driver.healthy
    )
    if health_server:
        log.info("metrics/healthz on :%d", health_server.port)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    log.info("compute-domain-kubelet-plugin running")
    stop.wait()
    driver.shutdown()
    if health_server:
        health_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
