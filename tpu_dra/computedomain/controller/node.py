"""Node-label lifecycle for ComputeDomains.

Reference analog: cmd/compute-domain-controller/node.go (:113-167): the CD
kubelet plugin labels nodes with ``resource.tpu.google.com/computeDomain=
<cdUID>`` when workload claims land; this manager removes those labels when
the CD is deleted, and a periodic pass GC's labels referencing CDs that no
longer exist.
"""

from __future__ import annotations

import logging
from typing import List, Set

from tpu_dra.computedomain import CD_LABEL_KEY
from tpu_dra.k8sclient import COMPUTE_DOMAINS, NODES, ResourceClient

log = logging.getLogger(__name__)


class NodeLabelManager:
    def __init__(self, backend):
        self.nodes = ResourceClient(backend, NODES)
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)

    def labeled_nodes(self, cd_uid: str) -> List[dict]:
        return self.nodes.list(label_selector={CD_LABEL_KEY: cd_uid})

    def remove_labels_for(self, cd_uid: str) -> int:
        n = 0
        for node in self.labeled_nodes(cd_uid):
            self.nodes.patch(
                node["metadata"]["name"],
                {"metadata": {"labels": {CD_LABEL_KEY: None}}},
            )
            n += 1
        return n

    def cleanup_stale_labels(self) -> int:
        """Periodic GC: drop CD labels whose CD no longer exists
        (node.go:113-167)."""
        live_uids: Set[str] = {
            cd["metadata"]["uid"] for cd in self.cds.list()
        }
        cleaned = 0
        for node in self.nodes.list():
            uid = (node["metadata"].get("labels") or {}).get(CD_LABEL_KEY)
            if uid and uid not in live_uids:
                self.nodes.patch(
                    node["metadata"]["name"],
                    {"metadata": {"labels": {CD_LABEL_KEY: None}}},
                )
                cleaned += 1
        if cleaned:
            log.info("removed %d stale computeDomain node labels", cleaned)
        return cleaned
