"""ResourceClaimTemplate management for ComputeDomains.

Reference analog: cmd/compute-domain-controller/resourceclaimtemplate.go
(:280-399): each CD gets (a) a **daemon RCT** (deviceClass
``compute-domain-daemon.tpu.google.com``) used by the per-CD DaemonSet, and
(b) the user-visible **workload RCT** (deviceClass
``compute-domain-default-channel.tpu.google.com``), named by
``spec.channel.resourceClaimTemplate.name``, embedding the opaque
ComputeDomain{Daemon,Channel}Config with domainID = the CD's UID.
"""

from __future__ import annotations

import logging

from tpu_dra.computedomain import (
    CD_DRIVER_NAME,
    CD_FINALIZER,
    CHANNEL_DEVICE_CLASS,
    DAEMON_DEVICE_CLASS,
)
from tpu_dra.computedomain.controller.daemonset import daemon_rct_name
from tpu_dra.k8sclient import (
    RESOURCE_CLAIM_TEMPLATES,
    ApiNotFound,
    ResourceClient,
)

log = logging.getLogger(__name__)

API_VERSION = "resource.tpu.google.com/v1beta1"


def _rct(
    name: str,
    namespace: str,
    cd_uid: str,
    device_class: str,
    config_kind: str,
    request_name: str,
    allocation_mode: str = "",
) -> dict:
    params: dict = {
        "apiVersion": API_VERSION,
        "kind": config_kind,
        "domainID": cd_uid,
    }
    if allocation_mode:
        params["allocationMode"] = allocation_mode
    request: dict = {
        "name": request_name,
        "deviceClassName": device_class,
    }
    if allocation_mode == "All":
        request["allocationMode"] = "All"
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaimTemplate",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "finalizers": [CD_FINALIZER],
            "labels": {"resource.tpu.google.com/computeDomain": cd_uid},
        },
        "spec": {
            "spec": {
                "devices": {
                    "requests": [request],
                    "config": [
                        {
                            "requests": [request_name],
                            "opaque": {
                                "driver": CD_DRIVER_NAME,
                                "parameters": params,
                            },
                        }
                    ],
                }
            }
        },
    }


class ResourceClaimTemplateManager:
    def __init__(self, backend, driver_namespace: str = "tpu-dra-driver"):
        self.rcts = ResourceClient(backend, RESOURCE_CLAIM_TEMPLATES)
        self.driver_namespace = driver_namespace

    def render_daemon_rct(self, cd: dict) -> dict:
        # The daemon RCT lives in the DRIVER namespace: a
        # resourceClaimTemplateName reference cannot cross namespaces, and
        # the per-CD daemon pods (its only consumers) run in the driver's
        # DaemonSet namespace (resourceclaimtemplate.go:295,320 — found
        # mis-namespaced by the first real bats execution: daemon pods
        # could never resolve their claim template).
        return _rct(
            name=daemon_rct_name(cd),
            namespace=self.driver_namespace,
            cd_uid=cd["metadata"]["uid"],
            device_class=DAEMON_DEVICE_CLASS,
            config_kind="ComputeDomainDaemonConfig",
            request_name="cd-daemon",
        )

    def render_workload_rct(self, cd: dict) -> dict:
        channel = cd["spec"].get("channel") or {}
        name = channel.get("resourceClaimTemplate", {}).get("name")
        if not name:
            raise ValueError(
                "ComputeDomain.spec.channel.resourceClaimTemplate.name is "
                "required"
            )
        return _rct(
            name=name,
            namespace=cd["metadata"]["namespace"],
            cd_uid=cd["metadata"]["uid"],
            device_class=CHANNEL_DEVICE_CLASS,
            config_kind="ComputeDomainChannelConfig",
            request_name="cd-channel",
            allocation_mode=channel.get("allocationMode", ""),
        )

    def create_or_update(self, cd: dict) -> None:
        for want in (self.render_daemon_rct(cd), self.render_workload_rct(cd)):
            cur = self.rcts.try_get(
                want["metadata"]["name"], want["metadata"]["namespace"]
            )
            if cur is None:
                self.rcts.create(want)
            elif cur["spec"] != want["spec"]:
                cur["spec"] = want["spec"]
                self.rcts.update(cur)

    def request_delete(self, cd: dict) -> None:
        for render in (self.render_daemon_rct, self.render_workload_rct):
            try:
                rct = render(cd)
            except ValueError:
                continue
            try:
                self.rcts.delete(
                    rct["metadata"]["name"], rct["metadata"]["namespace"]
                )
            except ApiNotFound:
                pass

    def finalize(self, cd: dict) -> bool:
        """Strip finalizers from deleted RCTs; True when all are gone."""
        gone = True
        for render in (self.render_daemon_rct, self.render_workload_rct):
            try:
                want = render(cd)
            except ValueError:
                continue
            cur = self.rcts.try_get(
                want["metadata"]["name"], want["metadata"]["namespace"]
            )
            if cur is None:
                continue
            if cur["metadata"].get("deletionTimestamp"):
                cur["metadata"]["finalizers"] = [
                    f for f in cur["metadata"].get("finalizers", [])
                    if f != CD_FINALIZER
                ]
                self.rcts.update(cur)
                cur = self.rcts.try_get(
                    want["metadata"]["name"], want["metadata"]["namespace"]
                )
            if cur is not None:
                gone = False
        return gone
