"""Cluster-level ComputeDomain controller (cmd/compute-domain-controller)."""
