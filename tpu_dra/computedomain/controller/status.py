"""ComputeDomain status aggregation.

Reference analog: cmd/compute-domain-controller/cdstatus.go (:135-241,
:286-354) + computedomain.go updateGlobalStatus (:251-280): clique daemon
registrations aggregate into ``CD.Status.Nodes``; the CD goes Ready when
every one of ``spec.numNodes`` expected hosts has registered **and**
reported Ready (all-or-nothing slice membership — stricter than IMEX's
incremental join, per JAX multi-host init semantics). Stale nodes (no
longer in any clique) are pruned.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from tpu_dra.api import CD_STATUS_NOT_READY, CD_STATUS_READY
from tpu_dra.computedomain import CD_LABEL_KEY
from tpu_dra.k8sclient import COMPUTE_DOMAIN_CLIQUES, COMPUTE_DOMAINS, ResourceClient

log = logging.getLogger(__name__)


class StatusManager:
    def __init__(self, backend):
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)
        self.cliques = ResourceClient(backend, COMPUTE_DOMAIN_CLIQUES)

    def cliques_for(self, cd: dict) -> List[dict]:
        return self.cliques.list(
            namespace=cd["metadata"]["namespace"],
            label_selector={CD_LABEL_KEY: cd["metadata"]["uid"]},
        )

    def sync(self, cd: dict) -> dict:
        """Recompute Status.Nodes + global status from clique registrations;
        persist when changed. Returns the updated CD."""
        nodes: List[dict] = []
        for clique in self.cliques_for(cd):
            clique_id = clique["metadata"]["name"].removeprefix(
                cd["metadata"]["uid"] + "."
            )
            for d in clique.get("daemons") or []:
                nodes.append(
                    {
                        "name": d.get("nodeName", ""),
                        "ipAddress": d.get("ipAddress", ""),
                        "cliqueID": d.get("cliqueID", clique_id),
                        "index": d.get("index", 0),
                        "status": d.get("status", ""),
                    }
                )
        nodes.sort(key=lambda n: (n["cliqueID"], n["index"]))
        num_ready = sum(1 for n in nodes if n["status"] == CD_STATUS_READY)
        want = cd["spec"]["numNodes"]
        status = CD_STATUS_READY if num_ready >= want else CD_STATUS_NOT_READY
        new_status = {"status": status, "nodes": nodes}
        if cd.get("status") != new_status:
            cd = self.cds.get(cd["metadata"]["name"], cd["metadata"]["namespace"])
            cd["status"] = new_status
            cd = self.cds.update_status(cd)
            log.info(
                "computedomain %s/%s status=%s (%d/%d nodes ready)",
                cd["metadata"]["namespace"],
                cd["metadata"]["name"],
                status,
                num_ready,
                want,
            )
        return cd

    def delete_cliques(self, cd: dict) -> bool:
        """Delete clique objects on CD teardown; True when all gone."""
        cliques = self.cliques_for(cd)
        for c in cliques:
            try:
                self.cliques.delete(
                    c["metadata"]["name"], c["metadata"]["namespace"]
                )
            except Exception:
                pass
        return not self.cliques_for(cd)
