"""ComputeDomain status aggregation.

Reference analog: cmd/compute-domain-controller/cdstatus.go (:135-241,
:286-354) + computedomain.go updateGlobalStatus (:251-280): clique daemon
registrations aggregate into ``CD.Status.Nodes``; the CD goes Ready when
every one of ``spec.numNodes`` expected hosts has registered **and**
reported Ready (all-or-nothing slice membership — stricter than IMEX's
incremental join, per JAX multi-host init semantics). Stale nodes (no
longer in any clique) are pruned.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Tuple

from tpu_dra.api import (
    CD_STATUS_FAILED,
    CD_STATUS_NOT_READY,
    CD_STATUS_READY,
    NODE_LOSS_FAIL_FAST,
    NODE_LOSS_SHRINK,
)
from tpu_dra.computedomain import CD_LABEL_KEY
from tpu_dra.infra import featuregates
from tpu_dra.k8sclient import (
    COMPUTE_DOMAIN_CLIQUES,
    COMPUTE_DOMAINS,
    PODS,
    ApiConflict,
    ResourceClient,
)

log = logging.getLogger(__name__)


class StatusManager:
    def __init__(
        self,
        backend,
        driver_namespace: str = "tpu-dra-driver",
        node_stale_after: float = 60.0,
    ):
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)
        self.cliques = ResourceClient(backend, COMPUTE_DOMAIN_CLIQUES)
        self.pods = ResourceClient(backend, PODS)
        self.driver_namespace = driver_namespace
        # A registration whose heartbeat went stale counts as NotReady
        # (crash liveness without relying on pod reaping — an improvement
        # over the reference, see registration.py). Staleness is measured
        # on the CONTROLLER's monotonic clock, from the moment it last saw
        # the entry's lastHeartbeatTime *value change* — never by comparing
        # the daemon's wall-clock stamp against ours, which would let
        # inter-node clock skew falsely mark live nodes NotReady (or mask
        # dead ones). Must be well above the daemons' heartbeat period;
        # <= 0 disables.
        self.node_stale_after = node_stale_after
        # (cd_uid, cliqueID, nodeName) -> (last seen heartbeat value,
        # monotonic time we first saw that value).
        self._observed: Dict[Tuple[str, str, str], Tuple[str, float]] = {}

    def _is_stale(
        self, cd_uid: str, clique_id: str, node_name: str, raw
    ) -> bool:
        """Has this entry's heartbeat stopped moving for longer than
        ``node_stale_after`` on OUR clock? Feeds both status derivation
        and (under nodeLossPolicy=shrink) clique pruning. Heartbeat-less
        entries (older drivers) stay live for upgrade compatibility."""
        if self.node_stale_after <= 0 or not raw:
            return False
        key = (cd_uid, clique_id, node_name)
        now = time.monotonic()
        prev = self._observed.get(key)
        if prev is None or prev[0] != raw:
            # New or changed value: the daemon wrote recently → alive.
            self._observed[key] = (raw, now)
            return False
        return now - prev[1] > self.node_stale_after

    def _apply_staleness(
        self, cd_uid: str, node: dict, entry: dict, stale_out: set
    ) -> dict:
        if self._is_stale(
            cd_uid,
            node.get("cliqueID", ""),
            node.get("name", ""),
            entry.get("lastHeartbeatTime"),
        ):
            node["status"] = CD_STATUS_NOT_READY
            stale_out.add((node.get("cliqueID", ""), node.get("name", "")))
        return node

    def _prune_observed(self, cd_uid: str, live_keys: set) -> None:
        for key in [
            k for k in self._observed
            if k[0] == cd_uid and k not in live_keys
        ]:
            del self._observed[key]

    def prune_domains(self, live_cd_uids: set) -> None:
        """Drop observed-heartbeat bookkeeping for ComputeDomains that no
        longer exist (a deleted CD is never synced again, so per-CD
        pruning alone would leak its keys forever). Called from the
        controller's periodic sync with the full CD list."""
        for key in [k for k in self._observed if k[0] not in live_cd_uids]:
            del self._observed[key]

    def cliques_for(self, cd: dict) -> List[dict]:
        return self.cliques.list(
            namespace=cd["metadata"]["namespace"],
            label_selector={CD_LABEL_KEY: cd["metadata"]["uid"]},
        )

    def _daemon_pod_node_names(self, cd: dict) -> set:
        """Nodes currently running a daemon pod for this CD
        (daemonsetpods.go analog — used to prune stale entries on the
        legacy path, where no clique object scopes liveness)."""
        pods = self.pods.list(
            namespace=self.driver_namespace,
            label_selector={CD_LABEL_KEY: cd["metadata"]["uid"]},
        )
        return {
            p["spec"].get("nodeName", "")
            for p in pods
            if not p["metadata"].get("deletionTimestamp")
        }

    def sync(self, cd: dict) -> dict:
        """Recompute Status.Nodes + global status from clique registrations
        (or, on the legacy ComputeDomainCliques=off path, take the
        daemon-written Status.Nodes pruned to live daemon pods —
        cdstatus.go:286-354); persist when changed. Returns the updated CD.

        Each attempt recomputes from a **fresh** read and writes with that
        read's resourceVersion: on the legacy path Status.Nodes is
        daemon-owned, so blind-overwriting with stale-derived data would
        erase concurrent daemon registrations (lost update). A conflict
        means a daemon won the race — re-derive and retry."""
        name, ns = cd["metadata"]["name"], cd["metadata"]["namespace"]
        # Fast path on the caller's (informer-cached) copy: skip the API
        # round-trips entirely when nothing would change.
        nodes, stale = self._derive_nodes(cd)
        if cd.get("status") == self._new_status(cd, nodes, stale):
            return cd
        for _ in range(20):
            cur = self.cds.try_get(name, ns)
            if cur is None:
                return cd
            nodes, stale = self._derive_nodes(cur)
            new_status = self._new_status(cur, nodes, stale)
            if cur.get("status") == new_status:
                return cur
            cur["status"] = new_status
            try:
                cur = self.cds.update_status(cur)
            except ApiConflict:
                continue
            log.info(
                "computedomain %s/%s status=%s (%d nodes)",
                ns, name, new_status["status"], len(nodes),
            )
            return cur
        log.warning(
            "computedomain %s/%s status sync: too many write conflicts; "
            "deferring to the next periodic sync", ns, name,
        )
        return cd

    def _derive_nodes(self, cd: dict) -> "Tuple[List[dict], set]":
        """(nodes, stale keys) — stale keys are the ``(cliqueID, name)``
        pairs whose heartbeat lapsed (a subset of the NotReady nodes)."""
        if featuregates.enabled(featuregates.COMPUTE_DOMAIN_CLIQUES):
            return self._nodes_from_cliques(cd)
        return self._nodes_from_status(cd)

    @staticmethod
    def _node_loss_policy(cd: dict) -> str:
        return cd["spec"].get("nodeLossPolicy") or NODE_LOSS_FAIL_FAST

    def _new_status(self, cd: dict, nodes: List[dict], stale: set) -> dict:
        """Readiness + node-loss policy:

        - assembling (never Ready): all-or-nothing — Ready only once
          ``spec.numNodes`` hosts registered AND report Ready (strict
          slice membership, per JAX multi-host init semantics);
        - ``failFast`` (default): a Ready domain that loses a member goes
          **Failed** promptly (and stays Failed until full strength is
          back) so consumers fail over instead of hanging in collectives;
        - ``shrink``: a Ready domain prunes lost (heartbeat-stale) members
          from its node list and stays Ready over the survivors as long
          as every one of them is Ready. A REPLACEMENT node that joins a
          shrunk domain registers NotReady while it boots — it must not
          count against readiness until it has been Ready once, or the
          join itself would flip the running domain to Failed (the exact
          disruption shrink exists to avoid)."""
        prev_status = cd.get("status") or {}
        prev = prev_status.get("status", "")
        policy = self._node_loss_policy(cd)
        required = cd["spec"]["numNodes"]
        if policy == NODE_LOSS_SHRINK and prev in (
            CD_STATUS_READY, CD_STATUS_FAILED
        ):
            kept = [
                n for n in nodes
                if (n.get("cliqueID", ""), n.get("name", "")) not in stale
            ]
            if kept:  # never shrink to an empty domain
                nodes = kept
            # Required = survivors (Ready in the previous status) plus
            # anyone Ready right now; a still-assembling joiner is
            # excluded until it first reports Ready.
            prev_ready = {
                (n.get("cliqueID", ""), n.get("name", ""))
                for n in prev_status.get("nodes") or []
                if n.get("status") == CD_STATUS_READY
            }
            required = max(1, sum(
                1 for n in nodes
                if n.get("status") == CD_STATUS_READY
                or (n.get("cliqueID", ""), n.get("name", "")) in prev_ready
            ))
        num_ready = sum(1 for n in nodes if n.get("status") == CD_STATUS_READY)
        if num_ready >= required:
            status = CD_STATUS_READY
        elif prev in (CD_STATUS_READY, CD_STATUS_FAILED):
            # Was whole, lost a member (or one went NotReady): that is a
            # failure, not re-assembly.
            status = CD_STATUS_FAILED
        else:
            status = CD_STATUS_NOT_READY
        return {"status": status, "nodes": nodes}

    def _nodes_from_cliques(self, cd: dict) -> "Tuple[List[dict], set]":
        uid = cd["metadata"]["uid"]
        nodes: List[dict] = []
        stale: set = set()
        for clique in self.cliques_for(cd):
            clique_id = clique["metadata"]["name"].removeprefix(uid + ".")
            for d in clique.get("daemons") or []:
                nodes.append(self._apply_staleness(
                    uid,
                    {
                        "name": d.get("nodeName", ""),
                        "ipAddress": d.get("ipAddress", ""),
                        "cliqueID": d.get("cliqueID", clique_id),
                        "index": d.get("index", 0),
                        "status": d.get("status", ""),
                    },
                    d,
                    stale,
                ))
        self._prune_observed(
            uid, {(uid, n["cliqueID"], n["name"]) for n in nodes}
        )
        nodes.sort(key=lambda n: (n["cliqueID"], n["index"]))
        return nodes, stale

    def _nodes_from_status(self, cd: dict) -> "Tuple[List[dict], set]":
        uid = cd["metadata"]["uid"]
        live = self._daemon_pod_node_names(cd)
        stale: set = set()
        nodes = [
            self._apply_staleness(uid, dict(n), n, stale)
            for n in (cd.get("status") or {}).get("nodes") or []
            if n.get("name") in live
        ]
        self._prune_observed(
            uid,
            {(uid, n.get("cliqueID", ""), n.get("name", "")) for n in nodes},
        )
        nodes.sort(key=lambda n: (n.get("cliqueID", ""), n.get("index", 0)))
        return nodes, stale

    def assign_slice_indices(self, cd: dict) -> None:
        """Pin gap-filled ``sliceIndex`` on cliques that lack one
        (multi-slice domains, cliques path). The leader-elected controller
        is the single writer, so two cliques can never both get 0 — the
        race daemon-side self-assignment across different objects would
        have. Deterministic order: creationTimestamp, then name."""
        if (cd["spec"].get("numSlices") or 1) <= 1:
            return
        if not featuregates.enabled(featuregates.COMPUTE_DOMAIN_CLIQUES):
            return  # legacy path CASes on the single CD status object
        for _ in range(5):
            cliques = self.cliques_for(cd)
            used = {
                c["sliceIndex"]
                for c in cliques
                if c.get("sliceIndex") is not None
            }
            missing = sorted(
                (c for c in cliques if c.get("sliceIndex") is None),
                key=lambda c: (
                    c["metadata"].get("creationTimestamp", ""),
                    c["metadata"]["name"],
                ),
            )
            if not missing:
                return
            conflicted = False
            for c in missing:
                idx = 0
                while idx in used:
                    idx += 1
                c["sliceIndex"] = idx
                try:
                    self.cliques.update(c)
                    used.add(idx)
                    log.info(
                        "pinned sliceIndex=%d on clique %s", idx,
                        c["metadata"]["name"],
                    )
                except ApiConflict:
                    conflicted = True  # daemon wrote the object; re-read
                    break
            if not conflicted:
                return

    def prune_lost_nodes(self, cd: dict) -> int:
        """nodeLossPolicy=shrink: physically remove heartbeat-stale daemon
        registrations from their clique objects so the clique SHRINKS — a
        replacement daemon gap-fills the freed index (stable DNS), and the
        dead entry stops haunting every future status derivation. Only a
        domain that has been whole (Ready/Failed) shrinks; during assembly
        a slow-to-boot host is not a lost host. Returns entries removed."""
        if self._node_loss_policy(cd) != NODE_LOSS_SHRINK:
            return 0
        if (cd.get("status") or {}).get("status") not in (
            CD_STATUS_READY, CD_STATUS_FAILED
        ):
            return 0
        uid = cd["metadata"]["uid"]
        removed = 0
        for clique in self.cliques_for(cd):
            clique_id = clique["metadata"]["name"].removeprefix(uid + ".")
            daemons = clique.get("daemons") or []
            kept = [
                d for d in daemons
                if not self._is_stale(
                    uid,
                    d.get("cliqueID", clique_id),
                    d.get("nodeName", ""),
                    d.get("lastHeartbeatTime"),
                )
            ]
            if len(kept) == len(daemons):
                continue
            clique["daemons"] = kept
            try:
                self.cliques.update(clique)
            except ApiConflict:
                continue  # a daemon wrote concurrently; next sync retries
            lost = {d.get("nodeName", "") for d in daemons} - {
                d.get("nodeName", "") for d in kept
            }
            removed += len(daemons) - len(kept)
            log.warning(
                "shrink: pruned lost node(s) %s from clique %s",
                sorted(lost), clique["metadata"]["name"],
            )
        return removed

    def delete_cliques(self, cd: dict) -> bool:
        """Delete clique objects on CD teardown; True when all gone."""
        cliques = self.cliques_for(cd)
        for c in cliques:
            try:
                self.cliques.delete(
                    c["metadata"]["name"], c["metadata"]["namespace"]
                )
            except Exception:
                pass
        return not self.cliques_for(cd)
