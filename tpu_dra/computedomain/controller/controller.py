"""The ComputeDomain reconciler.

Reference analog: cmd/compute-domain-controller/{controller.go,
computedomain.go} — a leader-elected loop (main.go:269-355) reconciling CD
objects through a coalescing work queue:

- add/update (computedomain.go:298-374): ensure finalizer, stamp the per-CD
  DaemonSet + both ResourceClaimTemplates, refresh aggregated status;
- delete (computedomain.go:314-348): strict teardown order with
  assert-removed barriers — delete RCTs, delete DS (finalizer removed only
  once its pods are gone), remove node labels, delete cliques, then drop
  the CD finalizer.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from tpu_dra.computedomain import CD_FINALIZER, CD_LABEL_KEY
from tpu_dra.computedomain.controller.daemonset import DaemonSetManager
from tpu_dra.computedomain.controller.node import NodeLabelManager
from tpu_dra.computedomain.controller.rct import ResourceClaimTemplateManager
from tpu_dra.computedomain.controller.status import StatusManager
from tpu_dra.infra.metrics import Metrics
from tpu_dra.infra.workqueue import (
    ShardedWorkQueue,
    default_controller_rate_limiter,
)
from tpu_dra.k8sclient import (
    COMPUTE_DOMAIN_CLIQUES,
    COMPUTE_DOMAINS,
    Informer,
    ResourceClient,
    install_read_fallback,
)

log = logging.getLogger(__name__)


class RetryLater(RuntimeError):
    """Reconcile barrier not yet met; the work queue re-enqueues."""


class ComputeDomainController:
    def __init__(
        self,
        backend,
        driver_namespace: str = "tpu-dra-driver",
        image: str = "tpu-dra-driver:latest",
        status_sync_period: float = 10.0,
        daemon_service_account: str = "",
        node_stale_after: float = 60.0,
        metrics: Optional[Metrics] = None,
        queue_shards: int = 8,
    ):
        self.metrics = metrics if metrics is not None else Metrics()
        self.backend = backend
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)
        self.daemonsets = DaemonSetManager(
            backend, driver_namespace, image,
            service_account=daemon_service_account,
        )
        self.rcts = ResourceClaimTemplateManager(
            backend, driver_namespace=driver_namespace
        )
        self.status = StatusManager(
            backend,
            driver_namespace=driver_namespace,
            node_stale_after=node_stale_after,
        )
        self.node_labels = NodeLabelManager(backend)
        # Sharded per domain (ISSUE 10): one hot domain — a flapping
        # clique storm, a teardown stuck on its RetryLater barriers —
        # used to serialize every other domain behind a single worker.
        # Dedup and shard routing both key on ns/name (see _enqueue for
        # why the UID must not route), so a domain's entire lifetime,
        # deletion and recreation included, stays on one queue.
        self.queue = ShardedWorkQueue(
            shards=queue_shards,
            rate_limiter_factory=default_controller_rate_limiter,
            metrics=self.metrics,
        )
        self.cd_informer = Informer(backend, COMPUTE_DOMAINS, metrics=self.metrics)
        self.clique_informer = Informer(
            backend, COMPUTE_DOMAIN_CLIQUES, metrics=self.metrics
        )
        self.status_sync_period = status_sync_period
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # --- lifecycle ---

    def start(self) -> None:
        self.cd_informer.add_handler(self._on_cd_event)
        self.clique_informer.add_handler(self._on_clique_event)
        self.cd_informer.start()
        self.clique_informer.start()
        # Degraded reads: while the apiserver circuit is open, get/list
        # for the watched resources serves stale from the informer
        # stores (reconcile decisions on slightly-old state beat a
        # controller frozen behind CircuitOpenError; writes still fail
        # fast and requeue).
        install_read_fallback(
            self.backend, [self.cd_informer, self.clique_informer]
        )
        self._threads.extend(self.queue.run_in_threads())
        t = threading.Thread(
            target=self._periodic_sync, daemon=True, name="cd-periodic-sync"
        )
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()
        self.cd_informer.stop()
        self.clique_informer.stop()

    def healthy(self) -> "tuple[bool, str]":
        """Liveness verdict for /healthz. A controller instance is
        single-use (stop() is permanent — lost leadership builds a FRESH
        instance, see main.py); so: not yet started = healthy standby,
        started = every worker thread must still be alive, stopped =
        healthy (a replacement is owned by the election loop)."""
        if not self._threads:
            return True, "standby (not leading)"
        if self._stop.is_set():
            return True, "stopped (not leading)"
        dead = [t.name for t in self._threads if not t.is_alive()]
        if dead:
            return False, f"dead worker threads: {dead}"
        return True, "ok"

    def _periodic_sync(self) -> None:
        """cdstatus.go:120-133 periodic sync + node.go label GC."""
        while not self._stop.wait(self.status_sync_period):
            try:
                cds = self.cds.list()
                self.metrics.set_gauge("compute_domains", len(cds))
                self.metrics.set_gauge(
                    "compute_domains_ready",
                    sum(
                        1 for c in cds
                        if (c.get("status") or {}).get("status") == "Ready"
                    ),
                )
                for cd in cds:
                    self._enqueue(cd)
                self.node_labels.cleanup_stale_labels()
                self.status.prune_domains(
                    {cd["metadata"]["uid"] for cd in cds}
                )
                n = self.daemonsets.delete_orphans(
                    {cd["metadata"]["uid"] for cd in cds}
                )
                if n:
                    log.info("GC'd %d orphaned CD daemonsets", n)
            except Exception:
                log.exception("periodic CD sync failed")

    # --- event plumbing ---

    def _key(self, cd: dict) -> str:
        return f"{cd['metadata']['namespace']}/{cd['metadata']['name']}"

    def _enqueue(self, cd: dict) -> None:
        # Shard key == dedup key (ns/name), NOT the UID: a domain
        # deleted and recreated changes UID, and routing the two
        # incarnations of one ns/name to different shards would let a
        # stale teardown retry run CONCURRENTLY with the new domain's
        # reconcile — the one-reconcile-in-flight-per-domain invariant
        # the dedup exists for. ns/name gives identical hot-domain
        # isolation (a hot domain IS one ns/name) without the race.
        self.queue.enqueue(cd, self._reconcile, key=self._key(cd))

    def _on_cd_event(self, event: str, cd: dict) -> None:
        if event == "DELETED":
            return  # finalizer flow handles teardown while it still exists
        self._enqueue(cd)

    def _on_clique_event(self, event: str, clique: dict) -> None:
        """Map a clique event to its owning CD via the CD informer's STORE
        (the lister), never a live REST list: informer handlers must not
        block on — or drop events to — apiserver weather
        (cdclique.go:36-139 uses a lister here for the same reason; a live
        list in this path dropped the decisive reconcile in round 3). If
        the CD isn't in the store yet (clique observed before the CD's own
        ADDED dispatch), dropping is safe: that pending ADDED, and the
        periodic sync, both enqueue it."""
        uid = (clique["metadata"].get("labels") or {}).get(CD_LABEL_KEY)
        if not uid:
            return
        cd = self.cd_informer.get_by_uid(uid)
        if cd is not None:
            self._enqueue(cd)

    # --- reconcile (computedomain.go:298-374) ---

    def _reconcile(self, cd_snapshot: dict) -> None:
        self.metrics.inc("reconciles_total")
        md = cd_snapshot["metadata"]
        cd = self.cds.try_get(md["name"], md["namespace"])
        if cd is None:
            return
        if cd["metadata"].get("deletionTimestamp"):
            self._teardown(cd)
            return
        # Ensure finalizer first (computedomain.go:351).
        fins = cd["metadata"].setdefault("finalizers", [])
        if CD_FINALIZER not in fins:
            fins.append(CD_FINALIZER)
            cd = self.cds.update(cd)
        self.rcts.create_or_update(cd)
        self.daemonsets.create_or_update(cd)
        self.status.assign_slice_indices(cd)
        # Node-loss handling (spec.nodeLossPolicy): under `shrink` a
        # Ready domain's heartbeat-stale registrations are pruned from
        # their cliques before status derivation, so the domain stays
        # Ready over the survivors; under `failFast` (default) the sync
        # below flips a degraded domain to Failed promptly.
        self.status.prune_lost_nodes(cd)
        self.status.sync(cd)

    def _teardown(self, cd: dict) -> None:
        """Strict deletion order with barriers (computedomain.go:314-348)."""
        self.rcts.request_delete(cd)
        self.daemonsets.request_delete(cd)
        self.node_labels.remove_labels_for(cd["metadata"]["uid"])
        if not self.rcts.finalize(cd):
            raise RetryLater("waiting for ResourceClaimTemplates to terminate")
        if not self.daemonsets.finalize_if_pods_gone(cd):
            raise RetryLater("waiting for daemon pods to terminate")
        if not self.status.delete_cliques(cd):
            raise RetryLater("waiting for cliques to terminate")
        # All dependents gone: drop our finalizer, completing deletion.
        cur = self.cds.try_get(
            cd["metadata"]["name"], cd["metadata"]["namespace"]
        )
        if cur is None:
            return
        cur["metadata"]["finalizers"] = [
            f for f in cur["metadata"].get("finalizers", []) if f != CD_FINALIZER
        ]
        self.cds.update(cur)
        log.info(
            "computedomain %s/%s fully removed",
            cd["metadata"]["namespace"],
            cd["metadata"]["name"],
        )
