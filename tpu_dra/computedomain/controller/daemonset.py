"""Per-CD DaemonSet management.

Reference analog: cmd/compute-domain-controller/daemonset.go — each
ComputeDomain gets one DaemonSet running the slice daemon, node-selected on
the ``resource.tpu.google.com/computeDomain=<cdUID>`` label (which the CD
kubelet plugin sets on nodes where workload channel claims land: "the CD
follows the workload", daemonset.go:189-253). Deletion is finalizer-ordered:
the DaemonSet finalizer is only removed once its pods are gone
(daemonset.go:317-366).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_dra.computedomain import CD_FINALIZER, CD_LABEL_KEY
from tpu_dra.infra import featuregates
from tpu_dra.k8sclient import (
    COMPUTE_DOMAINS,
    DAEMON_SETS,
    PODS,
    ApiNotFound,
    ResourceClient,
)

log = logging.getLogger(__name__)


class DaemonSetManager:
    def __init__(
        self,
        backend,
        driver_namespace: str,
        image: str = "tpu-dra-driver:latest",
        additional_namespaces: Optional[List[str]] = None,  # mnsdaemonset.go
        service_account: str = "",
    ):
        self.backend = backend
        self.daemonsets = ResourceClient(backend, DAEMON_SETS)
        self.pods = ResourceClient(backend, PODS)
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)
        self.driver_namespace = driver_namespace
        self.image = image
        # RBAC identity for daemon pods (clique registration needs write
        # access to ComputeDomainCliques); empty means the namespace default.
        self.service_account = service_account
        # mnsdaemonset.go analog: CDs may live in additional namespaces.
        self.namespaces = [driver_namespace] + (additional_namespaces or [])

    def delete_orphans(self, live_uids) -> int:
        """mnsdaemonset.go GC role: across every managed namespace, request
        deletion of CD-labeled DaemonSets whose ComputeDomain no longer
        exists (missed-finalizer safety net). Returns the count deleted."""
        n = 0
        for ns in self.namespaces:
            for ds in self.daemonsets.list(namespace=ns):
                uid = (ds["metadata"].get("labels") or {}).get(CD_LABEL_KEY)
                if not uid or uid in live_uids:
                    continue
                # live_uids is a snapshot: a CD created after it was taken
                # could already own this DS. Re-fetch via the DS annotations
                # before declaring it orphaned (TOCTOU guard).
                if self._cd_alive(ds, uid):
                    continue
                if not ds["metadata"].get("deletionTimestamp"):
                    try:
                        self.daemonsets.delete(ds["metadata"]["name"], ns)
                        n += 1
                    except ApiNotFound:
                        continue
                # With no CD left to drive the teardown reconcile, the GC
                # must also lift our finalizer once the pods are gone.
                cur = self.daemonsets.try_get(ds["metadata"]["name"], ns)
                if cur is not None:
                    self._strip_finalizer_if_pods_gone(cur, ns, uid)
        return n

    def _cd_alive(self, ds: dict, uid: str) -> bool:
        ann = ds["metadata"].get("annotations") or {}
        name = ann.get("resource.tpu.google.com/computeDomainName")
        ns = ann.get("resource.tpu.google.com/computeDomainNamespace")
        if not name or not ns:
            return False
        cd = self.cds.try_get(name, ns)
        return cd is not None and cd["metadata"].get("uid") == uid

    def name_for(self, cd: dict) -> str:
        return f"compute-domain-daemon-{cd['metadata']['uid'][:13]}"

    def render(self, cd: dict) -> dict:
        """templates/compute-domain-daemon.tmpl.yaml analog."""
        uid = cd["metadata"]["uid"]
        name = self.name_for(cd)
        labels = {
            "app.kubernetes.io/name": "compute-domain-daemon",
            CD_LABEL_KEY: uid,
        }
        return {
            "apiVersion": "apps/v1",
            "kind": "DaemonSet",
            "metadata": {
                "name": name,
                "namespace": self.driver_namespace,
                "labels": labels,
                "finalizers": [CD_FINALIZER],
                "annotations": {
                    "resource.tpu.google.com/computeDomainName": cd["metadata"][
                        "name"
                    ],
                    "resource.tpu.google.com/computeDomainNamespace": cd["metadata"][
                        "namespace"
                    ],
                },
            },
            "spec": {
                "selector": {"matchLabels": {CD_LABEL_KEY: uid}},
                "template": {
                    "metadata": {"labels": dict(labels)},
                    "spec": {
                        # Pods land only on nodes the workload touched
                        # ("CD follows workload").
                        "nodeSelector": {CD_LABEL_KEY: uid},
                        **(
                            {"serviceAccountName": self.service_account}
                            if self.service_account
                            else {}
                        ),
                        "tolerations": [
                            {"key": "google.com/tpu", "operator": "Exists"}
                        ],
                        "containers": [
                            {
                                "name": "compute-domain-daemon",
                                "image": self.image,
                                "command": ["tpu-compute-domain-daemon"],
                                # The container must reference the daemon
                                # claim or the kubelet never applies its CDI
                                # edits (the /tpu-cd config-dir mount).
                                "resources": {
                                    "claims": [{"name": "cd-daemon-claim"}]
                                },
                                "env": [
                                    {"name": "CD_UID", "value": uid},
                                    {
                                        "name": "CD_NAME",
                                        "value": cd["metadata"]["name"],
                                    },
                                    {
                                        "name": "CD_NAMESPACE",
                                        "value": cd["metadata"]["namespace"],
                                    },
                                    {
                                        "name": "NUM_NODES",
                                        "value": str(cd["spec"]["numNodes"]),
                                    },
                                    {
                                        "name": "NUM_SLICES",
                                        "value": str(
                                            cd["spec"].get("numSlices") or 1
                                        ),
                                    },
                                    {
                                        "name": "NODE_LOSS_POLICY",
                                        "value": (
                                            cd["spec"].get("nodeLossPolicy")
                                            or "failFast"
                                        ),
                                    },
                                    # Downward-API identity: without these
                                    # every daemon registers as '' and all
                                    # hosts collapse onto clique index 0.
                                    {
                                        "name": "NODE_NAME",
                                        "valueFrom": {
                                            "fieldRef": {
                                                "fieldPath": "spec.nodeName"
                                            }
                                        },
                                    },
                                    {
                                        "name": "POD_IP",
                                        "valueFrom": {
                                            "fieldRef": {
                                                "fieldPath": "status.podIP"
                                            }
                                        },
                                    },
                                    # Own-pod identity for the podmanager
                                    # readiness watcher (podmanager.go).
                                    {
                                        "name": "POD_NAME",
                                        "valueFrom": {
                                            "fieldRef": {
                                                "fieldPath": "metadata.name"
                                            }
                                        },
                                    },
                                    {
                                        "name": "POD_NAMESPACE",
                                        "valueFrom": {
                                            "fieldRef": {
                                                "fieldPath": "metadata.namespace"
                                            }
                                        },
                                    },
                                    # Propagate the controller's gate view so
                                    # daemon and controller pick the same
                                    # clique-vs-direct status path.
                                    {
                                        "name": "FEATURE_GATES",
                                        "value": ",".join(
                                            f"{k}={str(v).lower()}"
                                            for k, v in sorted(
                                                featuregates.to_map().items()
                                            )
                                        ),
                                    },
                                ],
                                # Probes exec the daemon's own check
                                # subcommand (template :72-94 analog).
                                "readinessProbe": {
                                    "exec": {
                                        "command": [
                                            "tpu-compute-domain-daemon",
                                            "check",
                                        ]
                                    },
                                    "periodSeconds": 5,
                                },
                            }
                        ],
                        "resourceClaims": [
                            {
                                "name": "cd-daemon-claim",
                                "resourceClaimTemplateName": daemon_rct_name(cd),
                            }
                        ],
                    },
                },
            },
        }

    def create_or_update(self, cd: dict) -> dict:
        want = self.render(cd)
        cur = self.daemonsets.try_get(
            want["metadata"]["name"], self.driver_namespace
        )
        if cur is None:
            return self.daemonsets.create(want)
        if cur["spec"] != want["spec"]:
            cur["spec"] = want["spec"]
            return self.daemonsets.update(cur)
        return cur

    def request_delete(self, cd: dict) -> None:
        try:
            self.daemonsets.delete(self.name_for(cd), self.driver_namespace)
        except ApiNotFound:
            pass

    def pods_gone(self, cd: dict) -> bool:
        pods = self.pods.list(
            namespace=self.driver_namespace,
            label_selector={CD_LABEL_KEY: cd["metadata"]["uid"]},
        )
        return not pods

    def _strip_finalizer_if_pods_gone(self, ds: dict, ns: str, uid: str) -> None:
        """Shared finalizer-removal semantics (daemonset.go:317-366): only
        once no daemon pod of the CD remains."""
        if CD_FINALIZER not in ds["metadata"].get("finalizers", []):
            return
        if self.pods.list(namespace=ns, label_selector={CD_LABEL_KEY: uid}):
            return
        ds["metadata"]["finalizers"] = [
            f for f in ds["metadata"]["finalizers"] if f != CD_FINALIZER
        ]
        self.daemonsets.update(ds)

    def finalize_if_pods_gone(self, cd: dict) -> bool:
        """Remove our finalizer from the DS once its pods are gone
        (daemonset.go:317-366); True when the DS is fully gone."""
        ds = self.daemonsets.try_get(self.name_for(cd), self.driver_namespace)
        if ds is None:
            return True
        if not ds["metadata"].get("deletionTimestamp"):
            return False
        self._strip_finalizer_if_pods_gone(
            ds, self.driver_namespace, cd["metadata"]["uid"]
        )
        return self.daemonsets.try_get(self.name_for(cd), self.driver_namespace) is None


def daemon_rct_name(cd: dict) -> str:
    # UID-scoped (resourceclaimtemplate.go:321 computedomain-daemon-<uid>):
    # the daemon RCT lives in the shared driver namespace, where same-named
    # CDs from different namespaces would collide on a name-derived key.
    return f"computedomain-daemon-{cd['metadata']['uid']}"
