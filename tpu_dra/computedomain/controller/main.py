"""compute-domain-controller entrypoint.

Reference analog: cmd/compute-domain-controller/main.go (:269-355) — a
leader-elected Deployment. Leader election uses a coordination.k8s.io Lease
(pkg/flags/leaderelection.go:25-85 analog).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from tpu_dra.computedomain.controller.controller import ComputeDomainController
from tpu_dra.infra import flags, signals
from tpu_dra.infra.leaderelection import LeaderElector  # noqa: F401
from tpu_dra.infra.metrics import Metrics, start_health_server

log = logging.getLogger(__name__)


# LeaderElector moved to tpu_dra.infra.leaderelection (shared with the
# DRA scheduler binary); re-exported here for existing importers.


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpu-compute-domain-controller")
    flags.add_version_flag(p)
    flags.KubeClientConfig.add_flags(p)
    flags.LoggingConfig.add_flags(p)
    flags.LeaderElectionConfig.add_flags(p)
    flags.add_feature_gate_flag(p)
    p.add_argument("--namespace", default=flags.env_default("NAMESPACE", "tpu-dra-driver"))
    p.add_argument("--image", default=flags.env_default("DAEMON_IMAGE", "tpu-dra-driver:latest"))
    p.add_argument(
        "--daemon-service-account",
        default=flags.env_default("DAEMON_SERVICE_ACCOUNT", ""),
        help="ServiceAccount for the per-CD daemon pods (clique RBAC)",
    )
    p.add_argument(
        "--node-stale-after",
        type=float,
        default=flags.env_default("NODE_STALE_AFTER", 60.0, float),
        help="Seconds after which a daemon registration with no heartbeat "
        "counts as NotReady (0 disables)",
    )
    p.add_argument(
        "--health-port",
        type=int,
        default=flags.env_default("HEALTH_PORT", 0, int),
        help="Serve /healthz + Prometheus /metrics (0 disables)",
    )
    args = p.parse_args(argv)
    flags.LoggingConfig.from_args(args).apply()
    signals.start_debug_signal_handlers()
    flags.apply_feature_gates(args)
    flags.log_startup_config(args)

    backend = flags.KubeClientConfig.from_args(args).new_client()
    metrics = Metrics()
    current: dict = {"controller": None}

    def build_controller() -> ComputeDomainController:
        # A controller instance is single-use (stop() permanently shuts
        # its queue/informers/threads): every leadership term gets a
        # FRESH one, the in-process equivalent of the reference exiting
        # the process so the pod restarts.
        c = ComputeDomainController(
            backend,
            driver_namespace=args.namespace,
            image=args.image,
            daemon_service_account=args.daemon_service_account,
            node_stale_after=args.node_stale_after,
            metrics=metrics,
        )
        current["controller"] = c
        return c

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    # Metrics/healthz endpoint (improvement over the reference, which has
    # no controller observability surface): reconcile counters + domain
    # gauges + leadership state, and a REAL liveness verdict for the
    # chart's probe — the leading instance's worker threads AND the
    # election thread itself (a dead election loop is a replica that
    # will never lead again; the probe must restart it).
    election: dict = {"thread": None}

    def healthz():
        t = election["thread"]
        if t is not None and not t.is_alive():
            return False, "leader-election thread dead"
        c = current["controller"]
        return c.healthy() if c is not None else (True, "standby")

    health_server = start_health_server(
        metrics, args.health_port, healthz=healthz
    )
    if health_server:
        log.info("metrics/healthz on :%d", health_server.port)

    le_config = flags.LeaderElectionConfig.from_args(args)
    if le_config.enabled:
        elector = LeaderElector(backend, le_config)

        def lead():
            controller = build_controller()
            metrics.set_gauge("leader", 1)
            controller.start()

            def stop_lead():
                metrics.set_gauge("leader", 0)
                # Domain gauges are only refreshed while leading; zero
                # them so a standby replica doesn't serve stale counts
                # as live data.
                metrics.set_gauge("compute_domains", 0)
                metrics.set_gauge("compute_domains_ready", 0)
                controller.stop()

            return stop_lead

        t = threading.Thread(target=elector.run_leading, args=(lead,), daemon=True)
        t.start()
        election["thread"] = t
        stop.wait()
        elector.stop()
    else:
        controller = build_controller()
        metrics.set_gauge("leader", 1)
        controller.start()
        stop.wait()
        controller.stop()
    if health_server:
        health_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
