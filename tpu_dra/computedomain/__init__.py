"""ComputeDomain subsystem: multi-host ICI pod-slice orchestration.

Reference analog: cmd/compute-domain-{controller,daemon,kubelet-plugin} —
the IMEX/Multi-Node-NVLink domain machinery, re-targeted at TPU slices:

- A **ComputeDomain** is a multi-host workload domain over an ICI pod slice
  (DCN across slices). No proprietary daemon to babysit: instead of
  supervising ``nvidia-imex``, the per-node slice daemon discovers local
  topology, registers into the ComputeDomainClique CRD with a stable index,
  and renders the JAX/libtpu multi-host bootstrap config (worker ids, peer
  hostnames, coordinator address) that the CD kubelet plugin injects into
  workload pods via CDI.
- A **clique** is one physical ICI domain (pod slice), named
  ``<cdUID>.<cliqueID>`` where cliqueID comes from tpulib
  (sliceUUID.partition — the NVLink clusterUUID.cliqueId analog).
- Readiness gating is identical in shape to the reference: workload pods
  stay in ContainerCreating until every expected host has registered and
  reported Ready — but gate on *complete* slice membership, because JAX
  multi-host init is all-or-nothing per slice (unlike IMEX's incremental
  join).
"""

CD_LABEL_KEY = "resource.tpu.google.com/computeDomain"
CD_FINALIZER = "resource.tpu.google.com/computedomain-finalizer"
CD_DRIVER_NAME = "compute-domain.tpu.google.com"

DAEMON_DEVICE_CLASS = "compute-domain-daemon.tpu.google.com"
CHANNEL_DEVICE_CLASS = "compute-domain-default-channel.tpu.google.com"

# Abstract channel devices advertised per node (nvlib.go:358-361 analog).
NUM_CHANNELS = 2048
