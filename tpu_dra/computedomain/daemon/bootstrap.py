"""JAX/libtpu multi-host bootstrap rendering.

This is the heart of the TPU re-imagining: where the reference's daemon
writes an IMEX config + nodes.cfg for the proprietary daemon
(cmd/compute-domain-daemon/main.go:454-517), the TPU daemon renders the
environment a JAX workload needs to run multi-host over the slice:

- ``TPU_WORKER_ID``        — this host's stable index in the domain
- ``TPU_WORKER_HOSTNAMES`` — all peers' stable DNS names, index order
- ``TPU_ACCELERATOR_TYPE`` / ``TPU_TOPOLOGY`` — slice shape
- ``JAX_COORDINATOR_ADDRESS`` — daemon-0's stable DNS name (the
  distributed-init rendezvous; stability across restarts is exactly why
  index assignment is gap-filling, cdclique.go:350-372 analog)
- ``MEGASCALE_*`` — DCN coordinator settings for multi-slice domains

The rendered file lands in the per-CD config dir the CD kubelet plugin
mounts into workload containers (device_state.go:516-573 analog: the
``/imexd`` mount becomes ``/tpu-cd``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from tpu_dra.computedomain.daemon.dnsnames import dns_name

COORDINATOR_PORT = 8476
MEGASCALE_PORT = 8477


def render_bootstrap_env(
    worker_id: int,
    num_nodes: int,
    accelerator_type: str,
    topology: str,
    peers: List[dict],
    num_slices: int = 1,
    slice_index: int = 0,
) -> Dict[str, str]:
    hostnames = ",".join(dns_name(i) for i in range(num_nodes))
    env = {
        "TPU_WORKER_ID": str(worker_id),
        "TPU_WORKER_HOSTNAMES": hostnames,
        "TPU_ACCELERATOR_TYPE": accelerator_type,
        "TPU_TOPOLOGY": topology,
        "JAX_COORDINATOR_ADDRESS": f"{dns_name(0)}:{COORDINATOR_PORT}",
        "JAX_NUM_PROCESSES": str(num_nodes),
        "JAX_PROCESS_ID": str(worker_id),
    }
    if num_slices > 1:
        # Multi-slice (DCN) domains: megascale coordinator on slice 0.
        env.update(
            {
                "MEGASCALE_COORDINATOR_ADDRESS": f"{dns_name(0)}:{MEGASCALE_PORT}",
                "MEGASCALE_NUM_SLICES": str(num_slices),
                "MEGASCALE_SLICE_ID": str(slice_index),
            }
        )
    return env


def write_bootstrap_files(
    config_dir: str,
    env: Dict[str, str],
    peers: List[dict],
) -> None:
    """Write bootstrap.env (KEY=VALUE lines), peers.json, and hosts
    fragments into the per-CD config dir."""
    os.makedirs(config_dir, exist_ok=True)
    tmp = os.path.join(config_dir, ".bootstrap.env.tmp")
    with open(tmp, "w") as f:
        for k, v in sorted(env.items()):
            f.write(f"{k}={v}\n")
    os.replace(tmp, os.path.join(config_dir, "bootstrap.env"))
    tmp = os.path.join(config_dir, ".peers.json.tmp")
    with open(tmp, "w") as f:
        json.dump(
            [
                {
                    "index": p.get("index", 0),
                    "nodeName": p.get("nodeName", ""),
                    "ipAddress": p.get("ipAddress", ""),
                    "dnsName": dns_name(p.get("index", 0)),
                    "status": p.get("status", ""),
                }
                for p in peers
            ],
            f,
            indent=2,
        )
    os.replace(tmp, os.path.join(config_dir, "peers.json"))


def read_bootstrap_env(config_dir: str) -> Optional[Dict[str, str]]:
    path = os.path.join(config_dir, "bootstrap.env")
    try:
        with open(path) as f:
            out = {}
            for line in f:
                line = line.strip()
                if line and "=" in line:
                    k, _, v = line.partition("=")
                    out[k] = v
            return out
    except FileNotFoundError:
        return None
