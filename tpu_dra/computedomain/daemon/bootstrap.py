"""JAX/libtpu multi-host bootstrap rendering.

This is the heart of the TPU re-imagining: where the reference's daemon
writes an IMEX config + nodes.cfg for the proprietary daemon
(cmd/compute-domain-daemon/main.go:454-517), the TPU daemon renders the
environment a JAX workload needs to run multi-host over the slice:

- ``TPU_WORKER_ID``        — this host's stable index in the domain
- ``TPU_WORKER_HOSTNAMES`` — all peers' stable DNS names, index order
- ``TPU_ACCELERATOR_TYPE`` / ``TPU_TOPOLOGY`` — slice shape
- ``JAX_COORDINATOR_ADDRESS`` — daemon-0's stable DNS name (the
  distributed-init rendezvous; stability across restarts is exactly why
  index assignment is gap-filling, cdclique.go:350-372 analog)
- ``MEGASCALE_*`` — DCN coordinator settings for multi-slice domains

The rendered file lands in the per-CD config dir the CD kubelet plugin
mounts into workload containers (device_state.go:516-573 analog: the
``/imexd`` mount becomes ``/tpu-cd``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from tpu_dra.computedomain.daemon.dnsnames import dns_name

COORDINATOR_PORT = 8476
MEGASCALE_PORT = 8477


def render_bootstrap_env(
    worker_id: int,
    num_nodes: int,
    accelerator_type: str,
    topology: str,
    peers: List[dict],
    num_slices: int = 1,
    slice_index: int = 0,
    megascale_coordinator_ip: Optional[str] = None,
    coordinator_port: int = COORDINATOR_PORT,
) -> Dict[str, str]:
    """``num_nodes`` is domain-global (spec.numNodes); ``worker_id`` is the
    host's **slice-local** index (its clique registration index — each ICI
    pod slice forms one clique, and DNS names/peers/hosts mappings are
    slice-local). The libtpu/JAX identity (TPU_WORKER_ID, hostnames,
    coordinator) therefore spans one slice, while MEGASCALE_* spans slices
    over DCN — its coordinator is addressed by **pod IP**, never by the
    shared DNS names, which each slice's /etc/hosts maps to its own peers
    and so cannot resolve across slices."""
    if num_nodes < 1 or num_slices < 1:
        raise ValueError("num_nodes and num_slices must be >= 1")
    if num_nodes % num_slices:
        raise ValueError(
            f"numNodes ({num_nodes}) must be divisible by numSlices "
            f"({num_slices})"
        )
    per_slice = num_nodes // num_slices
    if not 0 <= worker_id < per_slice:
        # An index past the slice size means more hosts registered into the
        # clique than numNodes/numSlices allows (numSlices misconfigured, or
        # hosts without ICI identity collapsing onto one fallback clique).
        # Aliasing it would hand two workers the same identity — fail loud.
        raise ValueError(
            f"worker index {worker_id} out of range for a "
            f"{per_slice}-host slice (numNodes={num_nodes}, "
            f"numSlices={num_slices})"
        )
    local_id = worker_id
    hostnames = ",".join(dns_name(i) for i in range(per_slice))
    env = {
        "TPU_WORKER_ID": str(local_id),
        "TPU_WORKER_HOSTNAMES": hostnames,
        "TPU_ACCELERATOR_TYPE": accelerator_type,
        "TPU_TOPOLOGY": topology,
        "JAX_COORDINATOR_ADDRESS": f"{dns_name(0)}:{coordinator_port}",
        "JAX_NUM_PROCESSES": str(per_slice),
        "JAX_PROCESS_ID": str(local_id),
    }
    if num_slices > 1:
        # Multi-slice (DCN) domains: megascale coordinator on slice 0's
        # index-0 host, addressed by pod IP — the shared DNS names resolve
        # slice-locally via /etc/hosts, so a name cannot reach across
        # slices. Until slice 0 has registered the IP is unknown and the
        # variable is omitted; the daemon re-renders every tick and the
        # readiness gate holds workloads until the domain is complete.
        env.update(
            {
                "MEGASCALE_NUM_SLICES": str(num_slices),
                "MEGASCALE_SLICE_ID": str(slice_index),
            }
        )
        if megascale_coordinator_ip:
            env["MEGASCALE_COORDINATOR_ADDRESS"] = (
                f"{megascale_coordinator_ip}:{MEGASCALE_PORT}"
            )
    return env


def write_bootstrap_files(
    config_dir: str,
    env: Dict[str, str],
    peers: List[dict],
) -> None:
    """Write bootstrap.env (KEY=VALUE lines), peers.json, and hosts
    fragments into the per-CD config dir."""
    os.makedirs(config_dir, exist_ok=True)
    tmp = os.path.join(config_dir, ".bootstrap.env.tmp")
    with open(tmp, "w") as f:
        for k, v in sorted(env.items()):
            f.write(f"{k}={v}\n")
    os.replace(tmp, os.path.join(config_dir, "bootstrap.env"))
    tmp = os.path.join(config_dir, ".peers.json.tmp")
    with open(tmp, "w") as f:
        json.dump(
            [
                {
                    "index": p.get("index", 0),
                    "nodeName": p.get("nodeName", ""),
                    "ipAddress": p.get("ipAddress", ""),
                    "dnsName": dns_name(p.get("index", 0)),
                    "status": p.get("status", ""),
                }
                for p in peers
            ],
            f,
            indent=2,
        )
    os.replace(tmp, os.path.join(config_dir, "peers.json"))


def read_bootstrap_env(config_dir: str) -> Optional[Dict[str, str]]:
    path = os.path.join(config_dir, "bootstrap.env")
    try:
        with open(path) as f:
            out = {}
            for line in f:
                line = line.strip()
                if line and "=" in line:
                    k, _, v = line.partition("=")
                    out[k] = v
            return out
    except FileNotFoundError:
        return None
