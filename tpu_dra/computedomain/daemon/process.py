"""Child-process manager with a polling watchdog.

Reference analog: cmd/compute-domain-daemon/process.go (:49-221) — start /
stop / restart / signal a child process, SIGCHLD-free 1 s polling watchdog
that restarts on crash, graceful SIGTERM then SIGKILL on stop.

The TPU daemon has no proprietary binary to babysit, but the manager is
used for optional pluggable helpers (e.g. an ICI link prober) and keeps the
supervision semantics available for operators that need a sidecar process.
"""

from __future__ import annotations

import logging
import signal
import subprocess
import threading
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class ProcessManager:
    def __init__(
        self,
        argv: List[str],
        restart_on_exit: bool = True,
        watchdog_tick: float = 1.0,
        on_restart: Optional[Callable[[int], None]] = None,
    ):
        self.argv = argv
        self.restart_on_exit = restart_on_exit
        self.watchdog_tick = watchdog_tick
        self.on_restart = on_restart
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None  # thread: daemon-main
        self.restarts = 0  # thread: pm-watchdog (sole writer; read via on_restart on the same thread)

    def ensure_started(self) -> None:  # thread: daemon-main
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._proc = subprocess.Popen(self.argv)
            log.info("started %s (pid %d)", self.argv[0], self._proc.pid)
        if self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._watch, daemon=True, name="process-watchdog"
            )
            self._watchdog.start()

    # thread: pm-watchdog (entry: the watchdog thread target)
    def _watch(self) -> None:
        """1s-tick polling watchdog (process.go:169-204)."""
        while not self._stop.wait(self.watchdog_tick):
            with self._lock:
                proc = self._proc
            if proc is None:
                continue
            rc = proc.poll()
            if rc is None:
                continue
            if not self.restart_on_exit or self._stop.is_set():
                continue
            log.warning(
                "%s exited with %d; restarting", self.argv[0], rc
            )
            self.restarts += 1
            if self.on_restart is not None:
                self.on_restart(self.restarts)
            with self._lock:
                self._proc = subprocess.Popen(self.argv)

    def signal(self, sig: int) -> None:  # thread: any (lock-guarded)
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(sig)

    def is_running(self) -> bool:  # thread: any (lock-guarded)
        with self._lock:
            return self._proc is not None and self._proc.poll() is None

    def stop(self, term_timeout: float = 5.0) -> None:  # thread: daemon-main
        """Graceful SIGTERM, then SIGKILL (process.go stop semantics)."""
        self._stop.set()
        with self._lock:
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=term_timeout)
        except subprocess.TimeoutExpired:
            log.warning("%s ignored SIGTERM; killing", self.argv[0])
            proc.kill()
            proc.wait(timeout=5)
