"""Own-pod readiness watcher.

Reference analog: cmd/compute-domain-daemon/podmanager.go:32-149 — the daemon
watches its *own* pod's Ready condition (which kubelet computes from the
readiness probe that execs ``tpu-compute-domain-daemon check``) and
propagates that into the clique/status registration. Registration readiness
therefore reflects what the cluster sees, not just the daemon's local view:
local membership+health -> ready file -> probe -> pod Ready condition ->
registration status. This ordering is what keeps the flow non-circular.
"""

from __future__ import annotations

import logging
from typing import Optional

from tpu_dra.k8sclient import PODS, ResourceClient

log = logging.getLogger(__name__)


class PodManager:
    def __init__(self, backend, namespace: str, pod_name: str):
        self.pods = ResourceClient(backend, PODS)
        self.namespace = namespace
        self.pod_name = pod_name

    def pod_ready(self) -> Optional[bool]:
        """The pod's Ready condition; None when the pod or condition cannot
        be observed (caller falls back to its local readiness view)."""
        if not self.pod_name:
            return None
        try:
            pod = self.pods.try_get(self.pod_name, self.namespace)
        except Exception:
            log.exception("cannot read own pod %s/%s", self.namespace, self.pod_name)
            return None
        if pod is None:
            return None
        for cond in (pod.get("status") or {}).get("conditions") or []:
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return None
