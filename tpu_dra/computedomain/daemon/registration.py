"""Shared daemon-registration machinery.

Both registration paths — clique CRD objects (ComputeDomainCliques=on,
cdclique.go) and direct CD.Status writes (gate off, cdstatus.go:223-333) —
are the same state machine: conflict-retried read-modify-writes inserting or
mutating *our* entry in a shared list, with gap-filled stable indices. The
subclasses supply only where the list lives and how it persists.
"""

from __future__ import annotations

import datetime
import logging
import time
from typing import Dict, List, Optional, Tuple

from tpu_dra.api import CD_STATUS_NOT_READY, CD_STATUS_READY
from tpu_dra.k8sclient import ApiConflict

log = logging.getLogger(__name__)

MAX_CONFLICT_RETRIES = 20

# How often a registered daemon refreshes its entry's lastHeartbeatTime.
# Liveness via heartbeats is an improvement over the reference, whose
# crash detection leans entirely on the pod lifecycle (daemonsetpods.go):
# with heartbeats the controller can mark a hard-crashed host NotReady
# even where no kubelet reaps a pod (and the no-cluster e2e stack has no
# pods at all). Keep this well under the controller's --node-stale-after.
DEFAULT_HEARTBEAT_PERIOD = 10.0


def now_iso() -> str:
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )


def heartbeat_age_seconds(entry: dict) -> Optional[float]:
    """Age of an entry's heartbeat, or None when it has none (written by
    an older driver — treated as always-live for upgrade compatibility)."""
    raw = entry.get("lastHeartbeatTime")
    if not raw:
        return None
    try:
        t = datetime.datetime.fromisoformat(raw.replace("Z", "+00:00"))
    except ValueError:
        return None
    return (
        datetime.datetime.now(datetime.timezone.utc) - t
    ).total_seconds()

# Sentinel: the subclass handled a missing parent object but the write
# raced; re-run the retry loop.
RETRY = object()


class MultisliceIdentityPending(RuntimeError):
    """Raised when a daemon's slice identity (MEGASCALE_SLICE_ID /
    coordinator) is not yet resolved; the caller degrades to NotReady and
    retries next tick rather than publishing an aliased identity."""


def assign_gap_filled_index(entries: List[dict]) -> int:
    """Smallest free index — gap-filling keeps indices (and the DNS names
    derived from them) stable across daemon restarts (cdclique.go:350-372)."""
    used = {e.get("index", 0) for e in entries}
    i = 0
    while i in used:
        i += 1
    return i


class RegistrationBase:
    """Template for clique/direct-status registration.

    Subclasses define: ``node_key`` (the entry field naming the node),
    ``_fetch()``, ``_persist(obj)``, ``_entries(obj)``, ``_describe()``,
    and either ``_on_missing_register()`` (create or raise) or accept the
    default raise.
    """

    node_key = "nodeName"

    def __init__(
        self,
        node_name: str,
        ip_address: str,
        clique_id: str,
        heartbeat_period: float = DEFAULT_HEARTBEAT_PERIOD,
    ):
        self.node_name = node_name
        self.ip_address = ip_address
        self.clique_id = clique_id
        self.heartbeat_period = heartbeat_period
        self.index: Optional[int] = None
        # Peer-liveness bookkeeping for lost_peers(): peer node name ->
        # (last seen heartbeat value, monotonic time we first saw it).
        self._peer_observed: Dict[str, Tuple[str, float]] = {}

    # --- subclass surface ---

    def _fetch(self) -> Optional[dict]:
        raise NotImplementedError

    def _persist(self, obj: dict) -> None:
        raise NotImplementedError

    def _entries(self, obj: dict) -> List[dict]:
        raise NotImplementedError

    def _describe(self) -> str:
        raise NotImplementedError

    def _on_missing_register(self):
        """Parent object absent during register(): return an index, RETRY,
        or raise."""
        raise RuntimeError(f"{self._describe()} not found")

    def _entry(self, index: int, status: str) -> dict:
        return {
            self.node_key: self.node_name,
            "ipAddress": self.ip_address,
            "cliqueID": self.clique_id,
            "index": index,
            "status": status,
            "lastHeartbeatTime": now_iso(),
        }

    def _scope(self, entries: List[dict]) -> List[dict]:
        """Restrict to OUR slice's entries. Identity where the fetched
        object is already slice-scoped (a clique); the legacy CD.Status path
        overrides to filter the domain-wide node list by cliqueID — indices
        and peers are always slice-local."""
        return entries

    def multislice_info(self):
        """(pinned slice index, megascale coordinator IP or None).
        Single-slice default."""
        return 0, None

    # --- shared state machine ---

    def register(self) -> int:
        """Insert or refresh our entry; returns our stable index."""
        for _ in range(MAX_CONFLICT_RETRIES):
            obj = self._fetch()
            if obj is None:
                got = self._on_missing_register()
                if got is RETRY:
                    continue
                return got
            entries = self._entries(obj)
            mine = next(
                (e for e in entries if e.get(self.node_key) == self.node_name),
                None,
            )
            if mine is not None:
                self.index = mine.get("index", 0)
                age = heartbeat_age_seconds(mine)
                fresh = age is not None and age < self.heartbeat_period
                if mine.get("ipAddress") == self.ip_address and fresh:
                    return self.index
                # Reclaiming a dead predecessor's entry (pod restart: IP
                # changed, or the heartbeat lapsed for several periods)
                # must reset its status — refreshing the heartbeat while
                # the old 'Ready' lingers would un-suppress the entry and
                # let the domain flip Ready before this daemon validated
                # anything. A merely *due* heartbeat is not a reclaim.
                lapsed = (
                    self.heartbeat_period > 0
                    and age is not None
                    and age > 3 * self.heartbeat_period
                )
                if mine.get("ipAddress") != self.ip_address or lapsed:
                    mine["status"] = CD_STATUS_NOT_READY
                mine["ipAddress"] = self.ip_address
                mine["lastHeartbeatTime"] = now_iso()
            else:
                self.index = assign_gap_filled_index(self._scope(entries))
                entries.append(self._entry(self.index, CD_STATUS_NOT_READY))
            try:
                self._persist(obj)
                return self.index
            except ApiConflict:
                continue
        raise RuntimeError(
            f"could not register {self.node_name} into {self._describe()}: "
            f"too many write conflicts"
        )

    def set_status(self, ready: bool) -> None:
        want = CD_STATUS_READY if ready else CD_STATUS_NOT_READY
        for _ in range(MAX_CONFLICT_RETRIES):
            obj = self._fetch()
            if obj is None:
                return
            changed = False
            for e in self._entries(obj):
                if e.get(self.node_key) == self.node_name and e.get("status") != want:
                    e["status"] = want
                    changed = True
            if not changed:
                return
            try:
                self._persist(obj)
                return
            except ApiConflict:
                continue

    def peers(self) -> List[dict]:
        obj = self._fetch()
        if obj is None:
            return []
        return sorted(
            self._scope(self._entries(obj)), key=lambda e: e.get("index", 0)
        )

    def lost_peers(
        self,
        stale_after: Optional[float] = None,
        peers: Optional[List[dict]] = None,
    ) -> List[dict]:
        """Registered peers (not us) whose heartbeat STOPPED MOVING for
        longer than ``stale_after`` (default: 3 heartbeat periods — the
        same reclaim threshold register() uses). This is the daemon-side
        view of a lost ICI neighbor, feeding the node-loss policy: a
        ``failFast`` domain's daemons flip NotReady promptly instead of
        hanging the workload in a collective; a ``shrink`` domain's
        controller prunes the entry and the survivors keep going.

        Staleness is measured like the controller's StatusManager: on OUR
        monotonic clock, from when we last saw the peer's heartbeat VALUE
        change — never by comparing the peer's wall-clock stamp against
        ours, which would let inter-node clock skew declare live peers
        lost and fail a healthy domain. Heartbeat-less entries (older
        drivers) are never counted lost. Pass ``peers`` to reuse an
        already-fetched registration list instead of re-reading the
        object."""
        cutoff = (
            stale_after if stale_after is not None
            else 3 * self.heartbeat_period
        )
        if cutoff <= 0:
            return []
        now = time.monotonic()
        out = []
        live_names = set()
        for e in peers if peers is not None else self.peers():
            name = e.get(self.node_key)
            if name == self.node_name:
                continue
            live_names.add(name)
            raw = e.get("lastHeartbeatTime")
            if not raw:
                continue  # older-driver entry: always live
            prev = self._peer_observed.get(name)
            if prev is None or prev[0] != raw:
                self._peer_observed[name] = (raw, now)
            elif now - prev[1] > cutoff:
                out.append(e)
        # Deregistered peers must not pin stale bookkeeping forever.
        for name in [n for n in self._peer_observed if n not in live_names]:
            del self._peer_observed[name]
        return out

    def deregister(self) -> None:
        for _ in range(MAX_CONFLICT_RETRIES):
            obj = self._fetch()
            if obj is None:
                return
            entries = self._entries(obj)
            kept = [e for e in entries if e.get(self.node_key) != self.node_name]
            if len(kept) == len(entries):
                return
            entries[:] = kept
            try:
                self._persist(obj)
                return
            except ApiConflict:
                continue
