"""Slice-daemon run loop.

Reference analog: cmd/compute-domain-daemon/main.go — run (:206-339): label
own pod, write config, register into the clique, then the update loop
(:376-423, DNS-names mode): refresh /etc/hosts from peers, re-render
bootstrap config on membership change, and report readiness. Readiness here
means **complete slice membership** — all ``numNodes`` peers registered and
the local ICI fabric healthy — probed by the ``check`` subcommand the pod's
readiness probe execs (template :72-94 analog, replacing
``nvidia-imex-ctl -q``).
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
from dataclasses import dataclass

from tpu_dra.computedomain.daemon.bootstrap import (
    render_bootstrap_env,
    write_bootstrap_files,
)
from tpu_dra.computedomain.daemon.clique import CliqueRegistration
from tpu_dra.computedomain.daemon.dnsnames import DNSNameManager
from tpu_dra.computedomain.daemon.podmanager import PodManager
from tpu_dra.api import NODE_LOSS_FAIL_FAST, NODE_LOSS_SHRINK
from tpu_dra.computedomain.daemon.registration import MultisliceIdentityPending
from tpu_dra.computedomain.daemon.status_legacy import DirectStatusRegistration
from tpu_dra.infra import featuregates, flags, signals
from tpu_dra.tpulib import new_tpulib
from tpu_dra.tpulib.types import topology_str

log = logging.getLogger(__name__)

READY_FILE = "ready"


@dataclass
class DaemonConfig:
    cd_uid: str
    cd_name: str
    cd_namespace: str
    num_nodes: int
    node_name: str
    pod_ip: str
    config_dir: str = "/tpu-cd"
    hosts_path: str = "/etc/hosts"
    update_period: float = 2.0
    heartbeat_period: float = 10.0
    num_slices: int = 1
    # 0 = the default rendezvous port; overridable so co-located test
    # daemons (or multiple domains on one host network) don't collide.
    coordinator_port: int = 0
    pod_name: str = ""
    pod_namespace: str = ""
    # Mirrors CD spec.nodeLossPolicy (rendered into the DaemonSet env):
    # failFast = a lost ICI neighbor flips us NotReady promptly; shrink =
    # keep serving the survivors after the controller prunes the loss.
    node_loss_policy: str = NODE_LOSS_FAIL_FAST


class SliceDaemon:
    def __init__(self, config: DaemonConfig, backend, tpulib=None):
        self.config = config
        self.backend = backend
        self.tpulib = tpulib or new_tpulib()
        ici = self.tpulib.ici_domain()
        self.clique_id = ici.clique_id() if ici else "local.0"
        if featuregates.enabled(featuregates.COMPUTE_DOMAIN_CLIQUES):
            self.registration = CliqueRegistration(
                backend,
                cd_uid=config.cd_uid,
                cd_namespace=config.cd_namespace,
                clique_id=self.clique_id,
                node_name=config.node_name,
                ip_address=config.pod_ip,
                heartbeat_period=config.heartbeat_period,
            )
        else:
            # Legacy path (cdstatus.go): write directly into CD.Status.
            self.registration = DirectStatusRegistration(
                backend,
                cd_uid=config.cd_uid,
                cd_name=config.cd_name,
                cd_namespace=config.cd_namespace,
                clique_id=self.clique_id,
                node_name=config.node_name,
                ip_address=config.pod_ip,
                heartbeat_period=config.heartbeat_period,
            )
        self.podmanager = PodManager(
            backend, config.pod_namespace or config.cd_namespace,
            config.pod_name,
        )
        self.dns = DNSNameManager(hosts_path=config.hosts_path)
        self._stop = threading.Event()
        self._ready = False
        # Latched the first time the slice is whole; shrink semantics only
        # apply to a slice that HAS been whole (assembly stays strict).
        self._was_ready = False

    # --- readiness ---

    def compute_ready(self, peers) -> bool:
        """All expected hosts registered + no lost neighbors + local chips
        healthy (the all-or-nothing slice-membership gate). Peers are
        slice-local, so the expectation is per-slice; domain-wide
        readiness is the controller's aggregation across cliques.

        Node-loss policy: under ``failFast`` a peer whose heartbeat lapsed
        (3 periods — the same reclaim threshold register() uses) flips us
        NotReady on the next tick, so the domain fails promptly instead of
        the workload hanging in a collective until the controller's
        staleness window fires. Under ``shrink``, once this slice has been
        whole the expectation follows the (controller-pruned) registration
        list down — the survivors stay Ready."""
        expected = max(
            1, self.config.num_nodes // max(1, self.config.num_slices)
        )
        if (
            self.config.node_loss_policy == NODE_LOSS_SHRINK
            and self._was_ready
        ):
            expected = min(expected, max(1, len(peers)))
        if len(peers) < expected:
            return False
        if self.config.node_loss_policy != NODE_LOSS_SHRINK:
            lost = self.registration.lost_peers(peers=peers)
            if lost:
                log.warning(
                    "lost ICI neighbor(s) %s (heartbeat stale): failing fast",
                    [e.get(self.registration.node_key) for e in lost],
                )
                return False
        if not all(c.healthy for c in self.tpulib.chips()):
            return False
        return True

    def _write_ready_file(self, ready: bool) -> None:
        path = os.path.join(self.config.config_dir, READY_FILE)
        if ready:
            with open(path, "w") as f:
                f.write("ready\n")
        else:
            try:
                os.remove(path)
            except FileNotFoundError:
                pass

    # --- main loop (main.go:343-423 analog) ---

    def run_once(self) -> bool:
        """One update-loop tick; returns current readiness."""
        index = self.registration.register()
        peers = self.registration.peers()
        self.dns.update_hosts(peers)
        gen = self.tpulib.generation()
        ici = self.tpulib.ici_domain()
        topo = (
            topology_str(ici.topology)
            if ici and ici.topology != (0, 0, 0)
            else topology_str(gen.host_extent)
        )
        # Accelerator type describes ONE slice (a 4-slice v5p-16 domain is
        # four v5p-16s over DCN, not a v5p-64).
        per_slice_nodes = max(
            1, self.config.num_nodes // max(1, self.config.num_slices)
        )
        n_chips = per_slice_nodes * len(self.tpulib.chips())
        if self.config.num_slices > 1:
            try:
                slice_index, coord_ip = self.registration.multislice_info()
            except MultisliceIdentityPending as e:
                # Publishing an unresolved identity could alias two slices
                # onto the same MEGASCALE_SLICE_ID; stay NotReady and let
                # the next tick retry once the controller has pinned it.
                log.info("multislice identity pending: %s", e)
                self._ready = False
                self._write_ready_file(False)
                self.registration.set_status(False)
                return False
        else:
            slice_index, coord_ip = 0, None
        env = render_bootstrap_env(
            worker_id=index,
            num_nodes=self.config.num_nodes,
            accelerator_type=gen.accelerator_type(n_chips),
            topology=topo,
            peers=peers,
            num_slices=self.config.num_slices,
            slice_index=slice_index,
            megascale_coordinator_ip=coord_ip,
            **(
                {"coordinator_port": self.config.coordinator_port}
                if self.config.coordinator_port
                else {}
            ),
        )
        write_bootstrap_files(self.config.config_dir, env, peers)
        ready = self.compute_ready(peers)
        if ready != self._ready:
            log.info("readiness -> %s (%d/%d peers)", ready, len(peers),
                     self.config.num_nodes)
        self._ready = ready
        self._was_ready = self._was_ready or ready
        self._write_ready_file(ready)
        # Registration readiness follows the pod's kubelet-probed Ready
        # condition when observable (podmanager.go:32-149): local view ->
        # ready file -> probe -> pod condition -> registration.
        pod_ready = self.podmanager.pod_ready()
        self.registration.set_status(ready if pod_ready is None else pod_ready)
        return ready

    def run(self) -> None:
        os.makedirs(self.config.config_dir, exist_ok=True)
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:
                log.exception("daemon update tick failed")
            self._stop.wait(self.config.update_period)
        # Teardown: mark NotReady and deregister.
        try:
            self.registration.set_status(False)
            self.registration.deregister()
        except Exception:
            log.exception("daemon deregistration failed")

    def stop(self) -> None:
        self._stop.set()


def check(config_dir: str = "/tpu-cd") -> int:
    """Readiness probe subcommand (the nvidia-imex-ctl -q analog,
    main.go:427-451): exit 0 iff the daemon last reported ready."""
    if os.path.exists(os.path.join(config_dir, READY_FILE)):
        print("READY")
        return 0
    print("NOT READY")
    return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpu-compute-domain-daemon")
    flags.add_version_flag(p)
    p.add_argument("command", nargs="?", default="run", choices=["run", "check"])
    flags.KubeClientConfig.add_flags(p)
    flags.LoggingConfig.add_flags(p)
    p.add_argument("--cd-uid", default=flags.env_default("CD_UID", ""))
    p.add_argument("--cd-name", default=flags.env_default("CD_NAME", ""))
    p.add_argument("--cd-namespace", default=flags.env_default("CD_NAMESPACE", "default"))
    p.add_argument("--num-nodes", type=int, default=flags.env_default("NUM_NODES", 1, int))
    p.add_argument("--num-slices", type=int, default=flags.env_default("NUM_SLICES", 1, int))
    p.add_argument(
        "--node-loss-policy",
        choices=[NODE_LOSS_FAIL_FAST, NODE_LOSS_SHRINK],
        default=flags.env_default("NODE_LOSS_POLICY", NODE_LOSS_FAIL_FAST),
        help="Mirror of the ComputeDomain's spec.nodeLossPolicy",
    )
    p.add_argument("--node-name", default=flags.env_default("NODE_NAME", ""))
    p.add_argument("--pod-ip", default=flags.env_default("POD_IP", ""))
    p.add_argument("--config-dir", default=flags.env_default("CD_CONFIG_DIR", "/tpu-cd"))
    p.add_argument(
        "--hosts-path",
        default=flags.env_default("CD_HOSTS_PATH", "/etc/hosts"),
        help="hosts file the DNS-names manager rewrites (the pod's own)",
    )
    p.add_argument(
        "--heartbeat-period",
        type=float,
        default=flags.env_default("CD_HEARTBEAT_PERIOD", 10.0, float),
        help="How often to refresh this daemon's liveness heartbeat",
    )
    p.add_argument(
        "--coordinator-port",
        type=int,
        default=flags.env_default("CD_COORDINATOR_PORT", 0, int),
        help="Override the JAX rendezvous port rendered into "
        "JAX_COORDINATOR_ADDRESS (0 = built-in default)",
    )
    p.add_argument("--pod-name", default=flags.env_default("POD_NAME", ""))
    p.add_argument(
        "--pod-namespace", default=flags.env_default("POD_NAMESPACE", "")
    )
    flags.add_feature_gate_flag(p)
    args = p.parse_args(argv)
    flags.apply_feature_gates(args)
    flags.LoggingConfig.from_args(args).apply()
    if args.command == "check":
        return check(args.config_dir)
    signals.start_debug_signal_handlers()
    backend = flags.KubeClientConfig.from_args(args).new_client()
    config = DaemonConfig(
        cd_uid=args.cd_uid,
        cd_name=args.cd_name,
        cd_namespace=args.cd_namespace,
        num_nodes=args.num_nodes,
        num_slices=args.num_slices,
        node_loss_policy=args.node_loss_policy,
        coordinator_port=args.coordinator_port,
        node_name=args.node_name,
        pod_ip=args.pod_ip,
        config_dir=args.config_dir,
        hosts_path=args.hosts_path,
        heartbeat_period=args.heartbeat_period,
        pod_name=args.pod_name,
        pod_namespace=args.pod_namespace,
    )
    daemon = SliceDaemon(config, backend)
    import signal as _sig

    _sig.signal(_sig.SIGTERM, lambda *a: daemon.stop())
    daemon.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
