"""Stable DNS-name rendering for slice peers.

Reference analog: cmd/compute-domain-daemon/dnsnames.go — maps
``compute-domain-daemon-<index>`` names to peer IPs, rewriting /etc/hosts
between sentinel markers (:145-190), plus a static nodes config listing all
possible peer names up front (:191-216; rationale in
api/.../computedomain.go:63-90 — peers can then join/leave without config
rewrites, only the hosts mapping changes).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List

log = logging.getLogger(__name__)

SENTINEL_BEGIN = "# BEGIN tpu-dra-compute-domain"
SENTINEL_END = "# END tpu-dra-compute-domain"
DNS_NAME_PREFIX = "compute-domain-daemon"


def dns_name(index: int) -> str:
    return f"{DNS_NAME_PREFIX}-{index}"


class DNSNameManager:
    def __init__(self, hosts_path: str = "/etc/hosts", max_nodes: int = 128):
        self.hosts_path = hosts_path
        self.max_nodes = max_nodes

    def write_nodes_config(self, path: str) -> None:
        """Static peer list with every possible DNS name
        (dnsnames.go:191-216): membership changes never touch this file."""
        with open(path, "w") as f:
            for i in range(self.max_nodes):
                f.write(f"{dns_name(i)}\n")

    def update_hosts(self, peers: List[dict]) -> bool:
        """Rewrite the sentinel-delimited block; True when the mapping
        changed (the caller then pokes consumers, the SIGUSR1 analog)."""
        mapping: Dict[str, str] = {
            dns_name(d.get("index", 0)): d.get("ipAddress", "")
            for d in peers
            if d.get("ipAddress")
        }
        block = [SENTINEL_BEGIN]
        for name, ip in sorted(mapping.items()):
            block.append(f"{ip}\t{name}")
        block.append(SENTINEL_END)

        try:
            with open(self.hosts_path) as f:
                lines = f.read().splitlines()
        except FileNotFoundError:
            lines = []
        out, skipping, had_block = [], False, False
        old_block: List[str] = []
        for line in lines:
            if line.strip() == SENTINEL_BEGIN:
                skipping, had_block = True, True
                old_block.append(line)
                continue
            if line.strip() == SENTINEL_END:
                skipping = False
                old_block.append(line)
                continue
            if skipping:
                old_block.append(line)
                continue
            out.append(line)
        if had_block and old_block == block:
            return False
        out.extend(block)
        tmp = self.hosts_path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(out) + "\n")
        os.replace(tmp, self.hosts_path)
        log.info("updated %s with %d peer mappings", self.hosts_path, len(mapping))
        return True
