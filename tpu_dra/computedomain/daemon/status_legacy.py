"""Legacy (ComputeDomainCliques=off) registration: write directly into
ComputeDomain.Status.

Reference analog: cmd/compute-domain-daemon/cdstatus.go:223-333 — before the
clique CRD existed, each daemon inserted its `{name, ipAddress, cliqueID,
index, status}` entry straight into ``CD.Status.Nodes`` with conflict-retried
read-modify-writes. The shared state machine lives in :mod:`.registration`;
the interface matches :class:`~tpu_dra.computedomain.daemon.clique.
CliqueRegistration` so :class:`~tpu_dra.computedomain.daemon.main.
SliceDaemon` can swap implementations on the gate.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_dra.computedomain.daemon.registration import (
    DEFAULT_HEARTBEAT_PERIOD,
    MultisliceIdentityPending,
    RegistrationBase,
)
from tpu_dra.k8sclient import COMPUTE_DOMAINS, ApiConflict, ResourceClient

log = logging.getLogger(__name__)


class DirectStatusRegistration(RegistrationBase):
    # CD.Status.Nodes names its node field "name" (computedomain.go
    # ComputeDomainNode), unlike clique daemon entries' "nodeName".
    node_key = "name"

    def __init__(
        self,
        backend,
        cd_uid: str,
        cd_name: str,
        cd_namespace: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
        heartbeat_period: float = DEFAULT_HEARTBEAT_PERIOD,
    ):
        super().__init__(
            node_name=node_name, ip_address=ip_address, clique_id=clique_id,
            heartbeat_period=heartbeat_period,
        )
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)
        self.cd_uid = cd_uid
        self.cd_name = cd_name
        self.cd_namespace = cd_namespace

    def _describe(self) -> str:
        return (
            f"computedomain {self.cd_namespace}/{self.cd_name} "
            f"(uid {self.cd_uid})"
        )

    def _fetch(self) -> Optional[dict]:
        cd = self.cds.try_get(self.cd_name, self.cd_namespace)
        if cd is not None and cd["metadata"].get("uid") not in ("", self.cd_uid):
            # A same-named CD that is not ours (delete + recreate race).
            return None
        return cd

    def _persist(self, obj: dict) -> None:
        self.cds.update_status(obj)

    def _entries(self, obj: dict) -> List[dict]:
        status = obj.setdefault("status", {})
        if status.get("nodes") is None:
            status["nodes"] = []
        return status["nodes"]

    def _scope(self, entries: List[dict]) -> List[dict]:
        # CD.Status.Nodes is domain-wide; indices/peers/readiness are
        # slice-local, so scope to our clique's entries.
        return [e for e in entries if e.get("cliqueID") == self.clique_id]

    def multislice_info(self):
        """(pinned slice index, megascale coordinator IP or None).

        The per-clique slice index is persisted as ``sliceIndex`` on the
        clique's node entries at first assignment (same pin-once rule as
        the clique-object path) with a conflict-retried status write."""
        for _ in range(5):
            cd = self._fetch()
            if cd is None:
                return 0, None
            nodes = (cd.get("status") or {}).get("nodes") or []
            by_clique = {}
            for n in nodes:
                if n.get("sliceIndex") is not None:
                    by_clique.setdefault(n.get("cliqueID", ""), n["sliceIndex"])
            idx = by_clique.get(self.clique_id)
            if idx is None:
                used = set(by_clique.values())
                idx = 0
                while idx in used:
                    idx += 1
                changed = False
                for n in nodes:
                    if n.get("cliqueID") == self.clique_id:
                        n["sliceIndex"] = idx
                        changed = True
                if changed:
                    try:
                        self.cds.update_status(cd)
                    except ApiConflict:
                        continue
            by_clique[self.clique_id] = idx
            slice0 = next(
                (cid for cid, si in by_clique.items() if si == 0), None
            )
            coord_ip = None
            if slice0 is not None:
                for n in nodes:
                    if n.get("cliqueID") == slice0 and n.get("index", 0) == 0:
                        coord_ip = n.get("ipAddress") or None
            return idx, coord_ip
        # Never alias onto slice 0 after exhausted retries — two slices
        # sharing MEGASCALE_SLICE_ID misassembles the DCN job (same
        # fail-loud rule as the worker-id bound in bootstrap.py).
        raise MultisliceIdentityPending(
            f"slice index for clique {self.clique_id} unresolved after "
            f"repeated write conflicts"
        )

    def peers(self) -> List[dict]:
        """Normalize CD.Status node entries to the clique daemon-entry shape
        consumed by DNSNameManager / bootstrap rendering (key "nodeName")."""
        return [
            {
                "nodeName": n.get("name", ""),
                "ipAddress": n.get("ipAddress", ""),
                "cliqueID": n.get("cliqueID", ""),
                "index": n.get("index", 0),
                "status": n.get("status", ""),
            }
            for n in super().peers()
        ]
