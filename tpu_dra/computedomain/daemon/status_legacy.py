"""Legacy (ComputeDomainCliques=off) registration: write directly into
ComputeDomain.Status.

Reference analog: cmd/compute-domain-daemon/cdstatus.go:223-333 — before the
clique CRD existed, each daemon inserted its `{name, ipAddress, cliqueID,
index, status}` entry straight into ``CD.Status.Nodes`` with conflict-retried
read-modify-writes. The shared state machine lives in :mod:`.registration`;
the interface matches :class:`~tpu_dra.computedomain.daemon.clique.
CliqueRegistration` so :class:`~tpu_dra.computedomain.daemon.main.
SliceDaemon` can swap implementations on the gate.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_dra.computedomain.daemon.registration import RegistrationBase
from tpu_dra.k8sclient import COMPUTE_DOMAINS, ResourceClient

log = logging.getLogger(__name__)


class DirectStatusRegistration(RegistrationBase):
    # CD.Status.Nodes names its node field "name" (computedomain.go
    # ComputeDomainNode), unlike clique daemon entries' "nodeName".
    node_key = "name"

    def __init__(
        self,
        backend,
        cd_uid: str,
        cd_name: str,
        cd_namespace: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
    ):
        super().__init__(
            node_name=node_name, ip_address=ip_address, clique_id=clique_id
        )
        self.cds = ResourceClient(backend, COMPUTE_DOMAINS)
        self.cd_uid = cd_uid
        self.cd_name = cd_name
        self.cd_namespace = cd_namespace

    def _describe(self) -> str:
        return (
            f"computedomain {self.cd_namespace}/{self.cd_name} "
            f"(uid {self.cd_uid})"
        )

    def _fetch(self) -> Optional[dict]:
        cd = self.cds.try_get(self.cd_name, self.cd_namespace)
        if cd is not None and cd["metadata"].get("uid") not in ("", self.cd_uid):
            # A same-named CD that is not ours (delete + recreate race).
            return None
        return cd

    def _persist(self, obj: dict) -> None:
        self.cds.update_status(obj)

    def _entries(self, obj: dict) -> List[dict]:
        status = obj.setdefault("status", {})
        if status.get("nodes") is None:
            status["nodes"] = []
        return status["nodes"]

    def peers(self) -> List[dict]:
        """Normalize CD.Status node entries to the clique daemon-entry shape
        consumed by DNSNameManager / bootstrap rendering (key "nodeName")."""
        return [
            {
                "nodeName": n.get("name", ""),
                "ipAddress": n.get("ipAddress", ""),
                "cliqueID": n.get("cliqueID", ""),
                "index": n.get("index", 0),
                "status": n.get("status", ""),
            }
            for n in super().peers()
        ]
