"""Clique self-registration with stable index assignment.

Reference analog: cmd/compute-domain-daemon/cdclique.go — the daemon
registers {nodeName, podIP, cliqueID, index, status} into the
ComputeDomainClique named ``<cdUID>.<cliqueID>`` (:173-176); index
assignment fills gaps so restarts keep DNS names stable (:350-372);
readiness updates flow through the same object (:429-...). The retry/index
state machine lives in :mod:`.registration`, shared with the legacy
direct-status path.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_dra.api import CD_STATUS_NOT_READY
from tpu_dra.computedomain import CD_LABEL_KEY
from tpu_dra.computedomain.daemon.registration import (
    DEFAULT_HEARTBEAT_PERIOD,
    RETRY,
    MultisliceIdentityPending,
    RegistrationBase,
)
from tpu_dra.k8sclient import (
    COMPUTE_DOMAIN_CLIQUES,
    ApiConflict,
    ResourceClient,
)

log = logging.getLogger(__name__)


class CliqueRegistration(RegistrationBase):
    node_key = "nodeName"

    def __init__(
        self,
        backend,
        cd_uid: str,
        cd_namespace: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
        heartbeat_period: float = DEFAULT_HEARTBEAT_PERIOD,
    ):
        super().__init__(
            node_name=node_name, ip_address=ip_address, clique_id=clique_id,
            heartbeat_period=heartbeat_period,
        )
        self.cliques = ResourceClient(backend, COMPUTE_DOMAIN_CLIQUES)
        self.cd_uid = cd_uid
        self.cd_namespace = cd_namespace

    @property
    def clique_name(self) -> str:
        return f"{self.cd_uid}.{self.clique_id}"

    def _describe(self) -> str:
        return f"clique {self.cd_namespace}/{self.clique_name}"

    def _fetch(self) -> Optional[dict]:
        return self.cliques.try_get(self.clique_name, self.cd_namespace)

    def _persist(self, obj: dict) -> None:
        self.cliques.update(obj)

    def _entries(self, obj: dict) -> List[dict]:
        if obj.get("daemons") is None:
            obj["daemons"] = []
        return obj["daemons"]

    def multislice_info(self):
        """(pinned slice index, megascale coordinator IP or None), one LIST.

        Slice indices are assigned by the **controller** (the single
        leader-elected writer — daemons racing gap-filled self-assignment
        across *different* clique objects could both claim 0, since
        optimistic concurrency only guards same-object writes). Daemons
        read their clique's pinned ``sliceIndex``; until it lands they
        report identity-pending and stay NotReady. The coordinator is
        slice 0's index-0 daemon, addressed by pod IP (each slice's
        /etc/hosts maps the shared DNS names to its OWN peers, so a name
        cannot cross slices)."""
        cliques = self.cliques.list(
            namespace=self.cd_namespace,
            label_selector={CD_LABEL_KEY: self.cd_uid},
        )
        mine = next(
            (c for c in cliques if c["metadata"]["name"] == self.clique_name),
            None,
        )
        if mine is None or mine.get("sliceIndex") is None:
            raise MultisliceIdentityPending(
                f"clique {self.clique_name} has no controller-assigned "
                f"sliceIndex yet"
            )
        idx = mine["sliceIndex"]
        coord_ip = None
        for c in cliques:
            if c.get("sliceIndex") == 0:
                for d in c.get("daemons") or []:
                    if d.get("index", 0) == 0:
                        coord_ip = d.get("ipAddress") or None
                break
        return idx, coord_ip

    def _on_missing_register(self):
        """First daemon of the clique creates the object (cdclique.go
        create path); a create conflict means a peer raced us — re-read."""
        obj = {
            "apiVersion": "resource.tpu.google.com/v1beta1",
            "kind": "ComputeDomainClique",
            "metadata": {
                "name": self.clique_name,
                "namespace": self.cd_namespace,
                "labels": {CD_LABEL_KEY: self.cd_uid},
            },
            "daemons": [self._entry(0, CD_STATUS_NOT_READY)],
        }
        try:
            self.cliques.create(obj)
            self.index = 0
            return 0
        except ApiConflict:
            return RETRY
