"""Clique self-registration with stable index assignment.

Reference analog: cmd/compute-domain-daemon/cdclique.go — the daemon
registers {nodeName, podIP, cliqueID, index, status} into the
ComputeDomainClique named ``<cdUID>.<cliqueID>`` (:173-176); index
assignment fills gaps so restarts keep DNS names stable (:350-372);
readiness updates flow through the same object (:429-...). The retry/index
state machine lives in :mod:`.registration`, shared with the legacy
direct-status path.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_dra.api import CD_STATUS_NOT_READY
from tpu_dra.computedomain import CD_LABEL_KEY
from tpu_dra.computedomain.daemon.registration import RETRY, RegistrationBase
from tpu_dra.k8sclient import (
    COMPUTE_DOMAIN_CLIQUES,
    ApiConflict,
    ResourceClient,
)

log = logging.getLogger(__name__)


class CliqueRegistration(RegistrationBase):
    node_key = "nodeName"

    def __init__(
        self,
        backend,
        cd_uid: str,
        cd_namespace: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
    ):
        super().__init__(
            node_name=node_name, ip_address=ip_address, clique_id=clique_id
        )
        self.cliques = ResourceClient(backend, COMPUTE_DOMAIN_CLIQUES)
        self.cd_uid = cd_uid
        self.cd_namespace = cd_namespace

    @property
    def clique_name(self) -> str:
        return f"{self.cd_uid}.{self.clique_id}"

    def _describe(self) -> str:
        return f"clique {self.cd_namespace}/{self.clique_name}"

    def _fetch(self) -> Optional[dict]:
        return self.cliques.try_get(self.clique_name, self.cd_namespace)

    def _persist(self, obj: dict) -> None:
        self.cliques.update(obj)

    def _entries(self, obj: dict) -> List[dict]:
        if obj.get("daemons") is None:
            obj["daemons"] = []
        return obj["daemons"]

    def _on_missing_register(self):
        """First daemon of the clique creates the object (cdclique.go
        create path); a create conflict means a peer raced us — re-read."""
        obj = {
            "apiVersion": "resource.tpu.google.com/v1beta1",
            "kind": "ComputeDomainClique",
            "metadata": {
                "name": self.clique_name,
                "namespace": self.cd_namespace,
                "labels": {CD_LABEL_KEY: self.cd_uid},
            },
            "daemons": [self._entry(0, CD_STATUS_NOT_READY)],
        }
        try:
            self.cliques.create(obj)
            self.index = 0
            return 0
        except ApiConflict:
            return RETRY
