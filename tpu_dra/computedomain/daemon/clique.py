"""Clique self-registration with stable index assignment.

Reference analog: cmd/compute-domain-daemon/cdclique.go — the daemon
registers {nodeName, podIP, cliqueID, index, status} into the
ComputeDomainClique named ``<cdUID>.<cliqueID>`` (:173-176); index
assignment fills gaps so restarts keep DNS names stable (:350-372);
readiness updates flow through the same object (:429-...).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_dra.api import CD_STATUS_NOT_READY, CD_STATUS_READY
from tpu_dra.computedomain import CD_LABEL_KEY
from tpu_dra.k8sclient import (
    COMPUTE_DOMAIN_CLIQUES,
    ApiConflict,
    ApiNotFound,
    ResourceClient,
)

log = logging.getLogger(__name__)


class CliqueRegistration:
    def __init__(
        self,
        backend,
        cd_uid: str,
        cd_namespace: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
    ):
        self.cliques = ResourceClient(backend, COMPUTE_DOMAIN_CLIQUES)
        self.cd_uid = cd_uid
        self.cd_namespace = cd_namespace
        self.clique_id = clique_id
        self.node_name = node_name
        self.ip_address = ip_address
        self.index: Optional[int] = None

    @property
    def clique_name(self) -> str:
        return f"{self.cd_uid}.{self.clique_id}"

    @staticmethod
    def _assign_index(daemons: List[dict]) -> int:
        """Smallest free index — gap-filling keeps DNS names stable across
        daemon restarts (cdclique.go:350-372)."""
        used = {d.get("index", 0) for d in daemons}
        i = 0
        while i in used:
            i += 1
        return i

    def register(self) -> int:
        """Insert or refresh our daemon entry; retries on write conflicts
        (multiple daemons register concurrently). Returns our index."""
        for _ in range(20):
            clique = self.cliques.try_get(self.clique_name, self.cd_namespace)
            if clique is None:
                obj = {
                    "apiVersion": "resource.tpu.google.com/v1beta1",
                    "kind": "ComputeDomainClique",
                    "metadata": {
                        "name": self.clique_name,
                        "namespace": self.cd_namespace,
                        "labels": {CD_LABEL_KEY: self.cd_uid},
                    },
                    "daemons": [self._entry(0, CD_STATUS_NOT_READY)],
                }
                try:
                    self.cliques.create(obj)
                    self.index = 0
                    return 0
                except ApiConflict:
                    continue  # raced with a peer; re-read
            daemons = clique.get("daemons") or []
            mine = next(
                (d for d in daemons if d.get("nodeName") == self.node_name), None
            )
            if mine is not None:
                # Keep our stable index; refresh IP (pod restart changes it).
                self.index = mine.get("index", 0)
                if mine.get("ipAddress") == self.ip_address:
                    return self.index
                mine["ipAddress"] = self.ip_address
            else:
                self.index = self._assign_index(daemons)
                daemons.append(self._entry(self.index, CD_STATUS_NOT_READY))
            clique["daemons"] = daemons
            try:
                self.cliques.update(clique)
                return self.index
            except ApiConflict:
                continue
        raise RuntimeError(
            f"could not register into clique {self.clique_name}: too many "
            f"write conflicts"
        )

    def _entry(self, index: int, status: str) -> dict:
        return {
            "nodeName": self.node_name,
            "ipAddress": self.ip_address,
            "cliqueID": self.clique_id,
            "index": index,
            "status": status,
        }

    def set_status(self, ready: bool) -> None:
        status = CD_STATUS_READY if ready else CD_STATUS_NOT_READY
        for _ in range(20):
            clique = self.cliques.try_get(self.clique_name, self.cd_namespace)
            if clique is None:
                return
            changed = False
            for d in clique.get("daemons") or []:
                if d.get("nodeName") == self.node_name and d.get("status") != status:
                    d["status"] = status
                    changed = True
            if not changed:
                return
            try:
                self.cliques.update(clique)
                return
            except ApiConflict:
                continue

    def peers(self) -> List[dict]:
        clique = self.cliques.try_get(self.clique_name, self.cd_namespace)
        if clique is None:
            return []
        return sorted(
            clique.get("daemons") or [], key=lambda d: d.get("index", 0)
        )

    def deregister(self) -> None:
        for _ in range(20):
            clique = self.cliques.try_get(self.clique_name, self.cd_namespace)
            if clique is None:
                return
            daemons = [
                d
                for d in clique.get("daemons") or []
                if d.get("nodeName") != self.node_name
            ]
            if len(daemons) == len(clique.get("daemons") or []):
                return
            clique["daemons"] = daemons
            try:
                self.cliques.update(clique)
                return
            except ApiConflict:
                continue
