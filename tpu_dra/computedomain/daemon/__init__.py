"""Per-node ComputeDomain slice daemon (cmd/compute-domain-daemon).

Instead of supervising ``nvidia-imex`` (main.go:44-51), the TPU daemon:
discovers local chip/ICI topology via tpulib, registers itself into the
ComputeDomainClique CRD with a stable index, renders the JAX/libtpu
multi-host bootstrap config the CD kubelet plugin injects into workload
pods, keeps /etc/hosts-style peer mappings fresh, and reports readiness
when the slice membership is complete.
"""
