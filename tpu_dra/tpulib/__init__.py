"""tpulib: the TPU hardware-abstraction layer.

Reference analog: ``deviceLib`` in cmd/gpu-kubelet-plugin/nvlib.go (NVML via
go-nvml cgo) plus nvpci sysfs walking. This is the layer the TPU build
replaces wholesale (SURVEY.md §1.7): there is no NVML equivalent for TPUs, so
discovery data comes from PCI sysfs, /dev/accel + /dev/vfio device nodes, and
GKE TPU environment conventions, unified behind one interface with two
backends:

- :mod:`tpu_dra.tpulib.stub`  — config-file-driven fake chips; the kind /
  CPU-only path (BASELINE config 1) and the unit-test seam the reference
  never had (SURVEY.md §4.1: "no fake NVML layer" is its biggest testability
  gap).
- :mod:`tpu_dra.tpulib.linux` — real enumeration from a (configurable-root)
  sysfs/dev tree, with hot paths in ``native/libtputopo.so`` (C++).

Backend selection mirrors the reference's driver-root resolution
(cmd/gpu-kubelet-plugin/root.go:29-65): explicit argument > env var >
auto-detect (real TPU PCI devices present -> linux, else stub).
"""

from __future__ import annotations

import logging
import os

from tpu_dra.tpulib.types import (  # noqa: F401
    ChipHealthEvent,
    ChipInfo,
    Generation,
    GENERATIONS,
    IciDomain,
    Placement,
    SubsliceShape,
    TopologyCoord,
    parse_topology,
)
from tpu_dra.tpulib.interface import TpuLib  # noqa: F401

log = logging.getLogger(__name__)

BACKEND_ENV = "TPU_DRA_BACKEND"


def new_tpulib(
    backend: str = "",
    sysfs_root: str = "/sys",
    dev_root: str = "/dev",
    **kwargs,
) -> TpuLib:
    """Create a tpulib backend (deviceLib constructor analog,
    nvlib.go:56-96). ``sysfs_root``/``dev_root`` are the driver-root
    resolution analog (root.go:29-87): a containerized plugin sees the
    host's trees mounted under a prefix. They apply to the linux backend
    and to auto-detection; the stub fakes its own hardware."""
    backend = backend or os.environ.get(BACKEND_ENV, "")
    if not backend:
        from tpu_dra.tpulib.linux import detect_tpu_pci_devices

        backend = "linux" if detect_tpu_pci_devices(sysfs_root) else "stub"
        log.info("auto-detected tpulib backend: %s", backend)
    if backend == "stub":
        from tpu_dra.tpulib.stub import StubTpuLib

        return StubTpuLib(**kwargs)
    if backend == "linux":
        from tpu_dra.tpulib.linux import LinuxTpuLib

        return LinuxTpuLib(
            sysfs_root=sysfs_root, dev_root=dev_root, **kwargs
        )
    raise ValueError(f"unknown tpulib backend: {backend!r}")
