"""Core tpulib data model.

Reference analog: the info structs in cmd/gpu-kubelet-plugin/deviceinfo.go
(GpuInfo :40-111 with uuid/productName/architecture/memory/pciBusID
attributes) and the MIG profile/placement model (MigProfileInfo,
MigDevicePlacement in nvlib.go:1129-1210).

TPU-native modeling decisions:

- A **chip** is the allocatable unit (the GPU analog). Chips sit at integer
  coordinates in the ICI mesh of their pod slice; the coordinate system is
  the basis for sub-slice placement (the MIG placement analog, which for
  TPUs is *topology-constrained*: a sub-slice must be a contiguous
  axis-aligned block of the mesh).
- A **sub-slice shape** (MIG profile analog) is an axis-aligned extent like
  ``2x2x1``, with per-generation catalogs mirroring the supported Cloud TPU
  slice shapes.
- The **ICI domain** (NVLink clique analog) identifies the pod slice a chip
  belongs to: ``sliceUUID.partition`` — the cliqueID string the CD machinery
  shares with the reference (cmd/compute-domain-kubelet-plugin/nvlib.go:188-357).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True, order=True)
class TopologyCoord:
    x: int
    y: int
    z: int = 0

    def __str__(self) -> str:
        return f"{self.x},{self.y},{self.z}"

    @classmethod
    def parse(cls, s: str) -> "TopologyCoord":
        parts = [int(p) for p in s.split(",")]
        while len(parts) < 3:
            parts.append(0)
        return cls(*parts[:3])


def parse_topology(s: str) -> Tuple[int, int, int]:
    """Parse ``4x4`` / ``2x2x2`` topology strings to a 3D extent."""
    m = re.fullmatch(r"(\d+)x(\d+)(?:x(\d+))?", s.strip())
    if not m:
        raise ValueError(f"invalid topology string: {s!r}")
    x, y, z = int(m.group(1)), int(m.group(2)), int(m.group(3) or 1)
    if x <= 0 or y <= 0 or z <= 0:
        raise ValueError(f"invalid topology string: {s!r}")
    return (x, y, z)


def topology_str(extent: Tuple[int, int, int]) -> str:
    x, y, z = extent
    return f"{x}x{y}" if z == 1 else f"{x}x{y}x{z}"


@dataclass(frozen=True)
class Generation:
    """Per-generation hardware catalog entry."""

    name: str  # "v5p"
    product_name: str  # "tpu-v5p-slice"
    cores_per_chip: int
    hbm_bytes: int
    chips_per_host: int
    # Host-local chip arrangement within the mesh (e.g. v5p: 2x2x1 per host).
    host_extent: Tuple[int, int, int]
    mesh_dims: int  # 2 for 2D meshes (v5e/v6e), 3 for 3D torus (v4/v5p)
    # Catalog of sub-slice shapes materializable *within one host's chips*
    # (the dynamic-reshape inventory; multi-host shapes are ComputeDomains).
    subslice_shapes: Tuple[Tuple[int, int, int], ...]
    pci_device_ids: Tuple[str, ...] = ()

    def accelerator_type(self, num_chips: int) -> str:
        """Cloud TPU naming counts TensorCores: v5p-16 == 8 chips."""
        return f"{self.name}-{num_chips * self.cores_per_chip}"


GIB = 1024**3

# Public Cloud TPU generation data (shapes are per-host sub-slice shapes).
GENERATIONS: Dict[str, Generation] = {
    "v4": Generation(
        name="v4",
        product_name="tpu-v4-podslice",
        cores_per_chip=2,
        hbm_bytes=32 * GIB,
        chips_per_host=4,
        host_extent=(2, 2, 1),
        mesh_dims=3,
        subslice_shapes=((1, 1, 1), (1, 2, 1), (2, 2, 1)),
        pci_device_ids=("0x005e",),
    ),
    "v5e": Generation(
        name="v5e",
        product_name="tpu-v5-lite-podslice",
        cores_per_chip=1,
        hbm_bytes=16 * GIB,
        chips_per_host=4,
        host_extent=(2, 2, 1),
        mesh_dims=2,
        subslice_shapes=((1, 1, 1), (1, 2, 1), (2, 2, 1)),
        pci_device_ids=("0x0063",),
    ),
    "v5p": Generation(
        name="v5p",
        product_name="tpu-v5p-slice",
        cores_per_chip=2,
        hbm_bytes=95 * GIB,
        chips_per_host=4,
        host_extent=(2, 2, 1),
        mesh_dims=3,
        subslice_shapes=((1, 1, 1), (1, 2, 1), (2, 2, 1)),
        pci_device_ids=("0x0062",),
    ),
    "v6e": Generation(
        name="v6e",
        product_name="tpu-v6e-slice",
        cores_per_chip=1,
        hbm_bytes=32 * GIB,
        chips_per_host=4,
        host_extent=(2, 2, 1),
        mesh_dims=2,
        subslice_shapes=((1, 1, 1), (1, 2, 1), (2, 2, 1)),
        pci_device_ids=("0x006f",),
    ),
}


@dataclass(frozen=True)
class IciDomain:
    """The pod-slice fabric a chip belongs to (NVLink clique analog).

    ``clique_id()`` yields the stable string the ComputeDomain machinery keys
    cliques on: ``<sliceUUID>.<partition>``.
    """

    slice_uuid: str
    partition: int = 0
    topology: Tuple[int, int, int] = (0, 0, 0)

    def clique_id(self) -> str:
        return f"{self.slice_uuid}.{self.partition}"


@dataclass
class ChipInfo:
    """One TPU chip (GpuInfo analog, deviceinfo.go:40-60)."""

    index: int  # host-local index (minor analog)
    uuid: str
    generation: Generation
    pci_bus_id: str = ""
    pcie_root: str = ""
    numa_node: int = -1
    dev_paths: List[str] = field(default_factory=list)  # /dev/accelN, /dev/vfio/..
    coord: TopologyCoord = field(default_factory=lambda: TopologyCoord(0, 0, 0))
    ici_domain: Optional[IciDomain] = None
    worker_id: int = 0  # this host's index within the pod slice
    iommu_group: int = -1
    vfio_capable: bool = False
    healthy: bool = True

    @property
    def hbm_bytes(self) -> int:
        return self.generation.hbm_bytes

    def canonical_name(self) -> str:
        """DRA device name for the full chip: ``tpu-<index>``."""
        return f"tpu-{self.index}"


@dataclass(frozen=True)
class SubsliceShape:
    """A materializable sub-slice profile (MigProfileInfo analog)."""

    extent: Tuple[int, int, int]

    @property
    def chip_count(self) -> int:
        x, y, z = self.extent
        return x * y * z

    def __str__(self) -> str:
        return topology_str(self.extent)

    @classmethod
    def parse(cls, s: str) -> "SubsliceShape":
        return cls(parse_topology(s))


@dataclass(frozen=True)
class Placement:
    """A concrete placement of a shape in the host mesh
    (MigDevicePlacement analog: start + size, nvlib.go:1176-1210)."""

    start: TopologyCoord
    shape: SubsliceShape

    def chips(self) -> List[TopologyCoord]:
        sx, sy, sz = self.shape.extent
        return [
            TopologyCoord(self.start.x + dx, self.start.y + dy, self.start.z + dz)
            for dz in range(sz)
            for dy in range(sy)
            for dx in range(sx)
        ]

    def overlaps(self, other: "Placement") -> bool:
        return bool(set(self.chips()) & set(other.chips()))

    def __str__(self) -> str:
        return f"{self.shape}@{self.start}"


@dataclass(frozen=True)
class ChipHealthEvent:
    """Health transition for a chip (XID-event analog,
    device_health.go:38-66)."""

    chip_uuid: str
    healthy: bool
    reason: str = ""


# Event reasons that must not mark a chip unhealthy (the XID skip-list
# analog, device_health.go:306-351). Filtered at INJECTION time so a
# benign event can never poison ChipInfo.healthy and get the chip
# unpublished by a later, unrelated health recompute — the reference
# likewise drops skipped XIDs before any marking.
BENIGN_HEALTH_REASONS = frozenset(
    {
        "preemption",  # workload preempted, chip fine
        "clock-throttle",  # thermal/power capping
        "application-error",  # user program crash, not a chip fault
    }
)
