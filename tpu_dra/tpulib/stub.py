"""Stub tpulib backend: config-driven fake chips.

This is the unit-test / kind / CPU-only path (BASELINE config 1) — the fake
hardware layer the reference never had (SURVEY.md §4.1 flags "no fake NVML"
as its biggest testability gap). Configure with a dict, a YAML/JSON file
(``TPU_DRA_STUB_CONFIG``), or accept the default single-host v5e-4.

Config schema::

    generation: v5p            # v4 | v5e | v5p | v6e
    chips: 4                   # chips on this host
    hostname: host-0
    state_dir: /var/lib/...    # persist sub-slices across restarts (the
                               # stub's "runtime introspection" surface —
                               # startup obliteration needs it)
    slice:                     # omit for a single-host node
      uuid: 1f0e...            # pod-slice UUID (fabric identity)
      partition: 0
      topology: 2x2x2          # whole-slice chip topology
      num_hosts: 2
      worker_id: 0
    fail:                      # fault injection knobs (tests)
      create_subslice: "msg"   # make create_subslice raise
    delay:                     # crash-window injection (tests)
      create_subslice: 5.0     # sleep AFTER persisting, BEFORE returning
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import uuid as uuidlib
from typing import List, Optional

import yaml

from tpu_dra.tpulib.base import BaseTpuLib
from tpu_dra.tpulib.interface import SubsliceInfo, TpuLibError
from tpu_dra.tpulib.types import (
    GENERATIONS,
    ChipHealthEvent,
    ChipInfo,
    Generation,
    IciDomain,
    Placement,
    TopologyCoord,
    parse_topology,
)

log = logging.getLogger(__name__)

STUB_CONFIG_ENV = "TPU_DRA_STUB_CONFIG"


def _stable_uuid(*parts: str) -> str:
    h = hashlib.sha256("/".join(parts).encode()).hexdigest()
    return str(uuidlib.UUID(h[:32]))


class StubTpuLib(BaseTpuLib):
    def __init__(
        self,
        config: Optional[dict] = None,
        config_path: Optional[str] = None,
        state_dir: Optional[str] = None,
    ):
        if config is None:
            path = config_path or os.environ.get(STUB_CONFIG_ENV)
            if path:
                with open(path) as f:
                    config = yaml.safe_load(f) or {}
            else:
                config = {}
        self._config = config
        gen_name = config.get("generation", "v5e")
        if gen_name not in GENERATIONS:
            raise TpuLibError(f"unknown TPU generation: {gen_name!r}")
        self._generation = GENERATIONS[gen_name]
        self._hostname = config.get("hostname", os.uname().nodename)
        # Where the advertised device inodes live: real hosts use /dev;
        # a minicluster node points this into its sandbox rootfs so the
        # paths CDI advertises are REAL inodes a device gate can chown
        # and a workload (or adversarial) process can open.
        dev_root = config.get("dev_root", "/dev")
        n = int(config.get("chips", self._generation.chips_per_host))
        hx, hy, hz = self._generation.host_extent
        if n > hx * hy * hz:
            raise TpuLibError(
                f"{n} chips exceed host extent "
                f"{self._generation.host_extent} for {gen_name}"
            )
        self._ici: Optional[IciDomain] = None
        self._worker_id = 0
        sl = config.get("slice")
        if sl:
            self._ici = IciDomain(
                slice_uuid=sl.get("uuid") or _stable_uuid(self._hostname, "slice"),
                partition=int(sl.get("partition", 0)),
                topology=parse_topology(sl.get("topology", "2x2x1")),
            )
            self._worker_id = int(sl.get("worker_id", 0))
        state_dir = state_dir or config.get("state_dir") or None
        self._chips: List[ChipInfo] = []
        for i in range(n):
            # Host-local coords fill x-fastest within the host extent.
            coord = TopologyCoord(i % hx, (i // hx) % hy, i // (hx * hy))
            self._chips.append(
                ChipInfo(
                    index=i,
                    uuid=_stable_uuid(self._hostname, gen_name, str(i)),
                    generation=self._generation,
                    pci_bus_id=f"0000:0{i}:00.0",
                    pcie_root=f"pci0000:0{i}",
                    numa_node=i // max(1, n // 2),
                    dev_paths=[os.path.join(dev_root, f"accel{i}")],
                    coord=coord,
                    ici_domain=self._ici,
                    worker_id=self._worker_id,
                    iommu_group=i,
                    vfio_capable=True,
                )
            )
        super().__init__(state_dir=state_dir)

    def generation(self) -> Generation:
        return self._generation

    def chips(self) -> List[ChipInfo]:
        return self._chips

    def ici_domain(self) -> Optional[IciDomain]:
        return self._ici

    # --- fault injection ---

    def create_subslice(self, placement: Placement) -> SubsliceInfo:
        msg = self._config.get("fail", {}).get("create_subslice")
        if msg:
            raise TpuLibError(f"injected fault: {msg}")
        info = super().create_subslice(placement)
        # delay.create_subslice: sleep AFTER the sub-slice persisted but
        # before returning — the window where the reference's slow GI/CI
        # creation (nvlib.go:860-989) can be interrupted by a plugin
        # crash, leaving a live orphan behind a PrepareStarted WAL entry.
        # Crash-recovery drills kill the plugin inside this window.
        delay = float(self._config.get("delay", {}).get("create_subslice", 0))
        if delay:
            time.sleep(delay)
        return info

    def delete_subslice(self, uuid: str) -> None:
        msg = self._config.get("fail", {}).get("delete_subslice")
        if msg:
            raise TpuLibError(f"injected fault: {msg}")
        super().delete_subslice(uuid)

    # --- cross-process health injection ---
    # The linux backend produces health events from kernel surfaces
    # (linux.py _probe_chip); the stub's monitor polls
    # ``<state_dir>/health-events/*.json`` so a SEPARATE process (e2e
    # runner, kind demo script) can break/heal fake chips:
    #
    #   {"chip_uuid": "..."| "chip_index": 0, "healthy": false,
    #    "reason": "injected"}
    #
    # Each file is consumed (deleted) once injected. In-process tests can
    # keep calling inject_health_event directly.

    def start_health_monitor(self, period: float = 0.5) -> None:
        if self._state_dir is None or getattr(self, "_hm_thread", None):
            return
        events_dir = os.path.join(self._state_dir, "health-events")
        os.makedirs(events_dir, exist_ok=True)
        self._hm_stop = threading.Event()

        def loop():
            while not self._hm_stop.wait(period):
                for name in sorted(os.listdir(events_dir)):
                    if not name.endswith(".json"):
                        continue
                    path = os.path.join(events_dir, name)
                    try:
                        with open(path) as f:
                            raw = json.load(f)
                        os.unlink(path)
                    except (OSError, ValueError):
                        continue  # partially written; retry next tick
                    uuid = raw.get("chip_uuid")
                    if uuid is None and "chip_index" in raw:
                        idx = int(raw["chip_index"])
                        if 0 <= idx < len(self._chips):
                            uuid = self._chips[idx].uuid
                    if not uuid:
                        log.warning("health-event file %s names no chip", name)
                        continue
                    self.inject_health_event(ChipHealthEvent(
                        chip_uuid=uuid,
                        healthy=bool(raw.get("healthy", False)),
                        reason=str(raw.get("reason", "injected")),
                    ))

        # Owner-thread confined: start/stop are driver lifecycle calls
        # (Driver.start/shutdown), never concurrent with each other.
        self._hm_thread = threading.Thread(  # lint: disable=R200
            target=loop, daemon=True, name="stub-health-file-poller"
        )
        self._hm_thread.start()

    def stop_health_monitor(self) -> None:
        if getattr(self, "_hm_thread", None) is None:
            return
        self._hm_stop.set()
        self._hm_thread.join(timeout=5)
        self._hm_thread = None  # lint: disable=R200 (lifecycle; see start)
