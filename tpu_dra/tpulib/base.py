"""Shared backend machinery: sub-slice lifecycle, persistence, health queue.

The sub-slice algebra is identical across backends (what differs is only how
chips are discovered), so it lives here:

- placements are validated against the host-mesh occupancy with the native
  allocator (tpu_dra.tpulib.native);
- live sub-slices are persisted one-JSON-file-per-subslice under
  ``state_dir`` — that file set is the "reliable runtime introspection
  source" that startup obliteration of unknown sub-slices reads
  (DestroyUnknownMIGDevices analog, device_state.go:337-373) and it survives
  plugin restarts the way real MIG devices survive in hardware;
- the workload-visible materialization is a rendered runtime env
  (``TPU_VISIBLE_DEVICES`` / ``TPU_CHIPS_PER_PROCESS_BOUNDS`` / host-bounds
  variables) instead of the GPU build's /dev/nvidia-caps nodes.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import uuid as uuidlib
from typing import Dict, List, Optional

from tpu_dra.infra.crashpoint import crashpoint
from tpu_dra.tpulib import native
from tpu_dra.tpulib.interface import SubsliceInfo, TpuLib, TpuLibError
from tpu_dra.tpulib.types import (
    BENIGN_HEALTH_REASONS,
    ChipHealthEvent,
    ChipInfo,
    Generation,
    Placement,
    SubsliceShape,
    TopologyCoord,
    topology_str,
)

log = logging.getLogger(__name__)


class BaseTpuLib(TpuLib):
    def __init__(self, state_dir: Optional[str] = None):
        self._state_dir = state_dir
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
        self._subslices: Dict[str, SubsliceInfo] = {}
        self._timeslice: Dict[str, int] = {}  # chip uuid -> ordinal
        self._health_q: "queue.Queue[ChipHealthEvent]" = queue.Queue()
        self._lock = threading.RLock()
        if state_dir:
            self._load_persisted_subslices()

    # --- backend hooks ---

    def generation(self) -> Generation:
        raise NotImplementedError

    # --- mesh helpers ---

    def host_mesh(self) -> "tuple[int, int, int]":
        return self.generation().host_extent

    def _chips_by_coord(self) -> Dict[TopologyCoord, ChipInfo]:
        return {c.coord: c for c in self.chips()}

    def _occupancy(self) -> List[bool]:
        """Busy flag per host-mesh coordinate from live sub-slices."""
        mx, my, mz = self.host_mesh()
        busy = [False] * (mx * my * mz)
        for ss in self._subslices.values():
            for c in ss.placement.chips():
                busy[c.x + mx * (c.y + my * c.z)] = True
        return busy

    # --- inventory ---

    def supported_shapes(self) -> List[SubsliceShape]:
        return [SubsliceShape(e) for e in self.generation().subslice_shapes]

    def possible_placements(self, shape: SubsliceShape) -> List[Placement]:
        starts = native.enumerate_placements(self.host_mesh(), shape.extent)
        return [Placement(TopologyCoord(*s), shape) for s in starts]

    # --- lifecycle ---

    def create_subslice(self, placement: Placement) -> SubsliceInfo:
        """Materialize a sub-slice (createMigDevice analog,
        nvlib.go:860-989): validate the placement against live occupancy,
        persist intent, render the workload runtime env."""
        with self._lock:
            mesh = self.host_mesh()
            try:
                free = native.placement_free(
                    mesh, placement.shape.extent,
                    (placement.start.x, placement.start.y, placement.start.z),
                    self._occupancy(),
                )
            except ValueError as e:
                raise TpuLibError(str(e)) from e
            if not free:
                raise TpuLibError(
                    f"placement {placement} overlaps an existing sub-slice"
                )
            by_coord = self._chips_by_coord()
            chips: List[ChipInfo] = []
            for coord in placement.chips():
                chip = by_coord.get(coord)
                if chip is None:
                    raise TpuLibError(
                        f"placement {placement} references coordinate {coord} "
                        f"with no chip on this host"
                    )
                if not chip.healthy:
                    raise TpuLibError(
                        f"placement {placement} includes unhealthy chip "
                        f"{chip.uuid}"
                    )
                chips.append(chip)
            ss_uuid = f"tpuss-{uuidlib.uuid4()}"
            info = SubsliceInfo(
                uuid=ss_uuid,
                parent_chip_uuids=[c.uuid for c in chips],
                placement=placement,
                generation=self.generation(),
                dev_paths=[p for c in chips for p in c.dev_paths],
                runtime_env=self._render_runtime_env(chips, placement),
            )
            self._materialize(info, chips)
            self._subslices[ss_uuid] = info
            self._persist(info)
            # The orphan window: the sub-slice is durable on "silicon"
            # but the caller never learns its uuid (the deterministic
            # analog of the stub's delay.create_subslice sleep).
            crashpoint("tpulib.subslice.after_persist")
            return info

    def delete_subslice(self, uuid: str) -> None:
        """deleteMigDevice analog (nvlib.go:990-1089); deleting an unknown
        uuid errors so orphan-GC bugs surface loudly."""
        with self._lock:
            info = self._subslices.pop(uuid, None)
            if info is None:
                raise TpuLibError(f"unknown sub-slice: {uuid}")
            self._dematerialize(info)
            self._unpersist(uuid)

    def list_subslices(self) -> List[SubsliceInfo]:
        with self._lock:
            return list(self._subslices.values())

    # --- materialization hooks (stub: no-op; linux: runtime config) ---

    def _render_runtime_env(
        self, chips: List[ChipInfo], placement: Placement
    ) -> Dict[str, str]:
        gen = self.generation()
        return {
            "TPU_VISIBLE_DEVICES": ",".join(str(c.index) for c in chips),
            "TPU_CHIPS_PER_PROCESS_BOUNDS": ",".join(
                str(d) for d in placement.shape.extent
            ),
            "TPU_PROCESS_BOUNDS": "1,1,1",
            "TPU_ACCELERATOR_TYPE": gen.accelerator_type(len(chips)),
            "TPU_SUBSLICE_SHAPE": topology_str(placement.shape.extent),
            "TPU_SUBSLICE_ORIGIN": str(placement.start),
        }

    def _materialize(self, info: SubsliceInfo, chips: List[ChipInfo]) -> None:
        pass

    def _dematerialize(self, info: SubsliceInfo) -> None:
        pass

    # --- persistence ---

    def _ss_path(self, uuid: str) -> str:
        assert self._state_dir
        return os.path.join(self._state_dir, f"{uuid}.json")

    def _persist(self, info: SubsliceInfo) -> None:
        if not self._state_dir:
            return
        d = {
            "uuid": info.uuid,
            "parentChipUUIDs": info.parent_chip_uuids,
            "shape": topology_str(info.placement.shape.extent),
            "start": str(info.placement.start),
            "generation": info.generation.name,
            "devPaths": info.dev_paths,
            "runtimeEnv": info.runtime_env,
        }
        tmp = self._ss_path(info.uuid) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(d, f)
        os.replace(tmp, self._ss_path(info.uuid))

    def _unpersist(self, uuid: str) -> None:
        if not self._state_dir:
            return
        try:
            os.remove(self._ss_path(uuid))
        except FileNotFoundError:
            pass

    def _load_persisted_subslices(self) -> None:
        from tpu_dra.tpulib.types import GENERATIONS

        assert self._state_dir
        for name in os.listdir(self._state_dir):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._state_dir, name)) as f:
                    d = json.load(f)
                info = SubsliceInfo(
                    uuid=d["uuid"],
                    parent_chip_uuids=d["parentChipUUIDs"],
                    placement=Placement(
                        TopologyCoord.parse(d["start"]),
                        SubsliceShape.parse(d["shape"]),
                    ),
                    generation=GENERATIONS[d["generation"]],
                    dev_paths=d.get("devPaths", []),
                    runtime_env=d.get("runtimeEnv", {}),
                )
                self._subslices[info.uuid] = info
            except (OSError, KeyError, ValueError) as e:
                log.warning("skipping unreadable sub-slice state %s: %s", name, e)

    # --- sharing knobs ---

    def set_time_slice(self, chip_uuids: List[str], ordinal: int) -> None:
        """Record the cooperative time-share interval per chip (the
        nvidia-smi compute-policy --set-timeslice analog, nvlib.go:772-791;
        carried to the TPU runtime via workload env)."""
        if ordinal < 0:
            raise TpuLibError(f"invalid time-slice ordinal: {ordinal}")
        known = {c.uuid for c in self.chips()}
        for u in chip_uuids:
            if u not in known:
                raise TpuLibError(f"unknown chip uuid: {u}")
        with self._lock:
            for u in chip_uuids:
                self._timeslice[u] = ordinal

    def get_time_slice(self, chip_uuid: str) -> Optional[int]:
        with self._lock:
            return self._timeslice.get(chip_uuid)

    # --- health ---

    def health_events(self) -> "queue.Queue[ChipHealthEvent]":
        return self._health_q

    def inject_health_event(self, ev: ChipHealthEvent) -> None:
        """Mark a chip (un)healthy and publish the event. On the linux
        backend this is driven by sysfs/runtime monitors; tests and the stub
        drive it directly (the XID fault-injection seam the reference lacks).

        Benign-reason unhealthy events (types.BENIGN_HEALTH_REASONS — the
        XID skip-list analog) are queued for observability but never
        mutate chip state: marking here would let a later, unrelated
        recompute unpublish a healthy chip.

        Taken under the backend lock so the health write is ordered against
        in-flight sub-slice creation (whose healthy check also holds it):
        an event racing a create lands after it and the republish path then
        unpublishes the affected devices."""
        benign = not ev.healthy and ev.reason in BENIGN_HEALTH_REASONS
        if not benign:
            with self._lock:
                for c in self.chips():
                    if c.uuid == ev.chip_uuid:
                        c.healthy = ev.healthy
        self._health_q.put(ev)

    def start_health_monitor(self, period: float = 5.0) -> None:
        """Start producing kernel/runtime-driven health events; no-op on
        backends whose events are injected (stub)."""

    def stop_health_monitor(self) -> None:
        """Stop the health producer started by start_health_monitor."""
