"""The TpuLib backend interface.

Reference analog: the responsibilities of ``deviceLib``
(cmd/gpu-kubelet-plugin/nvlib.go:41-51):

- enumerate chips and their attributes (:meth:`TpuLib.chips`;
  enumerateAllPossibleDevices nvlib.go:170-198)
- sub-slice shape/placement inventory (:meth:`TpuLib.possible_placements`;
  inspectMigProfilesAndPlacements nvlib.go:1129-1210)
- materialize / destroy sub-slices (:meth:`TpuLib.create_subslice`,
  :meth:`TpuLib.delete_subslice`; createMigDevice/deleteMigDevice
  nvlib.go:860-1089), plus listing live sub-slices for startup obliteration
  (DestroyUnknownMIGDevices, device_state.go:337-373)
- runtime sharing knobs (:meth:`TpuLib.set_time_slice`; setTimeSlice /
  setComputeMode via nvidia-smi, nvlib.go:772-815)
- health-event stream (:meth:`TpuLib.health_events`;
  nvmlDeviceHealthMonitor, device_health.go:38-66)
- ICI fabric identity (:meth:`TpuLib.ici_domain`; cliqueID discovery,
  cmd/compute-domain-kubelet-plugin/nvlib.go:188-357)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra.tpulib.types import (
    ChipInfo,
    Generation,
    IciDomain,
    Placement,
    SubsliceShape,
)


class TpuLibError(RuntimeError):
    pass


@dataclass
class SubsliceInfo:
    """A live (materialized) sub-slice (MigDeviceInfo analog,
    deviceinfo.go:61-111)."""

    uuid: str
    parent_chip_uuids: List[str]
    placement: Placement
    generation: Generation
    dev_paths: List[str] = field(default_factory=list)
    # Runtime bootstrap env the workload needs to address only this sub-slice
    # (TPU_VISIBLE_CHIPS-style variables; the /proc/nvcaps dev-node analog).
    runtime_env: Dict[str, str] = field(default_factory=dict)

    @property
    def hbm_bytes(self) -> int:
        return self.generation.hbm_bytes * self.placement.shape.chip_count

    def canonical_name(self) -> str:
        """``tpu-<parentIndexes>-ss-<shape>-<start>`` — the naming algebra
        the plugin parses back (mig.go:38-106 analog)."""
        s = self.placement.start
        return (
            f"ss-{self.placement.shape}-{s.x}-{s.y}-{s.z}"
        )


class TpuLib:
    """Abstract backend; see module docstring for the responsibility map."""

    def chips(self) -> List[ChipInfo]:
        raise NotImplementedError

    def chip_by_uuid(self, uuid: str) -> Optional[ChipInfo]:
        for c in self.chips():
            if c.uuid == uuid:
                return c
        return None

    def ici_domain(self) -> Optional[IciDomain]:
        """The pod-slice fabric identity of this host (None when the host is
        not part of a multi-host slice)."""
        raise NotImplementedError

    # --- sub-slice lifecycle (dynamic reshape) ---

    def supported_shapes(self) -> List[SubsliceShape]:
        raise NotImplementedError

    def possible_placements(self, shape: SubsliceShape) -> List[Placement]:
        raise NotImplementedError

    def create_subslice(self, placement: Placement) -> SubsliceInfo:
        raise NotImplementedError

    def delete_subslice(self, uuid: str) -> None:
        raise NotImplementedError

    def list_subslices(self) -> List[SubsliceInfo]:
        """Live sub-slices, whether or not this driver created them (feeds
        startup obliteration of unknown sub-slices)."""
        raise NotImplementedError

    # --- sharing knobs ---

    def set_time_slice(self, chip_uuids: List[str], ordinal: int) -> None:
        raise NotImplementedError

    # --- health ---

    def health_events(self) -> "queue.Queue[ChipHealthEvent]":
        raise NotImplementedError

    def start_health_monitor(self, period: float = 5.0) -> None:
        """Start producing backend-driven health events (no-op where events
        are injected externally)."""

    def stop_health_monitor(self) -> None:
        pass

    def shutdown(self) -> None:
        pass
