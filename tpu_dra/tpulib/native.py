"""ctypes binding to ``native/libtputopo.so`` with pure-Python fallback.

Reference analog: the cgo boundary into NVML
(vendor/github.com/NVIDIA/go-nvml/pkg/dl/dl.go dlopens libnvidia-ml.so.1 at
runtime; nvlib.go:56-96 resolves it under a configurable driver root). The
same shape here: dlopen at first use, resolved from TPU_DRA_NATIVE_LIB or
the in-repo build dir; when the library is absent every entry point falls
back to a Python implementation with identical semantics (parity-tested in
tests/test_tpulib.py) so stub-backend deployments never require a compiler.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
from typing import List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

NATIVE_LIB_ENV = "TPU_DRA_NATIVE_LIB"

_lib: "ctypes.CDLL | None" = None
_lib_tried = False


def _default_lib_paths() -> List[str]:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return [
        os.path.join(here, "native", "build", "libtputopo.so"),
        "/usr/local/lib/libtputopo.so",
    ]


def load_native() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    paths = []
    env = os.environ.get(NATIVE_LIB_ENV)
    if env:
        paths.append(env)
    paths.extend(_default_lib_paths())
    for p in paths:
        if not os.path.exists(p):
            continue
        try:
            lib = ctypes.CDLL(p)
            lib.tputopo_pci_scan.restype = ctypes.c_int
            lib.tputopo_pci_scan.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.tputopo_enumerate_placements.restype = ctypes.c_int
            lib.tputopo_enumerate_placements.argtypes = [
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.c_int,
            ]
            lib.tputopo_placement_free.restype = ctypes.c_int
            lib.tputopo_placement_free.argtypes = [
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_uint8),
            ]
            _lib = lib
            log.info("loaded native tputopo library: %s", p)
            return _lib
        except OSError as e:
            log.warning("failed to load %s: %s", p, e)
    log.info("native tputopo library unavailable; using Python fallback")
    return None


def native_available() -> bool:
    return load_native() is not None


Vec3 = Tuple[int, int, int]


def _c3(v: Sequence[int]):
    return (ctypes.c_int * 3)(*v)


def enumerate_placements(mesh: Vec3, shape: Vec3) -> List[Vec3]:
    """Aligned placements of ``shape`` in ``mesh``; ValueError on degenerate
    input (shape larger than mesh or non-positive dims)."""
    lib = load_native()
    if lib is not None:
        cap = 3 * (mesh[0] * mesh[1] * mesh[2] + 1)
        out = (ctypes.c_int * cap)()
        n = lib.tputopo_enumerate_placements(_c3(mesh), _c3(shape), out, cap)
        if n < 0:
            raise ValueError(
                f"invalid placement enumeration: shape {shape} in mesh {mesh}"
            )
        return [(out[i * 3], out[i * 3 + 1], out[i * 3 + 2]) for i in range(n)]
    return _py_enumerate_placements(mesh, shape)


def _py_enumerate_placements(mesh: Vec3, shape: Vec3) -> List[Vec3]:
    for d in range(3):
        if mesh[d] <= 0 or shape[d] <= 0 or shape[d] > mesh[d]:
            raise ValueError(
                f"invalid placement enumeration: shape {shape} in mesh {mesh}"
            )
    return [
        (x, y, z)
        for z in range(0, mesh[2] - shape[2] + 1, shape[2])
        for y in range(0, mesh[1] - shape[1] + 1, shape[1])
        for x in range(0, mesh[0] - shape[0] + 1, shape[0])
    ]


def placement_free(mesh: Vec3, shape: Vec3, start: Vec3, busy: Sequence[bool]) -> bool:
    """Whether the aligned placement at ``start`` is unoccupied. ``busy`` has
    one entry per mesh coordinate, indexed x + X*(y + Y*z). ValueError on an
    out-of-bounds or misaligned start."""
    lib = load_native()
    if lib is not None:
        arr = (ctypes.c_uint8 * len(busy))(*[1 if b else 0 for b in busy])
        r = lib.tputopo_placement_free(_c3(mesh), _c3(shape), _c3(start), arr)
        if r < 0:
            raise ValueError(f"invalid placement: {shape}@{start} in mesh {mesh}")
        return bool(r)
    return _py_placement_free(mesh, shape, start, busy)


def _py_placement_free(mesh: Vec3, shape: Vec3, start: Vec3, busy) -> bool:
    for d in range(3):
        if mesh[d] <= 0 or shape[d] <= 0:
            raise ValueError(f"invalid placement: {shape}@{start} in mesh {mesh}")
        if start[d] < 0 or start[d] % shape[d] != 0 or start[d] + shape[d] > mesh[d]:
            raise ValueError(f"invalid placement: {shape}@{start} in mesh {mesh}")
    for dz in range(shape[2]):
        for dy in range(shape[1]):
            for dx in range(shape[0]):
                idx = (start[0] + dx) + mesh[0] * (
                    (start[1] + dy) + mesh[1] * (start[2] + dz)
                )
                if busy[idx]:
                    return False
    return True


def pci_scan(sysfs_root: str) -> List[dict]:
    """Google-vendor PCI functions under ``<sysfs_root>/bus/pci/devices``."""
    lib = load_native()
    if lib is not None:
        cap = 1 << 20
        out = ctypes.create_string_buffer(cap)
        n = lib.tputopo_pci_scan(sysfs_root.encode(), out, cap)
        if n < 0:
            raise RuntimeError(f"pci scan failed under {sysfs_root!r}")
        return json.loads(out.value.decode())
    return _py_pci_scan(sysfs_root)


def _py_pci_scan(sysfs_root: str) -> List[dict]:
    base = os.path.join(sysfs_root, "bus", "pci", "devices")
    out = []
    if not os.path.isdir(base):
        return out

    def attr(dev: str, name: str) -> str:
        try:
            with open(os.path.join(base, dev, name)) as f:
                return f.read().strip()
        except OSError:
            return ""

    def linkbase(dev: str, name: str) -> str:
        try:
            return os.path.basename(os.readlink(os.path.join(base, dev, name)))
        except OSError:
            return ""

    for dev in sorted(os.listdir(base)):
        if attr(dev, "vendor") != "0x1ae0":
            continue
        out.append(
            {
                "address": dev,
                "device": attr(dev, "device"),
                "numa_node": attr(dev, "numa_node"),
                "driver": linkbase(dev, "driver"),
                "iommu_group": linkbase(dev, "iommu_group"),
            }
        )
    return out
