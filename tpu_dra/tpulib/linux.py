"""Linux tpulib backend: real chip enumeration.

Reference analog: deviceLib's NVML + nvpci path (nvlib.go:170-310 +
go-nvlib/nvpci sysfs walking), re-targeted at the TPU discovery surface:

- **PCI sysfs**: Google vendor (0x1ae0) functions, generation identified by
  PCI device id (native/tputopo.cc tputopo_pci_scan);
- **/dev/accel***: the TPU char devices the kernel accel subsystem exposes
  (the /dev/nvidiaN analog);
- **GKE/libtpu env conventions**: slice identity — worker id, hostnames,
  accelerator type, topology — read from the node environment or a metadata
  file (there is no NVML-style fabric query; this is how TPU VMs learn their
  ICI domain membership).

All roots are configurable (``sysfs_root``, ``dev_root``, env dict) so the
backend is testable against a fabricated filesystem tree — the analog of the
reference's configurable driver root (cmd/gpu-kubelet-plugin/root.go:29-65).
"""

from __future__ import annotations

import logging
import os
import re
import threading
from typing import Dict, List, Optional, Tuple

from tpu_dra.tpulib import native
from tpu_dra.tpulib.base import BaseTpuLib
from tpu_dra.tpulib.interface import TpuLibError
from tpu_dra.tpulib.types import (
    GENERATIONS,
    ChipHealthEvent,
    ChipInfo,
    Generation,
    IciDomain,
    TopologyCoord,
    parse_topology,
)

log = logging.getLogger(__name__)

# GKE / libtpu node environment conventions for slice membership.
ENV_WORKER_ID = "TPU_WORKER_ID"
ENV_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TOPOLOGY = "TPU_TOPOLOGY"
ENV_SLICE_UUID = "TPU_SLICE_UUID"


def detect_tpu_pci_devices(sysfs_root: str = "/sys") -> bool:
    try:
        return bool(native.pci_scan(sysfs_root))
    except Exception:
        return False


def _device_id_to_generation(device_id: str) -> Optional[Generation]:
    for gen in GENERATIONS.values():
        if device_id in gen.pci_device_ids:
            return gen
    return None


class LinuxTpuLib(BaseTpuLib):
    def __init__(
        self,
        sysfs_root: str = "/sys",
        dev_root: str = "/dev",
        env: Optional[Dict[str, str]] = None,
        state_dir: Optional[str] = None,
    ):
        self._sysfs_root = sysfs_root
        self._dev_root = dev_root
        self._env = dict(env) if env is not None else dict(os.environ)
        self._chips: List[ChipInfo] = []
        self._generation: Optional[Generation] = None
        self._ici: Optional[IciDomain] = None
        self._enumerate()
        super().__init__(state_dir=state_dir)

    # --- enumeration ---

    def _enumerate(self) -> None:
        funcs = native.pci_scan(self._sysfs_root)
        if not funcs:
            raise TpuLibError(
                f"no Google TPU PCI functions under {self._sysfs_root}"
            )
        accel_nodes = self._accel_nodes()
        self._ici = self._discover_ici_domain()
        worker_id = int(self._env.get(ENV_WORKER_ID, "0") or "0")

        chips: List[ChipInfo] = []
        for i, fn in enumerate(funcs):
            gen = _device_id_to_generation(fn["device"])
            if gen is None:
                log.warning(
                    "ignoring unknown Google PCI device %s (id %s)",
                    fn["address"],
                    fn["device"],
                )
                continue
            if self._generation is None:
                self._generation = gen
            elif self._generation is not gen:
                raise TpuLibError(
                    "mixed TPU generations on one host are unsupported"
                )
            hx, hy, _ = gen.host_extent
            try:
                numa = int(fn["numa_node"])
            except (ValueError, KeyError):
                numa = -1
            try:
                iommu = int(fn["iommu_group"])
            except (ValueError, KeyError):
                iommu = -1
            idx = len(chips)
            chips.append(
                ChipInfo(
                    index=idx,
                    uuid=f"tpu-{self._slice_uuid_prefix()}-{fn['address']}",
                    generation=gen,
                    pci_bus_id=fn["address"],
                    pcie_root=self._pcie_root(fn["address"]),
                    numa_node=numa,
                    dev_paths=[accel_nodes[idx]] if idx < len(accel_nodes) else [],
                    coord=TopologyCoord(idx % hx, (idx // hx) % hy, idx // (hx * hy)),
                    ici_domain=self._ici,
                    worker_id=worker_id,
                    iommu_group=iommu,
                    vfio_capable=bool(fn.get("iommu_group")),
                )
            )
        if not chips:
            raise TpuLibError("no recognizable TPU chips found")
        self._chips = chips

    def _accel_nodes(self) -> List[str]:
        nodes = []
        try:
            for name in sorted(os.listdir(self._dev_root)):
                if re.fullmatch(r"accel\d+", name):
                    nodes.append(os.path.join("/dev", name))
        except OSError:
            pass
        return nodes

    def _pcie_root(self, address: str) -> str:
        # Resolve the upstream root-port domain from the canonical device
        # symlink (pcieRoot attribute analog, deviceinfo.go:159-204).
        path = os.path.join(self._sysfs_root, "bus", "pci", "devices", address)
        try:
            real = os.readlink(path)
            m = re.search(r"(pci[0-9a-f]{4}:[0-9a-f]{2})", real)
            return m.group(1) if m else ""
        except OSError:
            return ""

    def _slice_uuid_prefix(self) -> str:
        ici = self._ici
        return ici.slice_uuid[:8] if ici else "local"

    def _discover_ici_domain(self) -> Optional[IciDomain]:
        """Slice identity from node env (no NVML fabric query exists).

        A host is part of a multi-host ICI domain iff the libtpu bootstrap
        variables are present. Partition derives from any DCN slice index.
        """
        hostnames = self._env.get(ENV_WORKER_HOSTNAMES, "")
        topology = self._env.get(ENV_TOPOLOGY, "")
        if not hostnames and not topology:
            return None
        slice_uuid = self._env.get(ENV_SLICE_UUID, "")
        if not slice_uuid:
            # Stable identity: hash of the member set (every host in the
            # slice computes the same value; the clique-name analog).
            import hashlib
            import uuid as uuidlib

            h = hashlib.sha256(hostnames.encode()).hexdigest()
            slice_uuid = str(uuidlib.UUID(h[:32]))
        topo = parse_topology(topology) if topology else (0, 0, 0)
        return IciDomain(slice_uuid=slice_uuid, partition=0, topology=topo)

    # --- health polling (the XID event-stream analog) ---
    #
    # TPUs expose no NVML-style event API; the observable fault surface is
    # the kernel's: the PCI function must stay present and enabled, and the
    # accel char device must not vanish. A poller watches for transitions
    # and feeds the shared health queue consumed by DeviceHealthMonitor
    # (device_health.go:146-204 analog, poll-based instead of event-based).

    def start_health_monitor(self, period: float = 5.0) -> None:
        if getattr(self, "_health_thread", None) is not None:
            return
        self._health_stop = threading.Event()
        # Owner-thread confined: start/stop are driver lifecycle calls
        # (Driver.start/shutdown), never concurrent with each other.
        self._health_thread = threading.Thread(  # lint: disable=R200
            target=self._health_poll_loop, args=(period,),
            daemon=True, name="tpulib-health-poller",
        )
        self._health_thread.start()

    def stop_health_monitor(self) -> None:
        if getattr(self, "_health_thread", None) is None:
            return
        self._health_stop.set()
        self._health_thread.join(timeout=10)
        self._health_thread = None  # lint: disable=R200 (lifecycle; see start)

    def _probe_chip(self, chip: ChipInfo) -> Tuple[bool, str]:
        pci_dir = os.path.join(
            self._sysfs_root, "bus", "pci", "devices", chip.pci_bus_id
        )
        if not os.path.isdir(pci_dir):
            return False, "pci-device-vanished"
        # A chip handed to a VM via passthrough is intentionally detached
        # from the accel driver; do not flag it (the reference likewise
        # excludes vfio devices from NVML health, they are not NVML-visible).
        try:
            bound = os.path.basename(os.readlink(os.path.join(pci_dir, "driver")))
        except OSError:
            bound = ""
        if bound == "vfio-pci":
            return True, ""
        # A chip the accel driver never bound has no device node a workload
        # could use — unhealthy until the driver claims it.
        if not chip.dev_paths:
            return False, "accel-node-missing"
        # A surprise-down/AER-contained function reads enable==0 after the
        # kernel tears it down; 0 is also the pre-driver state, so only
        # trust it for chips that do have a device node.
        try:
            with open(os.path.join(pci_dir, "enable")) as f:
                if f.read().strip() == "0":
                    return False, "pci-function-disabled"
        except OSError:
            pass
        for dev in chip.dev_paths:
            node = os.path.join(self._dev_root, os.path.basename(dev))
            if not os.path.exists(node):
                return False, "accel-node-vanished"
        return True, ""

    def _health_poll_loop(self, period: float) -> None:
        while not self._health_stop.wait(period):
            for chip in self._chips:
                try:
                    healthy, reason = self._probe_chip(chip)
                except Exception:
                    log.exception("health probe failed for %s", chip.uuid)
                    continue
                if healthy != chip.healthy:
                    self.inject_health_event(
                        ChipHealthEvent(
                            chip_uuid=chip.uuid,
                            healthy=healthy,
                            reason=reason or "recovered",
                        )
                    )

    # --- backend hooks ---

    def generation(self) -> Generation:
        assert self._generation is not None
        return self._generation

    def chips(self) -> List[ChipInfo]:
        return self._chips

    def ici_domain(self) -> Optional[IciDomain]:
        return self._ici
