"""Driver wiring: ResourceSlice publication, health, cleanup, sockets.

Reference analog: cmd/gpu-kubelet-plugin/driver.go — NewDriver (:66-173),
ResourceSlice generation split vs combined keyed on API-server version
(:188-268, :507-540), health-event handling + republish (:441-505),
Prepare/Unprepare RPC surface (:298-400).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu_dra.infra import featuregates as fg
from tpu_dra.infra.flock import Flock
from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import RESOURCE_SLICES, Informer, ResourceClient
from tpu_dra.k8sclient.circuit import bind_backend_metrics
from tpu_dra.k8sclient.degraded import DegradedModeController
from tpu_dra.plugin.allocatable import (
    AllocatableDevice,
    SUBSLICE_DYNAMIC_DEVICE_TYPE,
    dynamic_subslice_device_name,
)
from tpu_dra.plugin.cdi import CDIHandler, install_cdi_hook
from tpu_dra.plugin.checkpoint import (
    CLAIM_STATE_PREPARE_COMPLETED,
    Checkpoint,
    CheckpointManager,
    PreparedClaim,
)
from tpu_dra.plugin.prepared import (
    KubeletDevice,
    PreparedDevice,
    PreparedDeviceGroup,
    PreparedDevices,
)
from tpu_dra.plugin.cleanup import CheckpointCleanupManager
from tpu_dra.plugin.device_health import DeviceHealthMonitor
from tpu_dra.plugin.device_state import DRIVER_NAME, DeviceState
from tpu_dra.plugin.dra_service import (
    DRAService,
    RegistrationService,
    serve_unix,
)
from tpu_dra.plugin.remediation import RemediationController
from tpu_dra.plugin.sharing import MultiplexManager
from tpu_dra.plugin.slicepub import SlicePublisher, slice_content_digest
from tpu_dra.plugin.subslice import build_partitionable_model
from tpu_dra.plugin.vfio import VfioPciManager
from tpu_dra.tpulib.interface import TpuLib
from tpu_dra.tpulib.types import ChipHealthEvent

log = logging.getLogger(__name__)


def _attr_value(v) -> dict:
    if isinstance(v, bool):
        return {"bool": v}
    if isinstance(v, int):
        return {"int": v}
    return {"string": str(v)}


@dataclass
class DriverConfig:
    node_name: str = ""
    namespace: str = "tpu-dra-driver"
    cdi_root: str = "/var/run/cdi"
    plugin_data_dir: str = "/var/lib/kubelet/plugins/tpu.google.com"
    kubelet_registrar_dir: str = "/var/lib/kubelet/plugins_registry"
    # "v1beta1" publishes flat split slices; "v1beta2"/"v1" publish combined
    # partitionable slices with shared counters (driver.go:507-540 analog).
    resource_api_version: str = "v1beta1"
    multiplex_image: str = "tpu-dra-driver:latest"
    multiplex_socket_root: str = "/run/tpu-multiplex"
    start_grpc: bool = True
    # Shipped hook binary staged into plugin_data_dir at startup
    # (setNvidiaCDIHookPath analog); "" or missing file disables hooks.
    cdi_hook_source: str = "/usr/local/bin/tpu-cdi-hook"
    # Driver-root resolution (root.go:29-87 analog): host sysfs mount
    # prefix for the vfio manager's driver rebind plumbing.
    sysfs_root: str = "/sys"
    # Auto-remediation (featureGates.AutoRemediation): how long a chip
    # must stay unhealthy before leases are revoked and prepared claims
    # requeued — flaps shorter than this are suppressed.
    remediation_debounce_seconds: float = 30.0
    # Publish coalescing (ISSUE 10): health-event-driven publishes
    # arriving within this window collapse into ONE content-diffed
    # pass (publish_soon). 0 = publish synchronously per event (the
    # pre-fleet behavior; unit drills that assert immediately use it).
    publish_coalesce_seconds: float = 0.25
    # Node-scoped slice watcher (ISSUE 11, ROADMAP item 5 nibble): a
    # field-selector-scoped informer over THIS node's ResourceSlices —
    # the harness-proved <=O(node)-objects scoping wired into the real
    # plugin. External drift (admin delete, apiserver restore) heals
    # event-driven instead of waiting out the publisher's periodic
    # reverify relist. False keeps the pre-ISSUE-11 poll-only behavior.
    watch_slices: bool = True


class Driver:
    def __init__(
        self,
        tpulib: TpuLib,
        backend,
        config: DriverConfig,
    ):
        self.tpulib = tpulib
        self.backend = backend
        self.config = config
        self.metrics = Metrics()
        hook_path = install_cdi_hook(
            config.cdi_hook_source, config.plugin_data_dir
        )
        if hook_path:
            log.info("installed CDI hook at %s", hook_path)
        self.cdi = CDIHandler(cdi_root=config.cdi_root, hook_path=hook_path)
        self.checkpoints = CheckpointManager(
            config.plugin_data_dir,
            rebuild=self._rebuild_checkpoint_from_scan,
        )
        self.pu_flock = Flock(f"{config.plugin_data_dir}/pu.lock")
        multiplex = MultiplexManager(
            backend,
            namespace=config.namespace,
            node_name=config.node_name,
            image=config.multiplex_image,
            socket_root=config.multiplex_socket_root,
        )
        vfio = VfioPciManager(sysfs_root=config.sysfs_root)
        self.state = DeviceState(
            tpulib=tpulib,
            cdi=self.cdi,
            checkpoints=self.checkpoints,
            multiplex_manager=multiplex,
            vfio_manager=vfio,
            node_name=config.node_name,
            pool_name=config.node_name,
        )
        # Scrape-time gauges for per-claim sharing arbiters: revocation
        # and queue-depth counts live in the control daemons (py or
        # native), reachable only over their sockets.
        self._mux_claims_seen: set = set()
        self.metrics.register_collector(
            lambda: self._collect_multiplex_metrics(multiplex)
        )
        self.slices = ResourceClient(backend, RESOURCE_SLICES)
        # Component-wide stop event: budgets minted per kubelet RPC carry
        # it, so shutdown cancels in-flight waits instead of abandoning
        # handler threads mid-poll.
        self._stop = threading.Event()
        self.dra_service = DRAService(
            self.state, backend, self.pu_flock, metrics=self.metrics,
            stop=self._stop,
        )
        self._servers = []
        self.health_monitor = DeviceHealthMonitor(tpulib, self._on_health_change)
        # Control-plane weather: when the transport carries a circuit
        # breaker (rest.KubeClient does; the in-memory fake does not),
        # the driver runs an explicit degraded mode — background claim
        # GC and slice publication pause while any verb's circuit is
        # open, and a fenced resync runs on heal (DegradedModeController).
        self.circuit = bind_backend_metrics(backend, self.metrics)
        self.cleanup = CheckpointCleanupManager(
            self.state, backend, pu_flock=self.pu_flock,
            metrics=self.metrics, circuit=self.circuit,
        )
        # Auto-remediation rides the health-event stream; without the gate
        # the driver keeps the reference's unpublish-only behavior.
        self.remediation: Optional[RemediationController] = None
        if fg.enabled(fg.AUTO_REMEDIATION):
            self.remediation = RemediationController(
                self.state,
                backend,
                multiplex_manager=multiplex,
                publish=self.publish_with_retry,
                metrics=self.metrics,
                debounce_seconds=config.remediation_debounce_seconds,
                pu_flock=self.pu_flock,
                circuit=self.circuit,
            )
        self._publish_lock = threading.Lock()
        # Content-diffed pool-set publisher (plugin/slicepub.py): the
        # steady state (nothing changed) costs ZERO apiserver writes,
        # and the pool generation advances only when content moved.
        # Serialized by _publish_lock; its generation is the supersede
        # guard's token.
        self._publisher = SlicePublisher(
            self.slices, node_name=config.node_name, metrics=self.metrics,
        )
        # Coalesced publish trigger (publish_soon): one armed timer per
        # window; storms ride it instead of each publishing.
        self._coalesce_lock = threading.Lock()
        self._coalesce_timer: Optional[threading.Timer] = None
        # Node-scoped slice informer (ISSUE 11): field-selector keeps
        # the store at THIS node's slices (<= a handful of objects on a
        # 5k-node fleet — the PR-10 scoping, now in the real plugin),
        # and its events turn external slice drift into an immediate
        # coalesced republish (_on_slice_event) instead of a fact the
        # publisher's reverify poll discovers minutes later.
        self.slice_informer: Optional[Informer] = None
        # Drift-triggered republish cooldown: a PERSISTENT external
        # writer (split-brain: a second plugin incarnation on this
        # node, an operator script) would otherwise turn the
        # event-driven heal into a hot republish war — each side seeing
        # the other's write as drift. One heal attempt per window keeps
        # convergence fast for the one-shot cases (admin delete,
        # apiserver restore) and bounds the war to a slow drip for the
        # pathological one; the cache is still invalidated every time,
        # so any OTHER publish trigger also re-verifies.
        self._drift_republish_cooldown = 5.0
        self._last_drift_republish = -1e18
        if config.watch_slices:
            self.slice_informer = Informer(
                backend, RESOURCE_SLICES,
                field_selector={"spec.nodeName": config.node_name},
                metrics=self.metrics,
            )
            self.slice_informer.add_handler(self._on_slice_event)
            self.metrics.register_collector(
                lambda: self.metrics.set_gauge(
                    "plugin_slice_informer_objects",
                    float(self.slice_informer.store_size()),
                )
            )
        # The degraded-mode state machine (gauge, publish parking, heal
        # prober, fenced resync) is shared with the CD plugin; this
        # driver supplies the component-specific probe/resync/replay.
        # Its internal lock is distinct from _publish_lock and never
        # held across API calls: the breaker fires the listener
        # synchronously on whatever thread recorded the tripping failure
        # — including a publish thread that already holds _publish_lock
        # around its apiserver calls.
        self.degraded_ctl: Optional[DegradedModeController] = None
        if self.circuit is not None:
            node = config.node_name
            self.degraded_ctl = DegradedModeController(
                circuit=self.circuit,
                metrics=self.metrics,
                stop=self._stop,
                probe=lambda: self.slices.get(f"{node}-heal-probe"),
                resync=self._heal_reconcile,
                replay=self.publish_with_retry,
            )
        else:
            self.metrics.set_gauge("api_degraded", 0)

    def _collect_multiplex_metrics(self, multiplex) -> None:
        statuses = multiplex.poll_status()
        # Claims whose arbiter vanished (unprepared, daemon gone) must
        # drop their series, or dashboards alert forever on a dead
        # claim's last-seen contention.
        for claim_uid in self._mux_claims_seen - set(statuses):
            labels = {"claim": claim_uid}
            for name in (
                "multiplex_revocations", "multiplex_waiting",
                "multiplex_overdue", "multiplex_claim_occupancy",
                "multiplex_lease_wait_seconds_count",
                "multiplex_lease_wait_seconds_sum",
                "multiplex_lease_wait_seconds_max",
            ):
                self.metrics.remove_gauge(name, labels)
            # Bucket series carry an extra le label per edge — the
            # subset-matched removal is the only way to drop them all.
            self.metrics.remove_gauges(
                "multiplex_lease_wait_seconds_bucket", labels
            )
        self._mux_claims_seen = set(statuses)
        for claim_uid, st in statuses.items():
            labels = {"claim": claim_uid}
            self.metrics.set_gauge(
                "multiplex_revocations", st.get("revocations", 0), labels
            )
            self.metrics.set_gauge(
                "multiplex_waiting", st.get("waiting", 0), labels
            )
            self.metrics.set_gauge(
                "multiplex_overdue", 1.0 if st.get("overdue") else 0.0, labels
            )
            # Per-claim occupancy (ISSUE 12): lease-held fraction of
            # daemon uptime — the utilization signal the elastic
            # repacker's planner reads (idle claims migrate first,
            # MISO-style). Absent from older/native daemons: .get().
            if "occupancy" in st:
                self.metrics.set_gauge(
                    "multiplex_claim_occupancy", st["occupancy"], labels
                )
            # Grant-wait summary (r5, renamed for ISSUE 12 — the
            # planner's lease-wait signal): time-to-first-step
            # visibility; a late joiner starving behind a holder's
            # long compile is a dashboard alert, not a bench-tail
            # surprise.
            ws = st.get("waitSeconds") or {}
            if ws:
                self.metrics.set_gauge(
                    "multiplex_lease_wait_seconds_count",
                    ws.get("count", 0), labels,
                )
                self.metrics.set_gauge(
                    "multiplex_lease_wait_seconds_sum", ws.get("sum", 0.0),
                    labels,
                )
                self.metrics.set_gauge(
                    "multiplex_lease_wait_seconds_max", ws.get("max", 0.0),
                    labels,
                )
                for le, count in (ws.get("buckets") or {}).items():
                    self.metrics.set_gauge(
                        "multiplex_lease_wait_seconds_bucket", count,
                        {**labels, "le": le},
                    )

    def _rebuild_checkpoint_from_scan(self) -> Checkpoint:
        """Last-resort checkpoint reconstruction: both the committed file
        and its ``.bak`` are unreadable. Walk the node's other durable
        surfaces — the per-claim transient CDI specs (claim uid + granted
        device names) and the live sub-slices on silicon — and rebuild
        ``PrepareCompleted`` records from them. Request/config detail is
        gone (it only ever lived in the checkpoint), but the properties
        the checkpoint exists for survive: Prepare idempotency,
        double-allocation defense (device names), and orphan GC
        (sub-slice uuids re-attached by placement name)."""
        live_by_name = {
            dynamic_subslice_device_name(ss.placement): ss.uuid
            for ss in self.tpulib.list_subslices()
        }
        cp = Checkpoint()
        for uid in sorted(self.cdi.list_claim_uids()):
            try:
                spec = self.cdi.read_claim_spec(uid)
            except (OSError, ValueError) as e:
                # The disk incident that ate the checkpoint may have torn
                # specs too. A bad spec loses ONE claim (startup
                # obliteration sweeps its devices); raising here would
                # lose the boot — the one outcome this hook exists to
                # prevent.
                log.error(
                    "rebuild: skipping unreadable CDI spec for claim %s: %s",
                    uid, e,
                )
                continue
            if not spec:
                continue
            group = PreparedDeviceGroup()
            for dev in spec.get("devices", []):
                device_name = self.cdi.parse_claim_device_name(
                    uid, dev.get("name", "")
                )
                if device_name is None:
                    continue
                pd = PreparedDevice(
                    device=KubeletDevice(
                        pool_name=self.config.node_name,
                        device_name=device_name,
                        cdi_device_ids=[
                            self.cdi.qualified_device_id(uid, device_name)
                        ],
                    )
                )
                if device_name in live_by_name:
                    pd.type = SUBSLICE_DYNAMIC_DEVICE_TYPE
                    pd.subslice_uuid = live_by_name[device_name]
                group.devices.append(pd)
            if group.devices:
                cp.prepared_claims[uid] = PreparedClaim(
                    checkpoint_state=CLAIM_STATE_PREPARE_COMPLETED,
                    prepared_devices=PreparedDevices([group]),
                )
        log.error(
            "rebuilt checkpoint from device scan: %d claims reconstructed "
            "from CDI specs, %d live sub-slices re-attached",
            len(cp.prepared_claims), len(live_by_name),
        )
        return cp

    # --- lifecycle (RunPlugin/NewDriver analog) ---

    def start(self) -> None:
        # Boot-time WAL recovery BEFORE startup obliteration: rolling a
        # stale PrepareStarted back may itself delete the partial claim's
        # orphan sub-slices, and obliteration then sweeps anything no
        # completed claim vouches for (driver.go:103).
        rolled = self.state.recover_stale_prepares()
        if rolled:
            self.metrics.inc("boot_recovered_prepares_total", len(rolled))
            log.warning(
                "rolled back %d stale PrepareStarted claim(s) at startup",
                len(rolled),
            )
        destroyed = self.state.destroy_unknown_subslices()
        if destroyed:
            log.warning("destroyed %d unknown sub-slices at startup", len(destroyed))
        if self.config.start_grpc:
            dra_socket = f"{self.config.plugin_data_dir}/dra.sock"
            reg_socket = f"{self.config.kubelet_registrar_dir}/{DRIVER_NAME}-reg.sock"
            self.registration = RegistrationService(
                DRIVER_NAME, dra_socket, ["v1beta1"]
            )
            self._servers.append(serve_unix([self.dra_service], dra_socket))
            self._servers.append(serve_unix([self.registration], reg_socket))
            self._socket_paths = [dra_socket, reg_socket]
        if fg.enabled(fg.DEVICE_HEALTH_CHECK):
            self.health_monitor.start()
            # Backends with a kernel-surface poller (linux) start producing
            # events; the stub's hook is a no-op (its queue is test-injected).
            self.tpulib.start_health_monitor()
        self.cleanup.start()
        if self.remediation is not None:
            self.remediation.start()
        if self.slice_informer is not None:
            self.slice_informer.start()
        self.publish_resources()
        self.metrics.set_gauge("allocatable_devices", len(self.state.allocatable))

    def shutdown(self) -> None:
        self._stop.set()
        with self._coalesce_lock:
            if self._coalesce_timer is not None:
                self._coalesce_timer.cancel()
                self._coalesce_timer = None
        self.cleanup.stop()
        if self.remediation is not None:
            self.remediation.stop()
        if self.slice_informer is not None:
            self.slice_informer.stop()
        self.health_monitor.stop()
        self.tpulib.stop_health_monitor()
        for s in self._servers:
            # stop() only *initiates* shutdown; wait for full termination or
            # the executor's non-daemon workers block interpreter exit.
            s.stop(grace=1).wait(timeout=5)

    def healthy(self) -> "tuple[bool, str]":
        """Liveness verdict for /healthz (health.go:51-149 analog)."""
        from tpu_dra.infra.metrics import sockets_healthy

        return sockets_healthy(
            getattr(self, "_socket_paths", []),
            getattr(self, "registration", None),
        )

    # --- degraded mode (control-plane weather) ---

    def _heal_reconcile(self) -> None:
        """The component-specific half of the fenced heal resync
        (DegradedModeController drives it): relist claims and reconcile
        the checkpoint against the recovered apiserver — stale prepared
        claims whose ResourceClaim vanished during the partition are
        unprepared."""
        cleaned = self.cleanup.cleanup_once()
        if cleaned:
            log.warning(
                "heal resync: unprepared %d claim(s) that went stale "
                "during the outage", cleaned,
            )
        # The outage may have eaten our slices (apiserver restore, GC):
        # drop the publisher's diff cache so the replayed publish
        # re-verifies against the recovered server instead of trusting
        # pre-outage resourceVersions into a zero-write no-op.
        with self._publish_lock:
            self._publisher.invalidate()

    def _defer_publish_while_degraded(self) -> bool:
        """True when the circuit is open and the publish was queued for
        the heal resync instead (generation-supersede still applies: the
        heal publishes the LATEST state once, not every queued event)."""
        return (
            self.degraded_ctl is not None
            and self.degraded_ctl.defer_publish()
        )

    @property
    def _publish_pending_heal(self) -> bool:
        return (
            self.degraded_ctl is not None
            and self.degraded_ctl.publish_pending_heal
        )

    # --- health (driver.go:441-505) ---

    def _on_health_change(self, ev: ChipHealthEvent) -> None:
        # Chip-level health lives in tpulib (the event source already updated
        # ChipInfo.healthy); derive device health from it: a device is healthy
        # iff every chip coordinate it covers is healthy. A multi-chip
        # sub-slice therefore stays unpublished until ALL its chips recover.
        if self.state.recompute_health():
            self.metrics.inc("health_transitions_total")
            # Coalesced: a flap storm collapses into one diffed publish
            # pass per window instead of one write burst per event.
            self.publish_soon()
        # Remediation sees EVERY non-benign event, not only device-health
        # transitions: a second unhealthy reason on an already-unhealthy
        # chip must not reset or bypass the debounce bookkeeping.
        if self.remediation is not None:
            self.remediation.on_health_change(ev)

    def _on_slice_event(self, event: str, obj: dict) -> None:
        """Node-scoped slice watch (ISSUE 11): compare every event for
        a slice WE committed against the publisher's content digest.
        Our own writes echo back digest-equal (the handler serializes
        behind _publish_lock, so a mid-pass event waits for the commit
        it belongs to) and are ignored; a DELETED slice we still claim,
        or content that no longer matches, is external drift — drop the
        diff cache and ride the coalesced republish. A stale
        mid-sequence event can at worst force one spurious relist whose
        diff then writes nothing."""
        name = obj["metadata"]["name"]
        with self._publish_lock:
            known = self._publisher.committed_digest(name)
            if known is None:
                return  # not ours / cache cold (adoption relist owns it)
            if event == "DELETED":
                drift = True
            else:
                drift = slice_content_digest(obj) != known
            if not drift:
                return
            self._publisher.invalidate()
        self.metrics.inc("slice_drift_detected_total")
        now = time.monotonic()
        if now - self._last_drift_republish < self._drift_republish_cooldown:
            # See __init__: one drift-driven heal per window — a
            # persistent external writer must not drive a republish war.
            return
        self._last_drift_republish = now  # lint: disable=R200 (informer dispatch is single-threaded; worst case a racing reader publishes once more inside the window)
        log.warning(
            "slice %s drifted externally (%s); republishing", name, event
        )
        self.publish_soon()

    # --- ResourceSlice publication (driver.go:188-268) ---

    MAX_PUBLISH_RETRY_DELAY = 30.0

    @property
    def _slice_generation(self) -> int:
        """Supersede-guard token (read under _publish_lock): the
        publisher's committed pool generation. It advances only when a
        publish pass actually changed content, so a stale retry chain
        parked behind an unchanged no-op pass correctly survives."""
        return self._publisher.generation

    def publish_soon(self) -> None:
        """Coalesced publish trigger: the first call in a
        ``publish_coalesce_seconds`` window arms one timer; calls
        landing while it is armed ride it (``publish_coalesced_total``)
        — an event storm becomes one content-diffed pass. Window <= 0
        publishes synchronously (per-event, the pre-fleet behavior)."""
        window = self.config.publish_coalesce_seconds
        if window <= 0:
            self.publish_with_retry()
            return
        with self._coalesce_lock:
            if self._stop.is_set():
                return
            if self._coalesce_timer is not None:
                self.metrics.inc("publish_coalesced_total")
                return
            t = threading.Timer(window, self._coalesced_publish)
            t.daemon = True
            self._coalesce_timer = t
            t.start()

    def _coalesced_publish(self) -> None:
        with self._coalesce_lock:
            self._coalesce_timer = None
        self.publish_with_retry()

    def publish_with_retry(
        self,
        attempts: int = 5,
        delay: float = 0.5,
        _expected_generation: Optional[int] = None,
    ) -> None:
        """publish_resources, re-armed on failure. Health-driven publishes
        have no caller to propagate to (the monitor thread just logs), so
        a transient apiserver failure would otherwise leave the published
        slices contradicting chip health until the NEXT health event —
        exactly the stale-inventory window chaos drills flush out.

        Retries back off exponentially with jitter (a 429/5xx burst that
        defeats the client's own retry budget is the apiserver asking for
        LESS traffic, and synchronized fixed-delay timers from many nodes
        are exactly how it stays down). Each retry chain is tagged with
        the slice generation its failed attempt produced: when the timer
        fires after a NEWER publish already ran — a later health event,
        remediation, anything — the stale chain drops out instead of
        re-publishing and bumping the pool generation for no reason.
        """
        if _expected_generation is not None:
            with self._publish_lock:
                superseded = self._slice_generation != _expected_generation
            if superseded:
                self.metrics.inc("publish_retries_superseded_total")
                log.info(
                    "dropping stale publish retry (generation moved past %d)",
                    _expected_generation,
                )
                return
        # Degraded mode: a retry chain ticking against an OPEN circuit is
        # pure spin — park the publish for the heal resync instead. The
        # supersede guard makes the parked publish coalesce with anything
        # newer that arrives while the control plane is dark.
        if self._defer_publish_while_degraded():
            return
        try:
            self.publish_resources()
        except Exception as e:
            self.metrics.inc("publish_retries_total")
            if attempts <= 1:
                log.error("republish failed permanently: %s", e)
                return
            if self._defer_publish_while_degraded():
                return
            sleep = delay * random.uniform(0.5, 1.5)
            log.warning(
                "republish failed (%s); retrying in %.1fs", e, sleep
            )
            with self._publish_lock:
                chain_generation = self._slice_generation
            t = threading.Timer(
                sleep,
                self.publish_with_retry,
                args=(
                    attempts - 1,
                    min(delay * 2, self.MAX_PUBLISH_RETRY_DELAY),
                ),
                kwargs={"_expected_generation": chain_generation},
            )
            t.daemon = True
            t.start()

    def publish_resources(self) -> None:
        """One content-diffed publish pass (SlicePublisher): zero API
        writes when the desired pool set is unchanged, one PATCH/create
        per slice (plus deletes) when it is not."""
        with self._publish_lock:
            if self.config.resource_api_version == "v1beta1":
                build = self._generate_split_slices
            else:
                build = self._generate_combined_slices
            count = {"n": 0}

            def counted_build(generation: int):
                slices = build(generation)
                count["n"] = len(slices)
                return slices

            self._publisher.publish(counted_build)
            self.metrics.set_gauge("published_resource_slices", count["n"])

    def _device_entry(self, dev: AllocatableDevice) -> Optional[dict]:
        if not dev.healthy:
            return None  # unhealthy devices are unpublished (driver.go:441-505)
        attrs = {k: _attr_value(v) for k, v in dev.attributes().items()}
        capacity = {
            k: {"value": str(v)} for k, v in dev.capacity().items() if v
        }
        entry: dict = {"name": dev.name, "basic": {"attributes": attrs}}
        if capacity:
            entry["basic"]["capacity"] = capacity
        return entry

    def _slice_skeleton(
        self, name_suffix: str, device_entries: List[dict], generation: int
    ) -> dict:
        return {
            "apiVersion": "resource.k8s.io/v1beta1",
            "kind": "ResourceSlice",
            "metadata": {
                "name": f"{self.config.node_name}-{DRIVER_NAME}-{name_suffix}",
                "labels": {"tpu.google.com/driver": "true"},
            },
            "spec": {
                "driver": DRIVER_NAME,
                "nodeName": self.config.node_name,
                "pool": {
                    "name": self.config.node_name,
                    "generation": generation,
                    "resourceSliceCount": 1,
                },
                "devices": device_entries,
            },
        }

    def _generate_split_slices(self, generation: int) -> List[dict]:
        """Flat slices, one per device type (generateSplitResourceSlices,
        driver.go:188-225): older API servers reject counter fields."""
        by_type: Dict[str, List[dict]] = {}
        for dev in self.state.allocatable.values():
            entry = self._device_entry(dev)
            if entry is not None:
                by_type.setdefault(dev.type, []).append(entry)
        out = []
        for t, entries in sorted(by_type.items()):
            out.append(self._slice_skeleton(
                t, sorted(entries, key=lambda e: e["name"]), generation
            ))
        # The pool is only consistent when every slice declares the total
        # slice count at this generation (DRA pool semantics; the reference
        # delegates this bookkeeping to the k8s resourceslice helper).
        for s in out:
            s["spec"]["pool"]["resourceSliceCount"] = len(out)
        return out

    def _generate_combined_slices(self, generation: int) -> List[dict]:
        """One combined partitionable slice with KEP-4815 shared counters
        (generateCombinedResourceSlices, driver.go:230-268)."""
        model = build_partitionable_model(self.tpulib, self.state.allocatable)
        entries = []
        for dev in sorted(self.state.allocatable.values(), key=lambda d: d.name):
            entry = self._device_entry(dev)
            if entry is None:
                continue
            consumption = model.device_counter_consumption.get(dev.name)
            if consumption:
                entry["basic"]["consumesCounters"] = consumption
            entries.append(entry)
        s = self._slice_skeleton("combined", entries, generation)
        s["apiVersion"] = f"resource.k8s.io/{self.config.resource_api_version}"
        s["spec"]["sharedCounters"] = model.counter_sets
        s["spec"]["perDeviceNodeSelection"] = False
        return [s]
