"""Allocatable-device modeling.

Reference analog: cmd/gpu-kubelet-plugin/allocatable.go (the
``AllocatableDevice`` sum type {Gpu, MigStatic, MigDynamic, Vfio}, :39-63)
plus deviceinfo.go's announced DRA attributes (:159-204).

TPU mapping:

- ``TPU``              — a full chip (the Gpu analog)
- ``SUBSLICE_STATIC``  — a live, already-materialized sub-slice
- ``SUBSLICE_DYNAMIC`` — an abstract placement, materialized on Prepare
  (the DynamicMIG analog)
- ``VFIO``             — the same chip advertised for vfio-pci passthrough
  (sibling of its TPU device; sibling bookkeeping mirrors
  allocatable.go:238-289)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu_dra.tpulib.interface import SubsliceInfo
from tpu_dra.tpulib.types import ChipInfo, Placement

TPU_DEVICE_TYPE = "tpu"
SUBSLICE_STATIC_DEVICE_TYPE = "subslice-static"
SUBSLICE_DYNAMIC_DEVICE_TYPE = "subslice-dynamic"
VFIO_DEVICE_TYPE = "vfio"


def tpu_device_name(chip: ChipInfo) -> str:
    return f"tpu-{chip.index}"


def vfio_device_name(chip: ChipInfo) -> str:
    return f"tpu-{chip.index}-passthrough"


def dynamic_subslice_device_name(placement: Placement) -> str:
    """Canonical name algebra for abstract sub-slice devices
    (mig.go:38-106 analog): ``tpu-ss-<shape>-<x>-<y>-<z>``."""
    s = placement.start
    return f"tpu-ss-{placement.shape}-{s.x}-{s.y}-{s.z}"


def parse_dynamic_subslice_device_name(name: str) -> Placement:
    from tpu_dra.tpulib.types import SubsliceShape, TopologyCoord

    parts = name.split("-")
    if len(parts) != 6 or parts[0] != "tpu" or parts[1] != "ss":
        raise ValueError(f"not a dynamic sub-slice device name: {name!r}")
    shape = SubsliceShape.parse(parts[2])
    return Placement(
        TopologyCoord(int(parts[3]), int(parts[4]), int(parts[5])), shape
    )


def static_subslice_device_name(ss: SubsliceInfo) -> str:
    return f"tpu-live-{ss.canonical_name()}"


@dataclass
class AllocatableDevice:
    """One entry in the allocatable inventory (allocatable.go:39-45)."""

    name: str
    type: str
    chip: Optional[ChipInfo] = None  # TPU / VFIO
    subslice: Optional[SubsliceInfo] = None  # SUBSLICE_STATIC
    placement: Optional[Placement] = None  # SUBSLICE_DYNAMIC
    # SUBSLICE_DYNAMIC: the placement's parent chips, fixed at
    # enumeration — a sharing arbiter's chip set exists before the
    # sub-slice is materialized on Prepare.
    parent_chips: Optional[List[ChipInfo]] = None
    healthy: bool = True

    def is_subslice(self) -> bool:
        return self.type in (SUBSLICE_STATIC_DEVICE_TYPE, SUBSLICE_DYNAMIC_DEVICE_TYPE)

    def chip_coords(self) -> list:
        """Host-mesh coordinates this device occupies (drives the KEP-4815
        shared-counter consumption and overlap checks)."""
        if self.chip is not None:
            return [self.chip.coord]
        if self.subslice is not None:
            return self.subslice.placement.chips()
        if self.placement is not None:
            return self.placement.chips()
        return []

    def attributes(self) -> Dict[str, object]:
        """DRA device attributes (deviceinfo.go Attributes analog)."""
        attrs: Dict[str, object] = {"type": self.type}
        chip = self.chip
        if chip is not None:
            gen = chip.generation
            attrs.update(
                {
                    "uuid": chip.uuid,
                    "productName": gen.product_name,
                    "generation": gen.name,
                    "coresPerChip": gen.cores_per_chip,
                    "topologyCoord": str(chip.coord),
                    "workerID": chip.worker_id,
                    "pciBusID": chip.pci_bus_id,
                    "pcieRoot": chip.pcie_root,
                    "numaNode": chip.numa_node,
                    "driverVersion": _driver_version(),
                }
            )
            if chip.ici_domain is not None:
                attrs["iciDomainID"] = chip.ici_domain.clique_id()
        if self.subslice is not None:
            ss = self.subslice
            attrs.update(
                {
                    "uuid": ss.uuid,
                    "productName": ss.generation.product_name,
                    "generation": ss.generation.name,
                    "subsliceShape": str(ss.placement.shape),
                    "subsliceOrigin": str(ss.placement.start),
                }
            )
        if self.placement is not None:
            attrs.update(
                {
                    "subsliceShape": str(self.placement.shape),
                    "subsliceOrigin": str(self.placement.start),
                }
            )
        return attrs

    def capacity(self) -> Dict[str, int]:
        """DRA device capacity map (hbm is the memory-capacity analog)."""
        if self.chip is not None:
            return {"hbm": self.chip.hbm_bytes}
        if self.subslice is not None:
            return {"hbm": self.subslice.hbm_bytes}
        if self.placement is not None:
            return {"hbm": 0}  # filled by the caller with generation data
        return {}


def _driver_version() -> str:
    from tpu_dra.version import version_string

    return version_string()


class AllocatableDevices(dict):
    """name -> AllocatableDevice with sibling bookkeeping."""

    def uuids(self) -> List[str]:
        return [d.chip.uuid for d in self.values() if d.chip is not None]

    def tpu_uuids(self) -> List[str]:
        return [
            d.chip.uuid
            for d in self.values()
            if d.type == TPU_DEVICE_TYPE and d.chip is not None
        ]

    def arbiter_chip_uuids(self) -> List[str]:
        """Chip set a sharing arbiter (multiplex/time-slice control
        daemon) owns for these devices: full chips directly, and a
        sub-slice's parent chips — static OR dynamic (the reference runs
        MPS on both static and dynamically-created MIG devices:
        sharing.go applies per-device incl. MIG, device_state.go:653-677
        routes MigDeviceConfig+sharing through applySharingConfig;
        demo/specs/mig+mps). A dynamic placement's parent chips are fixed
        at enumeration, before materialization; while the claim holds the
        sub-slice, the overlap defenses (allocator counters + Prepare
        overlap check + tpulib occupancy) guarantee no reshape can touch
        these chips, so the arbiter's chip set is stable for the lease's
        whole life."""
        out: List[str] = []
        for d in self.values():
            if d.type == TPU_DEVICE_TYPE and d.chip is not None:
                out.append(d.chip.uuid)
            elif (
                d.type == SUBSLICE_STATIC_DEVICE_TYPE
                and d.subslice is not None
            ):
                out.extend(d.subslice.parent_chip_uuids)
            elif (
                d.type == SUBSLICE_DYNAMIC_DEVICE_TYPE
                and d.parent_chips
            ):
                out.extend(c.uuid for c in d.parent_chips)
        seen = set()
        return [u for u in out if not (u in seen or seen.add(u))]

    def arbiter_device_paths(self) -> List[str]:
        """Device nodes the arbiter's kernel gate (multiplexd DeviceGate,
        the EXCLUSIVE_PROCESS analog) chowns per lease: the chips' nodes
        plus any sub-slice nodes, deduped in discovery order. A
        sub-slice's dev nodes are exactly its parent chips' nodes
        (tpulib/base.py create_subslice), so gating the parent chips
        covers a dynamic sub-slice before it is even materialized."""
        out: List[str] = []
        for d in self.values():
            if d.type == TPU_DEVICE_TYPE and d.chip is not None:
                out.extend(d.chip.dev_paths)
            elif (
                d.type == SUBSLICE_STATIC_DEVICE_TYPE
                and d.subslice is not None
            ):
                out.extend(d.subslice.dev_paths)
            elif (
                d.type == SUBSLICE_DYNAMIC_DEVICE_TYPE
                and d.parent_chips
            ):
                out.extend(p for c in d.parent_chips for p in c.dev_paths)
        seen = set()
        return [p for p in out if not (p in seen or seen.add(p))]

    def siblings_of(self, device: "AllocatableDevice") -> List[str]:
        """Devices sharing any chip coordinate with ``device`` (the
        passthrough sibling set, allocatable.go:238-289)."""
        coords = set(device.chip_coords())
        out = []
        for name, other in self.items():
            if name == device.name:
                continue
            if coords & set(other.chip_coords()):
                out.append(name)
        return out

    def remove_sibling_devices(self, device: "AllocatableDevice") -> List[str]:
        """Drop all siblings from the inventory (done when a passthrough
        device is prepared: the chip is gone from the host's view)."""
        removed = self.siblings_of(device)
        for name in removed:
            del self[name]
        return removed
