"""Unhealthy-chip auto-remediation pipeline.

The reference driver (device_health.go + driver.go:441-505) and this port's
health monitor stop at *unpublishing*: an unhealthy chip silently leaves
the ResourceSlice while its multiplex leases, prepared claims, and
ComputeDomain membership keep dangling — one flaky chip wedges a
multi-slice JAX job until an operator intervenes. This controller closes
the loop. Driven by :class:`~tpu_dra.plugin.device_health.
DeviceHealthMonitor` events (forwarded by the driver), it:

1. **debounces**: a chip must stay unhealthy for ``debounce_seconds``
   before remediation fires — transient flaps (recovered before the window
   closes) are suppressed and counted, never acted on;
2. **revokes multiplex leases** on the failed chip through each affected
   claim's control-daemon socket (``revoke`` op — no cooldown: the client
   is a victim, not a hog);
3. **requeues prepared claims** covering the chip through a dead-lettered
   work queue: each claim is unprepared node-locally (its sub-slices torn
   down, CDI spec dropped, checkpoint entry removed) and its ResourceClaim
   is annotated ``tpu.google.com/remediation`` so the control plane — and
   operators — see *why* the claim lost its node;
4. **republishes** ResourceSlices (without the chip while down; restoring
   it on recovery — recovery needs no plugin restart).

Requeue work that keeps failing (apiserver down, wedged teardown) dead-
letters after ``max_requeue_retries`` instead of hammering the backoff cap
forever; the drop is visible as ``workqueue_dead_letter_total``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from tpu_dra.infra.workqueue import (
    WorkQueue,
    default_prep_unprep_rate_limiter,
)
from tpu_dra.k8sclient import RESOURCE_CLAIMS, ApiNotFound, ResourceClient
from tpu_dra.plugin.device_state import DeviceState
from tpu_dra.tpulib.types import ChipHealthEvent, ChipInfo

log = logging.getLogger(__name__)

# Gate registration for the G400 lint pass: any module calling into
# this subsystem must dominate the call with a check of this gate
# (driver.py does; see docs/static-analysis.md).
__feature_gate__ = "AutoRemediation"

REMEDIATION_ANNOTATION = "tpu.google.com/remediation"

DEFAULT_DEBOUNCE_SECONDS = 30.0
DEFAULT_MAX_REQUEUE_RETRIES = 5


class RemediationController:
    """Debounced unhealthy-chip remediation (see module docstring).

    The controller never runs its own poll loop: the driver forwards every
    non-benign health event to :meth:`on_health_change`, and per-chip
    debounce timers carry the delay. All mutating work (claim requeue)
    flows through one dead-lettered :class:`WorkQueue` so a poisoned claim
    cannot starve the others.
    """

    def __init__(
        self,
        state: DeviceState,
        backend,
        multiplex_manager=None,
        publish=None,
        metrics=None,
        debounce_seconds: float = DEFAULT_DEBOUNCE_SECONDS,
        max_requeue_retries: int = DEFAULT_MAX_REQUEUE_RETRIES,
        pu_flock=None,
        circuit=None,
    ):
        self.state = state
        self.claims = ResourceClient(backend, RESOURCE_CLAIMS)
        self.multiplex_manager = multiplex_manager
        self.publish = publish or (lambda: None)
        self.metrics = metrics
        self.debounce_seconds = debounce_seconds
        # Degraded mode: with the apiserver circuit open, the annotation
        # breadcrumb is skipped (not retried into the dead-letter cap —
        # local unprepare is the action that frees the silicon and needs
        # no API); the publish callback is the driver's, which defers
        # itself while degraded.
        self.circuit = circuit
        # Serialize requeue-unprepare with the RPC Prepare/Unprepare paths
        # across plugin processes, exactly like the cleanup manager.
        self.pu_flock = pu_flock
        self.queue = WorkQueue(
            default_prep_unprep_rate_limiter(),
            metrics=metrics,
            max_retries=max_requeue_retries,
        )
        self._lock = threading.Lock()
        self._pending: Dict[str, threading.Timer] = {}  # chip uuid -> timer
        # Chips we remediated and that have not recovered yet.
        self._quarantined: set = set()
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def start(self) -> None:
        self._thread = self.queue.run_in_thread()

    def stop(self) -> None:
        with self._lock:
            timers = list(self._pending.values())
            self._pending.clear()
        for t in timers:
            t.cancel()
        self.queue.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    # --- health-event intake (driver._on_health_change forwards here) ---

    def on_health_change(self, ev: ChipHealthEvent) -> None:
        if ev.healthy:
            self._on_recovered(ev)
        else:
            self._on_unhealthy(ev)

    def _on_unhealthy(self, ev: ChipHealthEvent) -> None:
        with self._lock:
            if ev.chip_uuid in self._pending or ev.chip_uuid in self._quarantined:
                return  # debounce already running / already remediated
            t = threading.Timer(
                self.debounce_seconds, self._debounce_fired, args=(ev.chip_uuid,)
            )
            t.daemon = True
            self._pending[ev.chip_uuid] = t
        log.info(
            "chip %s unhealthy (%s): remediation debounce %.1fs started",
            ev.chip_uuid, ev.reason or "no reason", self.debounce_seconds,
        )
        t.start()

    def _on_recovered(self, ev: ChipHealthEvent) -> None:
        with self._lock:
            timer = self._pending.pop(ev.chip_uuid, None)
            was_quarantined = ev.chip_uuid in self._quarantined
            self._quarantined.discard(ev.chip_uuid)
        if timer is not None:
            timer.cancel()
            self._inc("remediation_flaps_suppressed_total")
            log.info(
                "chip %s recovered inside the debounce window: flap "
                "suppressed, no remediation", ev.chip_uuid,
            )
        if was_quarantined:
            self._inc("remediation_recoveries_total")
            log.warning(
                "chip %s recovered after remediation: republishing",
                ev.chip_uuid,
            )
            # The driver's own health path republishes too; this call makes
            # recovery correct even when remediation runs stand-alone.
            self.publish()

    # --- remediation proper ---

    def _debounce_fired(self, chip_uuid: str) -> None:
        chip = self._chip(chip_uuid)
        # The healthy re-check and the quarantine add happen under ONE
        # lock acquisition: with them split, a recovery event processed in
        # between would neither cancel the (already-popped) debounce nor
        # clear the (not-yet-added) quarantine — remediating a healthy
        # chip AND muting remediation of its next real outage.
        with self._lock:
            if self._pending.pop(chip_uuid, None) is None:
                return  # recovery cancelled us while the timer raced
            if chip is None or chip.healthy:
                return  # recovered at the boundary: not sustained
            self._quarantined.add(chip_uuid)
        try:
            self.remediate(chip)
        except Exception:
            log.exception("remediation of chip %s failed", chip_uuid)

    def _chip(self, chip_uuid: str) -> Optional[ChipInfo]:
        return next(
            (c for c in self.state.tpulib.chips() if c.uuid == chip_uuid),
            None,
        )

    def remediate(self, chip: ChipInfo) -> None:
        """Act on one sustained-unhealthy chip: revoke leases, requeue the
        claims it was serving, republish without it."""
        self._inc("remediations_total")
        log.warning(
            "remediating sustained-unhealthy chip %s (index %d)",
            chip.uuid, chip.index,
        )
        if self.multiplex_manager is not None:
            revoked = self.multiplex_manager.revoke_for_chips(
                [chip.uuid], reason=f"chip {chip.uuid} unhealthy"
            )
            n = sum(1 for v in revoked.values() if v)
            if n and self.metrics is not None:
                self.metrics.inc("remediation_leases_revoked_total", n)
        for uid in self.claims_covering(chip):
            self.queue.enqueue(uid, self._requeue_claim, key=f"requeue/{uid}")
        self.publish()

    def claims_covering(self, chip: ChipInfo) -> List[str]:
        """UIDs of checkpointed prepared claims whose devices cover the
        chip — directly (chip/vfio device), through a sub-slice's parent
        chips, or by sharing the chip's topology coordinate."""
        subslice_parents = {
            ss.uuid: set(ss.parent_chip_uuids)
            for ss in self.state.tpulib.list_subslices()
        }
        out = []
        cp = self.state.checkpoints.get()
        for uid, claim in cp.prepared_claims.items():
            if self._claim_covers(claim, chip, subslice_parents):
                out.append(uid)
        return out

    def _claim_covers(self, claim, chip: ChipInfo, subslice_parents) -> bool:
        for group in claim.prepared_devices:
            for pd in group.devices:
                if pd.chip_uuid == chip.uuid:
                    return True
                if pd.subslice_uuid and chip.uuid in subslice_parents.get(
                    pd.subslice_uuid, ()
                ):
                    return True
                adev = self.state.allocatable.get(pd.device.device_name)
                if adev is not None and chip.coord in set(adev.chip_coords()):
                    return True
        return False

    def _requeue_claim(self, claim_uid: str) -> None:
        """Requeue one prepared claim off this node: annotate its
        ResourceClaim with the remediation verdict, then unprepare locally
        (WAL-checkpointed; sub-slices torn down, CDI spec dropped). The
        annotation lands FIRST so even a crash mid-unprepare leaves the
        control plane a breadcrumb; annotation failures other than
        not-found raise → the work queue retries (and dead-letters a
        poisoned claim after the cap)."""
        cp = self.state.checkpoints.get()
        claim = cp.prepared_claims.get(claim_uid)
        if claim is None:
            return  # already unprepared (kubelet or GC beat us)
        self._annotate(claim_uid, claim)
        if self.pu_flock is not None:
            release = self.pu_flock.acquire(timeout=60)
            try:
                self.state.unprepare(claim_uid)
            finally:
                release()
        else:
            self.state.unprepare(claim_uid)
        self._inc("remediation_claims_requeued_total")
        log.warning(
            "requeued claim %s/%s (%s): prepared devices covered an "
            "unhealthy chip", claim.namespace, claim.name, claim_uid,
        )

    def _annotate(self, claim_uid: str, claim) -> None:
        if not claim.name or not claim.namespace:
            return  # pre-upgrade checkpoint record: nothing to annotate
        if self.circuit is not None and self.circuit.any_open():
            # The breadcrumb is best-effort; spinning the work queue's
            # retry budget against an open circuit would dead-letter the
            # requeue and leave the unhealthy chip's claim prepared.
            self._inc("remediation_annotations_skipped_degraded_total")
            log.warning(
                "skipping remediation annotation for claim %s: apiserver "
                "circuit open (local unprepare proceeds)", claim_uid,
            )
            return
        try:
            live = self.claims.get(claim.name, claim.namespace)
        except ApiNotFound:
            return  # claim object already deleted
        if live["metadata"].get("uid") != claim_uid:
            return  # delete+recreate under the same name: not our claim
        ann = live["metadata"].setdefault("annotations", {})
        if REMEDIATION_ANNOTATION in ann:
            return  # idempotent retry
        ann[REMEDIATION_ANNOTATION] = (
            "requeued: prepared devices covered a sustained-unhealthy chip"
        )
        # A write conflict (or any transient API error) propagates: the
        # work queue retries the whole item with a fresh read.
        self.claims.update(live)
