"""Claim prepare/unprepare state machine with WAL-style checkpointing.

Reference analog: cmd/gpu-kubelet-plugin/device_state.go. The crash-
consistency design is ported whole (device_state.go:287-336 lays out the
strategy): every Prepare writes a ``PrepareStarted`` intent record first,
materializes devices, then flips to ``PrepareCompleted``; a retry that finds
a stale ``PrepareStarted`` rolls back partial sub-slice creation before
starting over (:223-228, :482-516); Prepare is idempotent on
``PrepareCompleted`` (:200-207); overlapping prepared devices are rejected
(:1118-1154); startup obliterates unknown sub-slices (:337-373).

Claims are the JSON dicts the kubelet hands over (resource.k8s.io/v1beta1
ResourceClaim).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpu_dra import api as configapi
from tpu_dra.api.errors import ApiError
from tpu_dra.infra import featuregates as fg
from tpu_dra.infra import trace
from tpu_dra.infra.crashpoint import crashpoint
from tpu_dra.plugin.allocatable import (
    AllocatableDevice,
    AllocatableDevices,
    SUBSLICE_DYNAMIC_DEVICE_TYPE,
    SUBSLICE_STATIC_DEVICE_TYPE,
    TPU_DEVICE_TYPE,
    VFIO_DEVICE_TYPE,
    static_subslice_device_name,
    tpu_device_name,
    vfio_device_name,
)
from tpu_dra.plugin.cdi import CDIHandler
from tpu_dra.plugin.checkpoint import (
    CLAIM_STATE_PREPARE_COMPLETED,
    CLAIM_STATE_PREPARE_STARTED,
    Checkpoint,
    CheckpointManager,
    PreparedClaim,
)
from tpu_dra.plugin.prepared import (
    DeviceConfigState,
    KubeletDevice,
    PreparedDevice,
    PreparedDeviceGroup,
    PreparedDevices,
)
from tpu_dra.plugin.sharing import MultiplexManager, TimeSlicingManager
from tpu_dra.plugin.subslice import enumerate_dynamic_subslice_devices
from tpu_dra.plugin.vfio import VfioPciManager
from tpu_dra.tpulib.interface import TpuLib, TpuLibError

log = logging.getLogger(__name__)

DRIVER_NAME = "tpu.google.com"


class PrepareError(RuntimeError):
    """Retryable prepare failure."""


class PermanentError(PrepareError):
    """Non-retryable failure (bad user config); the kubelet should not
    retry this claim (cd-plugin driver.go:55-59 classification analog)."""


def claim_to_string(claim: dict) -> str:
    md = claim.get("metadata", {})
    return f"{md.get('namespace')}/{md.get('name')}:{md.get('uid', '')[:8]}"


class DeviceState:
    def __init__(
        self,
        tpulib: TpuLib,
        cdi: CDIHandler,
        checkpoints: CheckpointManager,
        multiplex_manager: Optional[MultiplexManager] = None,
        vfio_manager: Optional[VfioPciManager] = None,
        node_name: str = "",
        pool_name: str = "",
    ):
        self.tpulib = tpulib
        self.cdi = cdi
        self.checkpoints = checkpoints
        self.ts_manager = TimeSlicingManager(tpulib)
        self.multiplex_manager = multiplex_manager
        self.vfio_manager = vfio_manager
        self.node_name = node_name
        self.pool_name = pool_name or node_name
        self._lock = threading.Lock()
        self.allocatable = self._enumerate_allocatable()
        warmed = self.cdi.warmup_dev_spec_cache(self._warmup_entries())
        log.debug("warmed %d CDI dev-spec cache entries", warmed)

    def _warmup_entries(self):
        """(name, dev_paths, runtime_env) for every allocatable device
        whose base CDI edits are derivable up front (WarmupDevSpecCache
        analog, cdi.go:151): full chips + static sub-slices. Dynamic
        sub-slices materialize at Prepare; vfio edits come from the vfio
        manager at Configure time."""
        for dev in self.allocatable.values():
            if dev.type == TPU_DEVICE_TYPE and dev.chip is not None:
                yield (
                    dev.name,
                    list(dev.chip.dev_paths),
                    self._chip_runtime_env([dev.chip]),
                )
            elif (
                dev.type == SUBSLICE_STATIC_DEVICE_TYPE
                and dev.subslice is not None
            ):
                yield (
                    dev.name,
                    list(dev.subslice.dev_paths),
                    dict(dev.subslice.runtime_env),
                )

    # --- inventory (enumerateAllPossibleDevices analog, nvlib.go:170-198) ---

    def _enumerate_allocatable(self) -> AllocatableDevices:
        devices = AllocatableDevices()
        for chip in self.tpulib.chips():
            dev = AllocatableDevice(
                name=tpu_device_name(chip), type=TPU_DEVICE_TYPE, chip=chip
            )
            devices[dev.name] = dev
            if fg.enabled(fg.PASSTHROUGH_SUPPORT) and chip.vfio_capable:
                vdev = AllocatableDevice(
                    name=vfio_device_name(chip), type=VFIO_DEVICE_TYPE, chip=chip
                )
                devices[vdev.name] = vdev
        if fg.enabled(fg.DYNAMIC_SUBSLICE):
            for dev in enumerate_dynamic_subslice_devices(self.tpulib):
                devices[dev.name] = dev
        else:
            for ss in self.tpulib.list_subslices():
                dev = AllocatableDevice(
                    name=static_subslice_device_name(ss),
                    type=SUBSLICE_STATIC_DEVICE_TYPE,
                    subslice=ss,
                )
                devices[dev.name] = dev
        self._apply_chip_health(devices)
        return devices

    def _apply_chip_health(self, devices: AllocatableDevices) -> None:
        """Device health derives from chip health: a device is healthy iff
        every chip coordinate it covers is healthy. Re-enumeration therefore
        never resets accumulated health state (it lives in tpulib)."""
        healthy_by_coord = {c.coord: c.healthy for c in self.tpulib.chips()}
        for dev in devices.values():
            dev.healthy = all(
                healthy_by_coord.get(coord, False) for coord in dev.chip_coords()
            )

    def recompute_health(self) -> bool:
        """Refresh device health from chip health; True when anything
        changed (drives ResourceSlice republish)."""
        before = {name: d.healthy for name, d in self.allocatable.items()}
        self._apply_chip_health(self.allocatable)
        return any(
            d.healthy != before.get(name)
            for name, d in self.allocatable.items()
        )

    # --- boot-time WAL recovery ---

    def recover_stale_prepares(self) -> List[str]:
        """Roll back claims stuck in ``PrepareStarted`` at startup.

        The reference defers this rollback to the next kubelet retry
        (device_state.go:223-228), which leaves a crashed prepare's
        partial sub-slices live until the kubelet happens to retry — or
        forever, if the pod was deleted during the outage. Rolling back
        at boot closes that window: partial device work is torn down,
        the orphaned CDI spec removed, and the WAL entry popped, so a
        retry starts from a clean slate and the GC never has to reason
        about in-flight records. Returns the rolled-back claim uids.
        """
        cp = self.checkpoints.get()
        rolled: List[str] = []
        for uid, claim in sorted(cp.prepared_claims.items()):
            if claim.checkpoint_state != CLAIM_STATE_PREPARE_STARTED:
                continue
            log.warning(
                "boot recovery: rolling back stale PrepareStarted claim "
                "%s (%s/%s)", uid, claim.namespace, claim.name,
            )
            with self._lock:
                # Spec before WAL: _unprepare_partially_prepared_claim
                # pops the WAL entry as its last step, and once that is
                # durable nothing would ever come back for the spec — a
                # crash in between must leave the entry, not the spec
                # (unprepare()'s teardown -> spec -> WAL ordering).
                self.cdi.delete_claim_spec_file(uid)
                self._unprepare_partially_prepared_claim(uid, claim)
            rolled.append(uid)
        return rolled

    # --- startup obliteration (device_state.go:337-373) ---

    def destroy_unknown_subslices(self) -> List[str]:
        """Tear down live sub-slices not referenced by any PrepareCompleted
        claim. Called once at startup before serving the kubelet."""
        if not fg.enabled(fg.DYNAMIC_SUBSLICE):
            return []
        cp = self.checkpoints.get()
        known = set()
        for claim in cp.prepared_claims.values():
            if claim.checkpoint_state != CLAIM_STATE_PREPARE_COMPLETED:
                continue
            for pd in claim.prepared_devices.of_type(SUBSLICE_DYNAMIC_DEVICE_TYPE):
                known.add(pd.subslice_uuid)
        destroyed = []
        for ss in self.tpulib.list_subslices():
            if ss.uuid in known:
                continue
            log.warning("destroying unknown sub-slice %s (%s)", ss.uuid, ss.placement)
            try:
                self.tpulib.delete_subslice(ss.uuid)
                destroyed.append(ss.uuid)
            except TpuLibError as e:
                log.error("failed to destroy unknown sub-slice %s: %s", ss.uuid, e)
        return destroyed

    # --- Prepare (device_state.go:180-285) ---

    def prepare(self, claim: dict) -> List[KubeletDevice]:
        t0 = time.monotonic()
        # Adopt the claim's trace ctx (stamped by the scheduler in the
        # allocation-commit write): this prepare becomes a child span of
        # the submit-side claim trace, so `doctor explain` can say how
        # much of the claim-ready budget the kubelet prepare ate.
        with trace.span(
            "plugin.claim.prepare",
            ctx=trace.extract(claim),
            attrs={"claim": claim_to_string(claim)},
        ):
            with self._lock:
                return self._prepare_locked(claim, t0)

    def _prepare_locked(self, claim: dict, t0: float) -> List[KubeletDevice]:
        claim_uid = claim["metadata"]["uid"]
        # Gang two-phase commit guard (ISSUE 19): a claim still carrying
        # a gang.tpu.google.com/state WAL annotation is mid-protocol —
        # its allocation may be ROLLED BACK by gang recovery, and
        # materializing sub-slices for an allocation that is about to
        # vanish would orphan silicon. Retryable: the scheduler drops
        # the annotation within one commit round trip (finalize) or
        # clears the allocation (rollback), and the kubelet retries.
        if (claim.get("metadata", {}).get("annotations") or {}).get(
            "gang.tpu.google.com/state"
        ):
            raise PrepareError(
                "claim is mid gang commit (gang.tpu.google.com/state "
                "present): refusing to prepare until the gang protocol "
                "resolves"
            )
        cp = self.checkpoints.get()
        log.debug("t_prep_get_checkpoint %.3f s", time.monotonic() - t0)

        # Idempotency: PrepareCompleted short-circuits before we would
        # overwrite it with PrepareStarted (device_state.go:196-207) —
        # UNLESS the claim's allocation moved underneath the checkpoint
        # (the elastic repacker rewrote status.allocation while the
        # claim was prepared, ISSUE 12): serving the stale sub-slice
        # would hand the container devices the allocation no longer
        # grants. The moved claim is torn down and re-prepared fresh —
        # the plugin-side "unprepare/prepare of the moved sub-slice".
        prev = cp.prepared_claims.get(claim_uid)
        if prev is not None and prev.checkpoint_state == CLAIM_STATE_PREPARE_COMPLETED:
            if self._allocated_device_set(prev.status) == \
                    self._allocated_device_set(claim.get("status", {})):
                log.info(
                    "skip prepare: claim already PrepareCompleted: %s",
                    claim_to_string(claim),
                )
                return prev.prepared_devices.get_devices()
            log.info(
                "claim %s allocation moved while prepared (repack): "
                "tearing down the old placement and re-preparing",
                claim_to_string(claim),
            )
            # Teardown first, checkpoint entry second: a crash between
            # the two leaves a PrepareCompleted record whose sub-slices
            # are gone — the kubelet retry lands back here (the
            # allocation still differs) and _unprepare_devices is
            # idempotent over already-destroyed silicon.
            self._unprepare_devices(claim_uid, prev.prepared_devices)
            self.cdi.delete_claim_spec_file(claim_uid)

            def drop_moved(c: Checkpoint) -> None:
                c.prepared_claims.pop(claim_uid, None)

            self.checkpoints.update(drop_moved)
            cp = self.checkpoints.get()
            prev = None

        # Double-allocation defense (device_state.go:211-216, :1118-1154).
        self._validate_no_overlapping_prepared_devices(cp, claim)

        # Roll back a stale partial prepare before retrying (:223-228).
        if prev is not None and prev.checkpoint_state == CLAIM_STATE_PREPARE_STARTED:
            log.info(
                "claim %s in PrepareStarted: rolling back partial prepare",
                claim_to_string(claim),
            )
            self._unprepare_partially_prepared_claim(claim_uid, prev)

        # WAL intent record (:230-243).
        def mark_started(c: Checkpoint) -> None:
            c.prepared_claims[claim_uid] = PreparedClaim(
                checkpoint_state=CLAIM_STATE_PREPARE_STARTED,
                status=claim.get("status", {}),
                name=claim["metadata"].get("name", ""),
                namespace=claim["metadata"].get("namespace", ""),
            )

        self.checkpoints.update(mark_started)
        trace.current().event("wal.prepare_started")
        crashpoint("plugin.prepare.after_wal_started")

        tp = time.monotonic()
        try:
            prepared = self._prepare_devices(claim)
        except Exception:
            # The PrepareStarted record stays; the kubelet retry path rolls
            # back whatever was partially created.
            raise
        log.debug(
            "t_prep_core %.3f s (claim %s)", time.monotonic() - tp, claim_to_string(claim)
        )

        # Passthrough: the chip leaves the host inventory; drop its siblings
        # (device_state.go:252-262).
        if fg.enabled(fg.PASSTHROUGH_SUPPORT):
            for pd in prepared.of_type(VFIO_DEVICE_TYPE):
                adev = self.allocatable.get(pd.device.device_name)
                if adev is None:
                    log.warning(
                        "allocatable not found for device: %s", pd.device.device_name
                    )
                    continue
                self.allocatable.remove_sibling_devices(adev)

        self.cdi.create_claim_spec_file(claim_uid, prepared)
        trace.current().event("cdi.spec_written")
        crashpoint("plugin.prepare.before_wal_completed")

        def mark_completed(c: Checkpoint) -> None:
            c.prepared_claims[claim_uid] = PreparedClaim(
                checkpoint_state=CLAIM_STATE_PREPARE_COMPLETED,
                status=claim.get("status", {}),
                prepared_devices=prepared,
                name=claim["metadata"].get("name", ""),
                namespace=claim["metadata"].get("namespace", ""),
            )

        self.checkpoints.update(mark_completed)
        trace.current().event("wal.prepare_completed")
        log.debug("t_prep_total %.3f s", time.monotonic() - t0)
        return prepared.get_devices()

    # --- Unprepare (device_state.go:375-441) ---

    def unprepare(self, claim_uid: str) -> None:
        with self._lock, trace.span(
            "plugin.claim.unprepare", attrs={"claim_uid": claim_uid}
        ) as s:
            cp = self.checkpoints.get()
            claim = cp.prepared_claims.get(claim_uid)
            if claim is None:
                log.info("unprepare noop: no checkpointed claim %s", claim_uid)
                s.set_status("noop")
                return
            if claim.checkpoint_state == CLAIM_STATE_PREPARE_STARTED:
                self._unprepare_partially_prepared_claim(claim_uid, claim)
            else:
                self._unprepare_devices(claim_uid, claim.prepared_devices)
            s.event("teardown.done")
            crashpoint("plugin.unprepare.after_teardown")
            self.cdi.delete_claim_spec_file(claim_uid)
            crashpoint("plugin.unprepare.before_wal_removed")
            self.checkpoints.update(
                lambda c: c.prepared_claims.pop(claim_uid, None)
            )
            s.event("wal.removed")

    def _unprepare_partially_prepared_claim(
        self, claim_uid: str, claim: PreparedClaim
    ) -> None:
        """Rollback of a partial prepare (device_state.go:482-516): any live
        sub-slice whose parent claim never completed is orphaned state."""
        if claim.prepared_devices:
            self._unprepare_devices(claim_uid, claim.prepared_devices)
            return
        # No device detail was persisted (crash mid-_prepare_devices): find
        # orphans among live sub-slices not referenced by any completed claim.
        if fg.enabled(fg.DYNAMIC_SUBSLICE):
            cp = self.checkpoints.get()
            known = set()
            for uid, c in cp.prepared_claims.items():
                if uid == claim_uid:
                    continue
                for pd in c.prepared_devices.of_type(SUBSLICE_DYNAMIC_DEVICE_TYPE):
                    known.add(pd.subslice_uuid)
            for ss in self.tpulib.list_subslices():
                if ss.uuid not in known:
                    log.info(
                        "rollback: deleting orphaned sub-slice %s for claim %s",
                        ss.uuid,
                        claim_uid,
                    )
                    self.tpulib.delete_subslice(ss.uuid)
        self.checkpoints.update(lambda c: c.prepared_claims.pop(claim_uid, None))

    def _unprepare_devices(self, claim_uid: str, devices: PreparedDevices) -> None:
        # vfio first (device_state.go:794-886 ordering): restore host driver,
        # re-advertise siblings.
        for pd in devices.of_type(VFIO_DEVICE_TYPE):
            if self.vfio_manager is not None:
                chip = self.tpulib.chip_by_uuid(pd.chip_uuid)
                if chip is not None:
                    self.vfio_manager.unconfigure(chip)
        if fg.enabled(fg.PASSTHROUGH_SUPPORT) and devices.of_type(VFIO_DEVICE_TYPE):
            self.allocatable = self._enumerate_allocatable()
        # Dynamic sub-slices torn down.
        for pd in devices.of_type(SUBSLICE_DYNAMIC_DEVICE_TYPE):
            if pd.subslice_uuid:
                try:
                    self.tpulib.delete_subslice(pd.subslice_uuid)
                except TpuLibError as e:
                    log.warning(
                        "delete sub-slice %s failed (continuing): %s",
                        pd.subslice_uuid,
                        e,
                    )
        # Sharing teardown: stop multiplex daemons, reset time-slice.
        for group in devices:
            cs = group.config_state
            if cs.multiplex_daemon_id and self.multiplex_manager is not None:
                self.multiplex_manager.daemon_by_id(cs.multiplex_daemon_id).stop()
            if cs.time_slice_ordinal is not None:
                uuids = [d.chip_uuid for d in group.devices if d.chip_uuid]
                if uuids:
                    try:
                        self.tpulib.set_time_slice(uuids, 0)
                    except TpuLibError as e:
                        log.warning("time-slice reset failed: %s", e)

    # --- overlap validation (device_state.go:1118-1154) ---

    def _validate_no_overlapping_prepared_devices(
        self, cp: Checkpoint, claim: dict
    ) -> None:
        requested = self._allocation_results(claim)
        requested_names = {r["device"] for r in requested}
        requested_coords = set()
        for name in requested_names:
            adev = self.allocatable.get(name)
            if adev is not None:
                requested_coords.update(adev.chip_coords())
        claim_uid = claim["metadata"]["uid"]
        for uid, prev in cp.prepared_claims.items():
            if uid == claim_uid:
                continue
            for pd in [d for g in prev.prepared_devices for d in g.devices]:
                if self._claim_had_admin_access(prev):
                    continue
                if pd.device.device_name in requested_names:
                    raise PrepareError(
                        f"device {pd.device.device_name} already prepared for "
                        f"claim {uid}"
                    )
                # TPU extra: coordinate-level overlap (a sub-slice and a chip
                # are distinct names but the same silicon).
                adev = self.allocatable.get(pd.device.device_name)
                if adev is not None and requested_coords & set(adev.chip_coords()):
                    raise PrepareError(
                        f"device {pd.device.device_name} (claim {uid}) overlaps "
                        f"requested chip coordinates"
                    )

    @staticmethod
    def _claim_had_admin_access(prev: PreparedClaim) -> bool:
        results = (
            prev.status.get("allocation", {}).get("devices", {}).get("results", [])
        )
        return any(r.get("adminAccess") for r in results)

    # --- device preparation core (device_state.go:595-792) ---

    @staticmethod
    def _allocation_results(claim: dict) -> List[dict]:
        alloc = claim.get("status", {}).get("allocation")
        if alloc is None:
            raise PrepareError("claim not yet allocated")
        return [
            r
            for r in alloc.get("devices", {}).get("results", [])
            if r.get("driver") == DRIVER_NAME
        ]

    @staticmethod
    def _allocated_device_set(status: dict) -> frozenset:
        """The (pool, device) set one claim status grants this driver —
        the moved-allocation probe: a prepared claim whose CURRENT set
        differs from the checkpointed one was repacked and must be
        re-prepared, not served from the stale checkpoint."""
        alloc = (status or {}).get("allocation") or {}
        return frozenset(
            (r.get("pool", ""), r.get("device", ""))
            for r in (alloc.get("devices") or {}).get("results", []) or []
            if r.get("driver") == DRIVER_NAME
        )

    def _prepare_devices(self, claim: dict) -> PreparedDevices:
        results = self._allocation_results(claim)

        configs = get_opaque_device_configs(claim)
        # Defaults at the front = lowest precedence (device_state.go:613-628).
        defaults: List[Tuple[List[str], configapi.Interface]] = [
            ([], configapi.default_tpu_subslice_config()),
            ([], configapi.default_tpu_config()),
        ]
        if fg.enabled(fg.PASSTHROUGH_SUPPORT):
            vf = configapi.default_vfio_device_config()
            if vf is not None:
                defaults.insert(0, ([], vf))
        configs = defaults + configs

        # Map each allocation result to the highest-precedence matching
        # config (device_state.go:632-677).
        config_results: Dict[int, List[dict]] = {}
        for result in results:
            device = self.allocatable.get(result["device"])
            if device is None:
                raise PrepareError(
                    f"requested device is not allocatable: {result['device']}"
                )
            if fg.enabled(fg.DEVICE_HEALTH_CHECK) and not device.healthy:
                raise PrepareError(
                    f"requested device is not healthy: {result['device']}"
                )
            matched = False
            for ci in range(len(configs) - 1, -1, -1):
                requests, cfg = configs[ci]
                explicit = result["request"] in requests
                if not explicit and requests:
                    continue
                if not self._config_matches_type(cfg, device):
                    if explicit:
                        raise PermanentError(
                            f"cannot apply {type(cfg).__name__} to device type "
                            f"{device.type} (request: {result['request']})"
                        )
                    continue
                config_results.setdefault(ci, []).append(result)
                matched = True
                break
            if not matched:
                raise PermanentError(
                    f"no config matched device {result['device']} "
                    f"(request {result['request']})"
                )

        # Normalize, validate, apply each config over its results
        # (device_state.go:683-717).
        prepared = PreparedDevices()
        for ci, cfg_results in config_results.items():
            _, cfg = configs[ci]
            try:
                cfg.normalize()
                cfg.validate()
            except ApiError as e:
                raise PermanentError(f"invalid device config: {e}") from e
            config_state = self._apply_config(cfg, claim, cfg_results)
            group = PreparedDeviceGroup(config_state=config_state)
            for result in cfg_results:
                with trace.span(
                    "plugin.device.prepare",
                    attrs={"device": result.get("device", "")},
                ):
                    group.devices.append(
                        self._prepare_one(claim, result, config_state)
                    )
                # A device (possibly a freshly-materialized sub-slice) is
                # live; its siblings and the WAL completion are not.
                crashpoint("plugin.prepare.between_devices")
            prepared.append(group)
        # Across ALL groups: devices of one request can land in different
        # config groups (a request whose selector matches both a chip and a
        # sub-slice maps them to different default configs), so request-
        # level reconciliation must see the whole claim.
        self._reconcile_request_env(prepared)
        return prepared

    # Env keys owned by the request-level merge: cleared before the merged
    # values land so no device keeps a stale per-chip value (CDI env
    # resolution is last-one-wins across all injected devices).
    _REQUEST_ENV_KEYS = (
        "TPU_VISIBLE_DEVICES",
        "TPU_ACCELERATOR_TYPE",
        "TPU_SLICE_ID",
        "TPU_WORKER_ID",
    )

    def _reconcile_request_env(self, prepared: PreparedDevices) -> None:
        """Devices granted under one request are injected into one
        container together, and CDI concatenates every injected device's
        env with last-one-wins on duplicates — diverging per-device values
        would silently hide all devices but one. Per type:

        - chips: rewrite every chip device of the request with the union
          env (all indices, request-wide accelerator type);
        - sub-slices: a sub-slice sharing a request with ANY other device
          is rejected loudly — a process runs one contiguous ICI
          process-bounds, so neither a second sub-slice nor extra chips
          can be addressed alongside it (request a larger shape);
        - vfio: merge TPU_VFIO_PCI_ADDRESS into a comma-joined list (a VMM
          can take several passthrough functions)."""
        by_request: Dict[str, List[PreparedDevice]] = {}
        for group in prepared:
            for pd in group.devices:
                for r in pd.device.requests:
                    by_request.setdefault(r, []).append(pd)
        for req, pds in by_request.items():
            if len(pds) < 2:
                continue
            n_subslice = sum(
                pd.type in (SUBSLICE_STATIC_DEVICE_TYPE, SUBSLICE_DYNAMIC_DEVICE_TYPE)
                for pd in pds
            )
            if n_subslice:
                raise PermanentError(
                    f"request {req!r} grants {len(pds)} devices including "
                    f"{n_subslice} sub-slice(s); a container can address "
                    "only one contiguous sub-slice — request a larger "
                    "sub-slice shape instead"
                )
            vfios = [pd for pd in pds if pd.type == VFIO_DEVICE_TYPE]
            if len(vfios) > 1:
                addrs = ",".join(
                    sorted(
                        pd.runtime_env.get("TPU_VFIO_PCI_ADDRESS", "")
                        for pd in vfios
                    )
                )
                for pd in vfios:
                    pd.runtime_env["TPU_VFIO_PCI_ADDRESS"] = addrs
            chip_pds = [pd for pd in pds if pd.type == TPU_DEVICE_TYPE]
            if len(chip_pds) > 1:
                chips = [
                    self.allocatable[pd.device.device_name].chip
                    for pd in chip_pds
                ]
                merged = self._chip_runtime_env(chips)
                for pd in chip_pds:
                    for k in self._REQUEST_ENV_KEYS:
                        pd.runtime_env.pop(k, None)
                    pd.runtime_env.update(merged)

    @staticmethod
    def _config_matches_type(cfg, device: AllocatableDevice) -> bool:
        if isinstance(cfg, configapi.TpuConfig):
            return device.type == TPU_DEVICE_TYPE
        if isinstance(cfg, configapi.TpuSubsliceConfig):
            return device.is_subslice()
        if isinstance(cfg, configapi.VfioDeviceConfig):
            return device.type == VFIO_DEVICE_TYPE
        return False

    def _apply_config(
        self, cfg, claim: dict, results: List[dict]
    ) -> DeviceConfigState:
        """applyConfig / applySharingConfig / applyVfioDeviceConfig
        (device_state.go:888-1006)."""
        requested = AllocatableDevices(
            {r["device"]: self.allocatable[r["device"]] for r in results}
        )
        state = DeviceConfigState()
        sharing = getattr(cfg, "sharing", None)

        if isinstance(cfg, configapi.VfioDeviceConfig):
            if self.vfio_manager is None:
                raise PrepareError("vfio manager not configured on this node")
            for dev in requested.values():
                assert dev.chip is not None
                self.vfio_manager.configure(dev.chip)
            return state

        if sharing is None:
            return state

        if fg.enabled(fg.TIME_SLICING_SETTINGS) and sharing.is_time_slicing():
            tsc = sharing.get_time_slicing_config()
            state.time_slice_ordinal = self.ts_manager.set_time_slice(
                requested, tsc
            )
            # A non-Default interval is ENFORCED through the same
            # per-claim control daemon as multiplexing, running in
            # time-slice mode: the interval ordinal sets the lease
            # quantum, and cooperating clients rotate at the quantum
            # (multiplexd.py). Without this the ordinal would be advisory
            # bookkeeping — the one wrong answer (reference execs
            # nvidia-smi: nvlib.go:772-815). Interval "Default" (ordinal
            # 0) is the reference's `--set-timeslice=default` reset: the
            # gate-on DEFAULT TpuConfig applies it to every plain claim
            # (configs.py default_tpu_config), so it must stay
            # daemon-free — an exclusive claim spawning an arbiter would
            # serialize nothing and stall Prepare on daemon readiness.
            if state.time_slice_ordinal > 0:
                if self.multiplex_manager is None:
                    raise PrepareError(
                        "time-slicing needs the multiplex manager on "
                        "this node"
                    )
                daemon = self.multiplex_manager.new_control_daemon(
                    claim["metadata"]["uid"], requested
                )
                daemon.start(
                    None, timeslice_ordinal=state.time_slice_ordinal
                )
                daemon.assert_ready()
                state.multiplex_daemon_id = daemon.get_id()
                state.container_edits = daemon.container_edits()

        if fg.enabled(fg.MULTIPLEXING_SUPPORT) and sharing.is_multiplexing():
            # Every requested device must have a chip set an arbiter can
            # own: full chips, or a sub-slice's parent chips — static
            # (live SubsliceInfo) or dynamic (placement-resolved parent
            # chips, fixed at enumeration; the arbiter starts BEFORE the
            # sub-slice is materialized in _prepare_one, which is safe
            # because a sub-slice's device nodes are exactly its parent
            # chips' nodes). MPS-on-MIG analog incl. dynamic MIG
            # (reference device_state.go:653-677, demo/specs/mig+mps).
            if self.multiplex_manager is None:
                raise PrepareError("multiplex manager not configured on this node")
            arbiter_chips = requested.arbiter_chip_uuids()
            if not arbiter_chips:
                raise PermanentError(
                    "multiplexing requires devices with an ownable chip "
                    "set; the requested devices expose none"
                )
            mpc = sharing.get_multiplexing_config()
            daemon = self.multiplex_manager.new_control_daemon(
                claim["metadata"]["uid"], requested
            )
            daemon.start(mpc)
            daemon.assert_ready()
            state.multiplex_daemon_id = daemon.get_id()
            state.container_edits = daemon.container_edits()
        return state

    def _prepare_one(
        self, claim: dict, result: dict, config_state: DeviceConfigState
    ) -> PreparedDevice:
        claim_uid = claim["metadata"]["uid"]
        adev = self.allocatable[result["device"]]
        kdev = KubeletDevice(
            requests=[result["request"]],
            pool_name=result.get("pool", self.pool_name),
            device_name=result["device"],
            cdi_device_ids=[self.cdi.qualified_device_id(claim_uid, result["device"])],
        )
        pd = PreparedDevice(type=adev.type, device=kdev)

        if adev.type == TPU_DEVICE_TYPE:
            chip = adev.chip
            assert chip is not None
            pd.chip_uuid = chip.uuid
            pd.dev_paths = list(chip.dev_paths)
            pd.runtime_env = self._chip_runtime_env([chip])
        elif adev.type == SUBSLICE_STATIC_DEVICE_TYPE:
            ss = adev.subslice
            assert ss is not None
            pd.subslice_uuid = ss.uuid
            pd.dev_paths = list(ss.dev_paths)
            pd.runtime_env = dict(ss.runtime_env)
        elif adev.type == SUBSLICE_DYNAMIC_DEVICE_TYPE:
            assert adev.placement is not None
            t0 = time.monotonic()
            try:
                ss = self.tpulib.create_subslice(adev.placement)
            except TpuLibError as e:
                raise PrepareError(f"error creating sub-slice: {e}") from e
            log.debug(
                "t_prep_create_subslice %.3f s (claim %s)",
                time.monotonic() - t0,
                claim_to_string(claim),
            )
            pd.subslice_uuid = ss.uuid
            pd.subslice_placement = str(adev.placement)
            pd.dev_paths = list(ss.dev_paths)
            pd.runtime_env = dict(ss.runtime_env)
        elif adev.type == VFIO_DEVICE_TYPE:
            chip = adev.chip
            assert chip is not None
            pd.chip_uuid = chip.uuid
            edits = (
                self.vfio_manager.container_edits(chip)
                if self.vfio_manager
                else {"devPaths": [], "env": {}}
            )
            pd.dev_paths = list(edits.get("devPaths", []))
            pd.runtime_env = dict(edits.get("env", {}))
        if config_state.time_slice_ordinal is not None:
            pd.runtime_env["TPU_TIMESLICE_ORDINAL"] = str(
                config_state.time_slice_ordinal
            )
        return pd

    def _chip_runtime_env(self, chips) -> Dict[str, str]:
        gen = chips[0].generation
        env = {
            "TPU_VISIBLE_DEVICES": ",".join(str(c.index) for c in chips),
            "TPU_ACCELERATOR_TYPE": gen.accelerator_type(len(chips)),
        }
        ici = chips[0].ici_domain
        if ici is not None:
            env["TPU_SLICE_ID"] = ici.clique_id()
            env["TPU_WORKER_ID"] = str(chips[0].worker_id)
        return env


def get_opaque_device_configs(
    claim: dict,
) -> List[Tuple[List[str], configapi.Interface]]:
    """Decode this driver's opaque configs from a claim's allocation
    (GetOpaqueDeviceConfigs analog, device_state.go:1019-1072). Returns
    (requests, config) in claim order — later entries take precedence (class
    configs come before claim configs in the allocation list, so claim
    configs win)."""
    out: List[Tuple[List[str], configapi.Interface]] = []
    alloc = claim.get("status", {}).get("allocation", {})
    for entry in alloc.get("devices", {}).get("config", []):
        opaque = entry.get("opaque")
        if not opaque or opaque.get("driver") != DRIVER_NAME:
            continue
        params = opaque.get("parameters")
        if params is None:
            raise PermanentError("opaque config contains no parameters")
        try:
            cfg = configapi.strict_decode(params)
        except ApiError as e:
            raise PermanentError(f"error decoding opaque config: {e}") from e
        out.append((entry.get("requests", []), cfg))
    return out
