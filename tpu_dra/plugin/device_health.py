"""Chip health monitoring.

Reference analog: cmd/gpu-kubelet-plugin/device_health.go — the NVML XID
event loop (:146-204) marking devices unhealthy and feeding the driver's
republish path (driver.go:441-505). The TPU source is tpulib's health-event
queue (sysfs/runtime-driven on the linux backend; injectable on the stub).

Like the reference, there is no auto-remediation: an unhealthy chip is
dropped from the published ResourceSlice until the event stream marks it
healthy again. Events whose reason is in the benign skip-list are ignored
(the XID skip-list analog, device_health.go:306-351).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Optional

from tpu_dra.tpulib.interface import TpuLib
from tpu_dra.tpulib.types import (
    BENIGN_HEALTH_REASONS,
    ChipHealthEvent,
)

# The canonical skip-list lives in tpulib (filtered at injection time so
# benign events never poison ChipInfo.healthy); aliased here for the
# monitor's own skip and for compatibility.
BENIGN_REASONS = BENIGN_HEALTH_REASONS

log = logging.getLogger(__name__)



class DeviceHealthMonitor:
    def __init__(
        self,
        tpulib: TpuLib,
        on_change: Callable[[ChipHealthEvent], None],
        poll_timeout: float = 5.0,
    ):
        self.tpulib = tpulib
        self.on_change = on_change
        self.poll_timeout = poll_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="device-health-monitor"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_timeout + 1)

    def _run(self) -> None:
        q = self.tpulib.health_events()
        while not self._stop.is_set():
            try:
                ev = q.get(timeout=self.poll_timeout)
            except queue.Empty:
                continue
            if not ev.healthy and ev.reason in BENIGN_REASONS:
                log.info(
                    "ignoring benign health event for %s: %s",
                    ev.chip_uuid,
                    ev.reason,
                )
                continue
            log.warning(
                "chip %s -> %s (%s)",
                ev.chip_uuid,
                "healthy" if ev.healthy else "UNHEALTHY",
                ev.reason or "no reason",
            )
            try:
                self.on_change(ev)
            except Exception:
                log.exception("health-change callback failed")
