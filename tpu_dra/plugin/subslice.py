"""KEP-4815 partitionable-device modeling for dynamic sub-slice reshape.

Reference analog: cmd/gpu-kubelet-plugin/partitions.go — SharedCounters per
GPU (memory + per-memory-slice counters, :45-55) consumed by each MIG
profile's abstract device (:141-212).

TPU counter model: the host mesh contributes one counter per chip
coordinate (``chip-x-y-z``: 1) into a single per-host counter set. Every
advertised device consumes the counters of the coordinates it covers:

- a full-chip device consumes its own coordinate,
- an abstract sub-slice device consumes every coordinate in its placement,
- a passthrough device consumes its chip's coordinate.

The scheduler can then never allocate overlapping devices simultaneously —
the exact double-booking defense MIG gets from memory-slice counters, but
expressed in mesh coordinates (the TPU-native constraint is contiguity in
the ICI mesh, already guaranteed by the placement enumeration).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from tpu_dra.plugin.allocatable import (
    AllocatableDevice,
    AllocatableDevices,
    SUBSLICE_DYNAMIC_DEVICE_TYPE,
    dynamic_subslice_device_name,
)
from tpu_dra.tpulib.interface import TpuLib
from tpu_dra.tpulib.types import TopologyCoord

COUNTER_SET_NAME = "tpu-host-mesh"


def counter_name(coord: TopologyCoord) -> str:
    return f"chip-{coord.x}-{coord.y}-{coord.z}"


@dataclass
class PartitionableModel:
    """SharedCounters + per-device counter consumption
    (partitions.go PartSharedCounterSets/PartGetDevice analog)."""

    counter_sets: List[dict] = field(default_factory=list)
    # device name -> list of consumed-counter entries
    device_counter_consumption: Dict[str, List[dict]] = field(default_factory=dict)


def build_partitionable_model(
    tpulib: TpuLib, allocatable: AllocatableDevices
) -> PartitionableModel:
    model = PartitionableModel()
    counters = {
        counter_name(c.coord): {"value": "1"} for c in tpulib.chips()
    }
    model.counter_sets = [{"name": COUNTER_SET_NAME, "counters": counters}]
    for name, dev in allocatable.items():
        consumed = {
            counter_name(coord): {"value": "1"} for coord in dev.chip_coords()
        }
        if consumed:
            model.device_counter_consumption[name] = [
                {"counterSet": COUNTER_SET_NAME, "counters": consumed}
            ]
    return model


def enumerate_dynamic_subslice_devices(tpulib: TpuLib) -> List[AllocatableDevice]:
    """All abstract sub-slice devices for this host
    (inspectMigProfilesAndPlacements analog, nvlib.go:1129-1210).

    Each abstract device carries its parent ChipInfos (resolved from the
    placement's coordinates): a sharing arbiter over a dynamic sub-slice
    owns exactly these chips — they are fixed by the placement BEFORE
    materialization, which is what makes multiplexing on dynamic
    sub-slices sound (the reference's MPS-on-dynamic-MIG,
    device_state.go:653-677)."""
    by_coord = {c.coord: c for c in tpulib.chips()}
    out: List[AllocatableDevice] = []
    for shape in tpulib.supported_shapes():
        # A sub-slice equal to the full host extent is just the set of all
        # chips; still advertised (the analog of the largest MIG profile).
        for placement in tpulib.possible_placements(shape):
            out.append(
                AllocatableDevice(
                    name=dynamic_subslice_device_name(placement),
                    type=SUBSLICE_DYNAMIC_DEVICE_TYPE,
                    placement=placement,
                    parent_chips=[
                        by_coord[c] for c in placement.chips()
                        if c in by_coord
                    ],
                )
            )
    return out
