"""Periodic stale-claim garbage collection.

Reference analog: cmd/gpu-kubelet-plugin/cleanup.go — every 10 minutes
(:34-36), claims recorded in the checkpoint whose ResourceClaim no longer
exists in the API server (or exists with a different UID — delete+recreate
under the same name) are unprepared (:110-189). This is the safety net for
claims the kubelet never told us to unprepare (force-deleted pods, kubelet
state loss).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from tpu_dra.infra.crashpoint import crashpoint
from tpu_dra.k8sclient import RESOURCE_CLAIMS, ApiNotFound, ResourceClient
from tpu_dra.plugin.device_state import DeviceState

log = logging.getLogger(__name__)

DEFAULT_INTERVAL = 600.0


class CheckpointCleanupManager:
    def __init__(
        self,
        state: DeviceState,
        backend,
        interval: float = DEFAULT_INTERVAL,
        pu_flock=None,
        metrics=None,
        circuit=None,
    ):
        self.state = state
        self.claims = ResourceClient(backend, RESOURCE_CLAIMS)
        self.interval = interval
        # The node-global prepare/unprepare flock: GC must serialize with
        # concurrent Prepare/Unprepare across plugin *processes* too
        # (upgrade window), exactly like the RPC paths.
        self.pu_flock = pu_flock
        self.metrics = metrics
        # Degraded mode: with the apiserver circuit open every staleness
        # probe is a guaranteed failure — the pass pauses (skips the
        # tick) instead of burning its per-claim error isolation on the
        # whole checkpoint each interval. GC work is deferrable by
        # definition; the driver's heal resync runs a pass immediately
        # after the circuit closes.
        self.circuit = circuit
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="checkpoint-cleanup"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if self.circuit is not None and self.circuit.any_open():
                if self.metrics is not None:
                    self.metrics.inc("cleanup_passes_skipped_degraded_total")
                log.info(
                    "skipping checkpoint GC pass: apiserver circuit open "
                    "(degraded mode)"
                )
                continue
            try:
                self.cleanup_once()
            except Exception:
                log.exception("checkpoint cleanup pass failed")

    def cleanup_once(self) -> int:
        """One GC pass; returns the number of unprepared stale claims.

        Failures are isolated PER CLAIM: one claim whose staleness probe
        hits a transient apiserver error (or whose unprepare fails) must
        not abort the pass for every claim behind it — the reference's
        loop has the same property (cleanup.go:110-147 logs and moves
        on), and losing it would let a single flaky GET starve the GC of
        genuinely stale claims for a full interval.
        """
        cp = self.state.checkpoints.get()
        cleaned = 0
        for uid, claim in sorted(cp.prepared_claims.items()):
            try:
                if not self._is_stale(uid, claim):
                    continue
            except Exception as e:
                log.warning(
                    "staleness probe failed for claim %s (skipping this "
                    "pass): %s", uid, e,
                )
                continue
            log.info(
                "unpreparing stale claim %s/%s (%s)",
                claim.namespace,
                claim.name,
                uid,
            )
            crashpoint("plugin.gc.before_unprepare")
            try:
                if self.pu_flock is not None:
                    # Stop-aware: the worker may sit in this acquire for
                    # up to 60s while a Prepare holds the node flock;
                    # stop() must be able to cancel the wait instead of
                    # abandoning the thread (its join times out at 2s).
                    try:
                        release = self.pu_flock.acquire(
                            timeout=60, cancel_event=self._stop
                        )
                    except InterruptedError:
                        log.info("GC pass cancelled by stop()")
                        return cleaned
                    try:
                        self.state.unprepare(uid)
                    finally:
                        release()
                else:
                    self.state.unprepare(uid)
                cleaned += 1
            except Exception as e:
                log.warning("stale-claim unprepare failed for %s: %s", uid, e)
            crashpoint("plugin.gc.between_claims")
        return cleaned

    def _is_stale(self, uid: str, claim) -> bool:
        """Stale = the API server no longer knows this (name, namespace, uid)
        (cleanup.go unprepareIfStale :149-189)."""
        if not claim.name or not claim.namespace:
            # Pre-upgrade checkpoint without name/namespace: cannot verify,
            # leave alone (reference behavior for V1-era records).
            return False
        try:
            live = self.claims.get(claim.name, claim.namespace)
        except ApiNotFound:
            return True
        return live["metadata"]["uid"] != uid
