"""gRPC DRA plugin service + kubelet registration service.

Reference analog: the gRPC plumbing kubeletplugin.Start() provides
(cmd/gpu-kubelet-plugin/driver.go:123-136): two unix sockets — the
registration socket under /var/lib/kubelet/plugins_registry and the DRA
service socket under /var/lib/kubelet/plugins/<driver>/ — plus the
Prepare/Unprepare RPC handlers (driver.go:298-332) and per-claim error
isolation (one failing claim must not fail the batch).

grpc_tools is not available in this environment, so service registration is
hand-written over protoc-generated message classes (the same method table
grpc_tools would emit).
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent import futures
from typing import List, Optional

import grpc

from tpu_dra.infra.deadline import Budget, BudgetExceeded
from tpu_dra.k8sclient import RESOURCE_CLAIMS, ApiNotFound, ResourceClient
from tpu_dra.k8sclient.circuit import CircuitOpenError
from tpu_dra.plugin.checkpoint import CLAIM_STATE_PREPARE_COMPLETED
from tpu_dra.plugin.device_state import DeviceState, PermanentError, claim_to_string
from tpu_dra.plugin.pb import dra_v1beta1_pb2 as drapb
from tpu_dra.plugin.pb import pluginregistration_pb2 as regpb

log = logging.getLogger(__name__)

DRA_SERVICE_NAME = "v1beta1.DRAPlugin"
REGISTRATION_SERVICE_NAME = "pluginregistration.Registration"

# Per-RPC deadline budget. The kubelet's DRA client calls with a 2min
# context; finishing (even retriable-failing) well inside that keeps the
# retry loop in the kubelet, where it belongs, instead of stacking
# blocked RPC handler threads here while the control plane misbehaves.
DEFAULT_RPC_BUDGET_SECONDS = 55.0


class DRAService:
    """NodePrepareResources/NodeUnprepareResources over the node's
    DeviceState, with the node-global prepare/unprepare flock taken around
    each claim (driver.go:334-400).

    Every RPC runs under a :class:`~tpu_dra.infra.deadline.Budget`
    (deadline + the driver's stop event) activated for the handler
    thread: apiserver retries, flock polls, and readiness waits nested
    anywhere below consume the budget, and expiry surfaces as a typed
    retriable per-claim error instead of a hung kubelet RPC. The PR-4
    WAL makes the kubelet's retry idempotent.
    """

    def __init__(
        self,
        state: DeviceState,
        backend,
        pu_flock,
        metrics=None,
        rpc_budget_seconds: float = DEFAULT_RPC_BUDGET_SECONDS,
        stop: Optional[threading.Event] = None,
    ):
        self.state = state
        self.claims = ResourceClient(backend, RESOURCE_CLAIMS)
        self.pu_flock = pu_flock
        self.metrics = metrics
        self.rpc_budget_seconds = rpc_budget_seconds
        self.stop = stop if stop is not None else threading.Event()

    def _budget(self, name: str) -> Budget:
        return Budget(self.rpc_budget_seconds, stop=self.stop, name=name)

    # --- RPC handlers ---

    def node_prepare_resources(
        self, request: drapb.NodePrepareResourcesRequest, context
    ) -> drapb.NodePrepareResourcesResponse:
        resp = drapb.NodePrepareResourcesResponse()
        budget = self._budget("NodePrepareResources")
        for claim_ref in request.claims:
            result = resp.claims[claim_ref.uid]
            try:
                with budget.active():
                    # Per-claim gate: a multi-claim request whose earlier
                    # claims consumed the budget (slow-but-answering
                    # apiserver — no retry sleep ever fires) must fail
                    # the REMAINING claims retriable here, not start
                    # work it cannot finish.
                    budget.check(f"starting claim {claim_ref.uid}")
                    devices = self._prepare_one(claim_ref, budget)
                for d in devices:
                    result.devices.append(
                        drapb.Device(
                            requests=d.requests,
                            pool_name=d.pool_name,
                            device_name=d.device_name,
                            cdi_device_ids=d.cdi_device_ids,
                        )
                    )
            except PermanentError as e:
                # Mark non-retryable so the kubelet surfaces it to the pod
                # instead of hot-looping (cd-plugin driver.go:55-59).
                result.error = f"permanent error: {e}"
                log.error(
                    "prepare failed permanently for claim %s: %s", claim_ref.uid, e
                )
            except BudgetExceeded as e:
                # Retriable by construction: nothing after the WAL's
                # PrepareStarted record survives un-rolled-back, so the
                # kubelet's next attempt converges.
                result.error = f"deadline: {e}"
                if self.metrics is not None:
                    self.metrics.inc("prepare_budget_exceeded_total")
                log.warning(
                    "prepare for claim %s ran out of budget (kubelet will "
                    "retry): %s", claim_ref.uid, e,
                )
            except Exception as e:
                result.error = str(e)
                log.warning("prepare failed for claim %s: %s", claim_ref.uid, e)
        return resp

    def node_unprepare_resources(
        self, request: drapb.NodeUnprepareResourcesRequest, context
    ) -> drapb.NodeUnprepareResourcesResponse:
        resp = drapb.NodeUnprepareResourcesResponse()
        budget = self._budget("NodeUnprepareResources")
        for claim_ref in request.claims:
            result = resp.claims[claim_ref.uid]
            try:
                with budget.active():
                    budget.check(f"starting claim {claim_ref.uid}")
                    release = self.pu_flock.acquire(timeout=60, budget=budget)
                    try:
                        self.state.unprepare(claim_ref.uid)
                    finally:
                        release()
                if self.metrics is not None:
                    self.metrics.inc("unprepare_total")
            except BudgetExceeded as e:
                result.error = f"deadline: {e}"
                if self.metrics is not None:
                    self.metrics.inc("unprepare_budget_exceeded_total")
                log.warning(
                    "unprepare for claim %s ran out of budget (kubelet "
                    "will retry): %s", claim_ref.uid, e,
                )
            except Exception as e:
                result.error = str(e)
                log.warning("unprepare failed for claim %s: %s", claim_ref.uid, e)
                if self.metrics is not None:
                    self.metrics.inc("unprepare_failures_total")
        return resp

    def _completed_devices(self, claim_uid: str):
        """KubeletDevices from a PrepareCompleted checkpoint record, or
        None. The WAL is the degraded-mode source of truth: a kubelet
        re-Prepare of an already-prepared claim must keep succeeding
        while the apiserver is dark."""
        claim = self.state.checkpoints.get().prepared_claims.get(claim_uid)
        if (
            claim is None
            or claim.checkpoint_state != CLAIM_STATE_PREPARE_COMPLETED
        ):
            return None
        return claim.prepared_devices.get_devices()

    def _prepare_one(self, claim_ref: drapb.Claim, budget: Budget):
        import time

        t0 = time.monotonic()
        # Fetch the full claim from the API server (the kubelet only hands
        # over references). With the circuit open or no budget left for
        # API retries, an ALREADY-COMPLETED claim still serves from the
        # checkpoint — degraded mode must not wedge a restarting pod
        # whose node state is fully materialized.
        try:
            claim = self.claims.get(claim_ref.name, claim_ref.namespace)
        except (CircuitOpenError, BudgetExceeded):
            devices = self._completed_devices(claim_ref.uid)
            if devices is not None:
                if self.metrics is not None:
                    self.metrics.inc("prepare_served_degraded_total")
                log.warning(
                    "serving prepare for claim %s from checkpoint "
                    "(apiserver unavailable)", claim_ref.uid,
                )
                return devices
            raise
        if claim["metadata"]["uid"] != claim_ref.uid:
            raise ApiNotFound(
                f"claim {claim_ref.namespace}/{claim_ref.name} UID mismatch: "
                f"have {claim['metadata']['uid']}, want {claim_ref.uid}"
            )
        release = self.pu_flock.acquire(timeout=60, budget=budget)
        log.debug("t_prep_lock_acq %.3f s", time.monotonic() - t0)
        try:
            devices = self.state.prepare(claim)
        finally:
            release()
        if self.metrics is not None:
            self.metrics.inc("prepare_total")
            self.metrics.observe("prepare_seconds", time.monotonic() - t0)
        log.info(
            "prepared claim %s: %s",
            claim_to_string(claim),
            [d.device_name for d in devices],
        )
        return devices

    # --- grpc registration (what grpc_tools would generate) ---

    def add_to_server(self, server: grpc.Server) -> None:
        handlers = {
            "NodePrepareResources": grpc.unary_unary_rpc_method_handler(
                self.node_prepare_resources,
                request_deserializer=drapb.NodePrepareResourcesRequest.FromString,
                response_serializer=(
                    drapb.NodePrepareResourcesResponse.SerializeToString
                ),
            ),
            "NodeUnprepareResources": grpc.unary_unary_rpc_method_handler(
                self.node_unprepare_resources,
                request_deserializer=drapb.NodeUnprepareResourcesRequest.FromString,
                response_serializer=(
                    drapb.NodeUnprepareResourcesResponse.SerializeToString
                ),
            ),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(DRA_SERVICE_NAME, handlers),)
        )


class RegistrationService:
    """The kubelet plugin-registration handshake."""

    def __init__(self, driver_name: str, endpoint: str, versions: List[str]):
        self.driver_name = driver_name
        self.endpoint = endpoint
        self.versions = versions
        self.registered = threading.Event()
        self.registration_error: Optional[str] = None

    def get_info(self, request: regpb.InfoRequest, context) -> regpb.PluginInfo:
        return regpb.PluginInfo(
            type="DRAPlugin",
            name=self.driver_name,
            endpoint=self.endpoint,
            supported_versions=self.versions,
        )

    def notify_registration_status(
        self, request: regpb.RegistrationStatus, context
    ) -> regpb.RegistrationStatusResponse:
        if request.plugin_registered:
            log.info("kubelet registered plugin %s", self.driver_name)
            self.registered.set()
        else:
            self.registration_error = request.error
            log.error("kubelet registration failed: %s", request.error)
        return regpb.RegistrationStatusResponse()

    def add_to_server(self, server: grpc.Server) -> None:
        handlers = {
            "GetInfo": grpc.unary_unary_rpc_method_handler(
                self.get_info,
                request_deserializer=regpb.InfoRequest.FromString,
                response_serializer=regpb.PluginInfo.SerializeToString,
            ),
            "NotifyRegistrationStatus": grpc.unary_unary_rpc_method_handler(
                self.notify_registration_status,
                request_deserializer=regpb.RegistrationStatus.FromString,
                response_serializer=(
                    regpb.RegistrationStatusResponse.SerializeToString
                ),
            ),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(REGISTRATION_SERVICE_NAME, handlers),)
        )


def serve_unix(
    services: list, socket_path: str, max_workers: int = 8
) -> grpc.Server:
    """Start a gRPC server on a unix socket; returns the running server."""
    os.makedirs(os.path.dirname(socket_path), exist_ok=True)
    try:
        os.remove(socket_path)
    except FileNotFoundError:
        pass
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    for s in services:
        s.add_to_server(server)
    server.add_insecure_port(f"unix://{socket_path}")
    server.start()
    log.info("gRPC server listening on %s", socket_path)
    return server
