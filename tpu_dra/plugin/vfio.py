"""vfio-pci passthrough manager.

Reference analog: cmd/gpu-kubelet-plugin/vfio-device.go — driver rebind via
sysfs (:230-267), IOMMU validation, per-device serialization (:49-75), CDI
edits exposing /dev/vfio nodes (:269-298).

TPU note: Cloud TPU VMs already reach chips through vfio-pci in many
configurations; this manager flips a chip between the host accel driver and
vfio-pci for handing the function to a guest VM / userspace driver. All
sysfs paths are under a configurable root for tests.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

from tpu_dra.tpulib.types import ChipInfo

log = logging.getLogger(__name__)

VFIO_PCI_DRIVER = "vfio-pci"


class VfioError(RuntimeError):
    pass


class VfioPciManager:
    def __init__(self, sysfs_root: str = "/sys", default_host_driver: str = "google-tpu"):
        self.sysfs_root = sysfs_root
        self.default_host_driver = default_host_driver
        # Per-chip serialization (mutex.go:23-41 analog).
        self._locks: Dict[str, threading.Lock] = {}
        self._locks_guard = threading.Lock()
        # Remember the original driver to restore on unconfigure.
        self._saved_driver: Dict[str, str] = {}

    def _lock_for(self, pci_address: str) -> threading.Lock:
        with self._locks_guard:
            return self._locks.setdefault(pci_address, threading.Lock())

    # --- sysfs plumbing ---

    def _dev_dir(self, pci_address: str) -> str:
        return os.path.join(self.sysfs_root, "bus", "pci", "devices", pci_address)

    def _drivers_dir(self, driver: str) -> str:
        return os.path.join(self.sysfs_root, "bus", "pci", "drivers", driver)

    def current_driver(self, pci_address: str) -> Optional[str]:
        try:
            return os.path.basename(
                os.readlink(os.path.join(self._dev_dir(pci_address), "driver"))
            )
        except OSError:
            return None

    def iommu_group(self, pci_address: str) -> Optional[str]:
        try:
            return os.path.basename(
                os.readlink(os.path.join(self._dev_dir(pci_address), "iommu_group"))
            )
        except OSError:
            return None

    def _write(self, path: str, value: str) -> None:
        with open(path, "w") as f:
            f.write(value)

    def _change_driver(self, pci_address: str, target: str) -> None:
        """Unbind from the current driver and bind to ``target`` via
        driver_override (vfio-device.go changeDriver :239-267)."""
        dev = self._dev_dir(pci_address)
        cur = self.current_driver(pci_address)
        if cur == target:
            return
        if cur is not None:
            self._write(os.path.join(dev, "driver", "unbind"), pci_address)
        self._write(os.path.join(dev, "driver_override"), target)
        probe = os.path.join(self.sysfs_root, "bus", "pci", "drivers_probe")
        bind = os.path.join(self._drivers_dir(target), "bind")
        if os.path.exists(probe):
            self._write(probe, pci_address)
        elif os.path.exists(bind):
            self._write(bind, pci_address)
        else:
            raise VfioError(
                f"no drivers_probe or {target} bind interface under "
                f"{self.sysfs_root}"
            )
        now = self.current_driver(pci_address)
        if now != target:
            raise VfioError(
                f"driver rebind failed for {pci_address}: bound to {now!r}, "
                f"wanted {target!r}"
            )

    # --- lifecycle (vfio-device.go Configure/Unconfigure :176-229) ---

    def configure(self, chip: ChipInfo) -> None:
        if not chip.vfio_capable or self.iommu_group(chip.pci_bus_id) is None:
            raise VfioError(
                f"chip {chip.uuid} ({chip.pci_bus_id}) has no IOMMU group; "
                f"cannot pass through"
            )
        with self._lock_for(chip.pci_bus_id):
            cur = self.current_driver(chip.pci_bus_id)
            if cur == VFIO_PCI_DRIVER:
                return  # idempotent
            if cur is not None:
                self._saved_driver[chip.pci_bus_id] = cur
            self._change_driver(chip.pci_bus_id, VFIO_PCI_DRIVER)
            log.info("bound %s to vfio-pci", chip.pci_bus_id)

    def unconfigure(self, chip: ChipInfo) -> None:
        with self._lock_for(chip.pci_bus_id):
            if self.current_driver(chip.pci_bus_id) != VFIO_PCI_DRIVER:
                return
            target = self._saved_driver.pop(
                chip.pci_bus_id, self.default_host_driver
            )
            self._change_driver(chip.pci_bus_id, target)
            log.info("restored %s to %s", chip.pci_bus_id, target)

    # --- CDI edits (vfio-device.go :269-298) ---

    def container_edits(self, chip: ChipInfo) -> Dict[str, object]:
        group = self.iommu_group(chip.pci_bus_id)
        dev_paths = ["/dev/vfio/vfio"]
        if group is not None:
            dev_paths.append(f"/dev/vfio/{group}")
        return {
            "devPaths": dev_paths,
            "env": {"TPU_VFIO_PCI_ADDRESS": chip.pci_bus_id},
        }
