"""tpu-multiplex-daemon: the per-claim chip-sharing control daemon.

Reference analog: the MPS control daemon the GPU plugin runs as a
dynamically-created Deployment (sharing.go:151-440 +
templates/mps-control-daemon.tmpl.yaml). CUDA MPS funnels kernels from many
processes through one server; TPUs have no kernel-level equivalent, so the
TPU-native design is **cooperative lease arbitration**: one daemon per
shared claim owns the chips and hands out exclusive, bounded leases to
client processes over a unix socket in the claim's CDI-mounted socket dir.
Clients (see :mod:`tpu_dra.workloads.multiplex_client`) acquire before
touching the chip and release after; a client that dies mid-lease is
detected by its socket closing and the lease is revoked, so a crashed
workload can never wedge its neighbors.

Protocol: one JSON object per line over ``<socket_dir>/multiplexd.sock``.

  -> {"op": "acquire", "client": "<name>"}
  <- {"ok": true, "lease": {"chips": [...], "hbmLimits": {...},
      "maxHoldSeconds": N}}          # blocks until the lease is granted
  -> {"op": "release"}
  <- {"ok": true}
  -> {"op": "status"}
  <- {"ok": true, "holder": "...", "waiting": N, "chips": [...]}

Config via env (set by the Deployment the plugin renders):
``TPU_MULTIPLEX_CHIPS`` (comma uuids), ``TPU_MULTIPLEX_SOCKET_DIR``,
``TPU_MULTIPLEX_HBM_LIMITS`` (uuid=bytes,...), and
``TPU_MULTIPLEX_COMPUTE_SHARE_PCT`` — the share percentage maps to each
lease's max-hold budget within a scheduling window, the analog of MPS
active-thread-percentage.

Time-sliced claims run the same daemon in time-slice mode:
``TPU_MULTIPLEX_TIMESLICE_ORDINAL`` (Default/Short/Medium/Long ordinal
from the claim's TimeSlicingConfig) sets the lease quantum as a fraction
of the window — the analog of ``nvidia-smi compute-policy
--set-timeslice`` — and cooperative clients rotate at the quantum via
``MultiplexClient.maybe_yield``. ``TPU_MULTIPLEX_WINDOW_SECONDS``
overrides the window (tests).

``tpu-multiplex-daemon check`` probes a running daemon's socket (the
Deployment's readiness probe).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import select
import signal
import socket
import socketserver
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

SOCKET_NAME = "multiplexd.sock"
# One scheduling window; a lease's max hold is share% of this.
SCHEDULING_WINDOW_SECONDS = 10.0

# Time-slice interval ordinal (api/sharing.py: Default/Short/Medium/Long)
# -> fraction of the scheduling window one lease may hold while others
# wait. The TPU analog of `nvidia-smi compute-policy --set-timeslice`
# (reference nvlib.go:772-815): shorter slices rotate the chip between
# cooperating processes more often; Long hands each holder the whole
# window.
TIMESLICE_WINDOW_FRACTION = {0: 0.25, 1: 0.05, 2: 0.25, 3: 1.0}


class LeaseState:
    """FIFO lease arbiter. One holder at a time; waiters queue in arrival
    order; a dropped client connection releases its lease/queue slot.

    Identity is the CONNECTION (a daemon-assigned unique id), never the
    client-supplied display name: containers in separate PID namespaces
    can collide on names like ``pid-7``, and a name key would let one
    workload release or revoke another's live lease."""

    def __init__(self, chips: List[str], hbm_limits: Dict[str, str],
                 compute_share_pct: Optional[int],
                 timeslice_ordinal: Optional[int] = None,
                 window_seconds: float = SCHEDULING_WINDOW_SECONDS):
        self.chips = chips
        self.hbm_limits = hbm_limits
        self.compute_share_pct = compute_share_pct
        self.timeslice_ordinal = timeslice_ordinal
        self.window_seconds = window_seconds
        self._lock = threading.Lock()
        self._granted = threading.Condition(self._lock)
        self._holder: Optional[str] = None
        self._hold_started: float = 0.0
        # When the current holder FIRST had competition (0.0 = uncontended).
        # A cooperative holder owes a yield within one quantum of
        # contention — not of the grant: a client alone on the chip
        # legitimately holds (and locally restarts its quantum) for hours.
        self._contended_since: float = 0.0
        self._queue: "deque[str]" = deque()
        self._names: Dict[str, str] = {}  # conn id -> display name

    def max_hold_seconds(self) -> float:
        if self.timeslice_ordinal is not None:
            frac = TIMESLICE_WINDOW_FRACTION.get(self.timeslice_ordinal, 0.25)
            return self.window_seconds * frac
        pct = self.compute_share_pct or 100
        return self.window_seconds * pct / 100.0

    def lease_body(self) -> dict:
        return {
            "chips": self.chips,
            "hbmLimits": self.hbm_limits,
            "maxHoldSeconds": self.max_hold_seconds(),
        }

    def acquire(self, conn_id: str, name: str, cancelled) -> bool:
        """Block until `conn_id` holds the lease; `cancelled()` aborts
        (client hung up while queued). Re-acquiring while already holding
        is an idempotent grant — blocking there would deadlock the whole
        queue (the holder's handler thread could never process the release
        that frees it)."""
        with self._granted:
            self._names[conn_id] = name
            if self._holder == conn_id:
                return True
            self._queue.append(conn_id)
            if self._holder is not None and not self._contended_since:
                self._contended_since = time.monotonic()
            while True:
                if cancelled():
                    self._drop_locked(conn_id)
                    return False
                if self._holder is None and self._queue[0] == conn_id:
                    self._queue.popleft()
                    self._holder = conn_id
                    now = time.monotonic()
                    self._hold_started = now
                    self._contended_since = now if self._queue else 0.0
                    return True
                self._granted.wait(timeout=0.2)

    def release(self, conn_id: str) -> bool:
        with self._granted:
            if self._holder != conn_id:
                return False
            self._holder = None
            self._granted.notify_all()
            return True

    def drop(self, conn_id: str) -> None:
        """Connection died: free whatever the client held or queued."""
        with self._granted:
            self._drop_locked(conn_id)
            self._names.pop(conn_id, None)

    def _drop_locked(self, conn_id: str) -> None:
        if self._holder == conn_id:
            self._holder = None
        try:
            self._queue.remove(conn_id)
        except ValueError:
            pass
        if not self._queue:
            self._contended_since = 0.0
        self._granted.notify_all()

    def status(self) -> dict:
        with self._lock:
            held = (
                time.monotonic() - self._hold_started if self._holder else 0.0
            )
            return {
                "holder": (
                    self._names.get(self._holder, self._holder)
                    if self._holder
                    else None
                ),
                "waiting": len(self._queue),
                "chips": self.chips,
                "heldSeconds": round(held, 3),
                "maxHoldSeconds": self.max_hold_seconds(),
                # A cooperative holder owes a yield within one quantum of
                # CONTENTION (a lone holder restarts its quantum locally
                # without telling us); overdue surfaces misbehaving
                # workloads to probes/operators.
                "overdue": bool(
                    self._holder
                    and self._queue
                    and self._contended_since
                    and (
                        time.monotonic()
                        - max(self._hold_started, self._contended_since)
                    ) > self.max_hold_seconds()
                ),
            }


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):  # noqa: A003
        state: LeaseState = self.server.lease_state  # type: ignore[attr-defined]
        # The connection IS the identity (unique per handler); the
        # client-supplied name is display-only.
        conn_id = f"conn-{id(self)}"
        touched = False
        try:
            for raw in self.rfile:
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    self._send({"ok": False, "error": "bad json"})
                    continue
                op = msg.get("op")
                if op == "acquire":
                    name = msg.get("client") or conn_id
                    touched = True
                    ok = state.acquire(conn_id, name, cancelled=self._conn_dead)
                    if not ok:
                        return
                    try:
                        self._send({"ok": True, "lease": state.lease_body()})
                    except OSError:
                        # The grant raced the client's death: hand the
                        # lease straight to the next waiter instead of
                        # waiting out this handler's teardown.
                        state.release(conn_id)
                        return
                elif op == "release":
                    self._send({"ok": state.release(conn_id)})
                elif op == "status":
                    self._send({"ok": True, **state.status()})
                elif op == "ping":
                    self._send({"ok": True})
                else:
                    self._send({"ok": False, "error": f"unknown op {op!r}"})
        finally:
            if touched:
                state.drop(conn_id)

    def _send(self, obj: dict) -> None:
        self.wfile.write(json.dumps(obj).encode() + b"\n")
        self.wfile.flush()

    # Peer shut down its write side (close/crash) — visible even while
    # unread pipelined bytes sit in our receive buffer, where an
    # MSG_PEEK-for-EOF probe would see data and judge the peer alive.
    # Linux-only bit (absent from the select module); node plugins run on
    # Linux, but keep a portable fallback for dev boxes.
    _POLLRDHUP = 0x2000 if sys.platform.startswith("linux") else 0

    def _conn_dead(self) -> bool:
        # While a client is queued, poll its socket: a hung-up peer must
        # not be granted a dead lease.
        if not self._POLLRDHUP:
            return self._conn_dead_peek()
        try:
            p = select.poll()
            p.register(
                self.connection,
                self._POLLRDHUP | select.POLLHUP | select.POLLERR,
            )
            for _, events in p.poll(0):
                if events & (
                    self._POLLRDHUP
                    | select.POLLHUP
                    | select.POLLERR
                    | select.POLLNVAL
                ):
                    return True
            return False
        except OSError:
            return True

    def _conn_dead_peek(self) -> bool:
        # Portable probe: EOF only shows once the buffer drains, so a dead
        # client with unread pipelined bytes is caught later, at grant
        # time (the _send OSError path releases immediately).
        try:
            self.connection.setblocking(False)
            try:
                return self.connection.recv(1, socket.MSG_PEEK) == b""
            except BlockingIOError:
                return False
            finally:
                self.connection.setblocking(True)
        except OSError:
            return True


class MultiplexDaemon:
    def __init__(self, socket_dir: str, chips: List[str],
                 hbm_limits: Optional[Dict[str, str]] = None,
                 compute_share_pct: Optional[int] = None,
                 timeslice_ordinal: Optional[int] = None,
                 window_seconds: float = SCHEDULING_WINDOW_SECONDS):
        os.makedirs(socket_dir, exist_ok=True)
        self.socket_dir = socket_dir
        self.socket_path = os.path.join(socket_dir, SOCKET_NAME)
        self.state = LeaseState(
            chips, hbm_limits or {}, compute_share_pct,
            timeslice_ordinal=timeslice_ordinal,
            window_seconds=window_seconds,
        )
        try:
            os.remove(self.socket_path)
        except FileNotFoundError:
            pass

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        self._server = Server(self.socket_path, _Handler)
        self._server.lease_state = self.state  # type: ignore[attr-defined]
        # Remember which filesystem entry is OURS: during pod replacement a
        # successor daemon may have re-bound the same path (shared hostPath
        # dir); its socket must survive our teardown.
        self._socket_ino = os.stat(self.socket_path).st_ino
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MultiplexDaemon":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="multiplexd"
        )
        self._thread.start()
        log.info(
            "multiplex daemon serving %d chips on %s",
            len(self.state.chips), self.socket_path,
        )
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        try:
            if os.stat(self.socket_path).st_ino == self._socket_ino:
                os.remove(self.socket_path)
        except FileNotFoundError:
            pass


def check(socket_dir: str) -> int:
    """Readiness probe: 0 iff a daemon answers a ping on the socket."""
    path = os.path.join(socket_dir, SOCKET_NAME)
    try:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(2.0)
            s.connect(path)
            s.sendall(b'{"op": "ping"}\n')
            resp = json.loads(s.makefile().readline())
            return 0 if resp.get("ok") else 1
    except (OSError, json.JSONDecodeError, ValueError):
        return 1


def parse_env(environ=os.environ) -> dict:
    limits: Dict[str, str] = {}
    raw = environ.get("TPU_MULTIPLEX_HBM_LIMITS", "")
    for part in raw.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            limits[k] = v
    pct_raw = environ.get("TPU_MULTIPLEX_COMPUTE_SHARE_PCT", "")
    ts_raw = environ.get("TPU_MULTIPLEX_TIMESLICE_ORDINAL", "")
    win_raw = environ.get("TPU_MULTIPLEX_WINDOW_SECONDS", "")
    return {
        "chips": [c for c in environ.get("TPU_MULTIPLEX_CHIPS", "").split(",") if c],
        "socket_dir": environ.get("TPU_MULTIPLEX_SOCKET_DIR", "/var/run/tpu-multiplex"),
        "hbm_limits": limits,
        "compute_share_pct": int(pct_raw) if pct_raw else None,
        "timeslice_ordinal": int(ts_raw) if ts_raw else None,
        "window_seconds": float(win_raw) if win_raw else SCHEDULING_WINDOW_SECONDS,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpu-multiplex-daemon")
    p.add_argument("command", nargs="?", default="run", choices=["run", "check"])
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    cfg = parse_env()
    if args.command == "check":
        return check(cfg["socket_dir"])
    daemon = MultiplexDaemon(
        cfg["socket_dir"], cfg["chips"], cfg["hbm_limits"],
        cfg["compute_share_pct"], cfg["timeslice_ordinal"],
        cfg["window_seconds"],
    ).start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
